"""Latency-SLO scenario with LOCAL device attachment and REALISTIC load
(VERDICT r3 #6): 16 threads over many distinct keys with the negative
cache disabled, so essentially every request misses host-side state and
crosses the device boundary through the micro-batcher.

The <=1 ms p99 target (BASELINE.md) is a local-attachment claim; the
main bench's SLO section is tunnel-RTT-bound, and the prior local run
covered only the one-hot-key shape.  This subprocess pins jax to the
in-process CPU device (RTT ~ 0 — the shape of a production host with a
local-attached accelerator) and drives the full batcher round trip per
request: submit -> size-or-deadline flush -> device step -> future.
bench.py records the output as latency_slo_local.

Run from the repo root (subprocess of bench.py).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(assert_meets: bool = False) -> int:
    import jax

    # Latency runs want prompt GIL handoff between submitters, flusher
    # and drain (default 5 ms slices add multi-ms scheduling tails).
    sys.setswitchinterval(0.001)

    # Must be pinned before any device op (see local_single_key.py).
    jax.config.update("jax_platforms", "cpu")
    import jax.extend

    jax.extend.backend.clear_backends()

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
    from ratelimiter_tpu.bench.harness import bench_threaded
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    # Cache OFF: every decision must cross the device boundary — the
    # worst-case shape for the 1 ms target (cache hits would be ~100 ns).
    sw_cfg = RateLimitConfig(max_permits=1_000_000, window_ms=60_000,
                             enable_local_cache=False)
    storage = TpuBatchedStorage(num_slots=1 << 14, max_delay_ms=0.3)
    limiter = SlidingWindowRateLimiter(storage, sw_cfg, MeterRegistry())

    # Pre-compile the dedicated small-shape step (r6: micro-batches
    # bucket at the 32-lane floor instead of padding to 256), then warm
    # every batch shape the 16-thread run can produce (the batcher
    # buckets lane counts, so a handful of sizes covers them).
    storage.warm_micro_shapes()
    for i in range(200):
        limiter.try_acquire(f"warm-{i % 64}")

    # Decomposition probes (sequential, untimed threads):
    # (a) one synchronous acquire = flush deadline + one device step,
    # (b) one direct engine dispatch+drain at a 16-lane shape = the
    #     device step alone.
    t0 = time.perf_counter()
    for i in range(50):
        limiter.try_acquire(f"probe-a-{i}")
    acquire_ms = (time.perf_counter() - t0) / 50 * 1000
    import numpy as np

    eng = storage.engine
    slots = list(range(16))
    lids = [0] * 16
    perms = [1] * 16
    h = eng.sw_acquire_dispatch(slots, lids, perms, 1_000_000)
    eng.sw_acquire_drain(h, 16)
    t0 = time.perf_counter()
    for i in range(50):
        h = eng.sw_acquire_dispatch(slots, lids, perms, 1_000_000 + i)
        eng.sw_acquire_drain(h, 16)
    step_ms = (time.perf_counter() - t0) / 50 * 1000

    # The closed-loop generator SHARES the host with the serving stack:
    # on a many-core box 16 threads is the realistic interactive load,
    # but on a 1-2 core CI container that many spinning submitters
    # saturate the core and the bench degenerates into a capacity
    # measurement (every request queues behind 15 others) instead of
    # the latency SLO it exists to check.  Scale the offered concurrency
    # to the hardware: 2x cores, clamped to [2, 16].
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):  # pragma: no cover - non-linux
        cores = os.cpu_count() or 1
    n_threads = max(2, min(16, 2 * cores))
    keys_per = 256  # n_threads*256 distinct keys; each request a new one
    res = bench_threaded(
        limiter,
        keys_per_thread=lambda t: [f"slo-u{t}-{i}" for i in range(keys_per)],
        n_threads=n_threads,
        requests_per_thread=4_000,
    )
    lat = res["request_latency"]
    res["device"] = "cpu-in-process"
    res["target_p99_ms"] = 1.0
    res["meets_target"] = bool(lat["p99_us"] < 1000.0)
    # Per-stage decomposition from the request-lifecycle histograms
    # (observability/trace.py): where each request's milliseconds went —
    # queue wait / batch assembly / device step / resolve.  ROADMAP
    # item 3's gate reads queue_wait from exactly this surface.
    stages = {}
    scrape = storage.registry.scrape()
    for name in ("queue_wait", "assembly", "device", "resolve", "total"):
        snap = scrape.get(f"ratelimiter.latency.{name}")
        if snap and snap["count"]:
            stages[name] = {
                "p50_ms": round(snap["p50_us"] / 1000.0, 3),
                "p99_ms": round(snap["p99_us"] / 1000.0, 3),
                "mean_ms": round(snap["mean_us"] / 1000.0, 3),
                "count": int(snap["count"]),
            }
    print("per-stage decomposition (p50 / p99 ms):", file=sys.stderr)
    for name, row in stages.items():
        print(f"  {name:<10} {row['p50_ms']:>8.3f} {row['p99_ms']:>8.3f}",
              file=sys.stderr)
    res["decomposition"] = {
        "stages": stages,
        "batcher_max_delay_ms": 0.3,
        "single_acquire_ms": round(acquire_ms, 3),
        "device_step_16_lanes_ms": round(step_ms, 3),
        "note": ("multi-key, cache-off: every request rides a device "
                 "micro-batch; p99 ~= flush deadline + one device step + "
                 "queue depth under 16-thread load.  The step time here "
                 "is the CPU backend's dispatch+execute+fetch for a "
                 "16-lane micro-batch — the floor the 1 ms target is "
                 "judged against in this environment; a local-attached "
                 "TPU swaps it for its own dispatch + ~10-30 us PCIe "
                 "round trip."),
    }
    storage.close()
    print(json.dumps(res))
    if assert_meets:
        # CI gate (verify.sh): the 1 ms p99 target must hold on CPU, and
        # the decomposition must show assembly is no longer the dominant
        # stage (the r11 double-buffer/staged-dispatch claim).
        if not res["meets_target"]:
            print(f"FAIL: p99 {lat['p99_us']:.0f} us > 1000 us target",
                  file=sys.stderr)
            return 1
        # "No longer dominant": pre-r11 assembly sat at 0.88-1.02 ms
        # p50, ~3x every other stage.  Post-fix it runs at parity with
        # queue wait (~0.1 ms), so a hair's win either way is noise —
        # fail only if assembly CLEARLY dominates again (>1.25x the
        # largest other stage) or regresses toward the old absolute
        # level (>0.45 ms p50, half the pre-fix figure).
        asm = stages.get("assembly", {}).get("p50_ms", 0.0)
        others = max((stages[s]["p50_ms"] for s in stages
                      if s not in ("total", "assembly")), default=0.0)
        if asm > max(1.25 * others, 0.2) or asm > 0.45:
            print(f"FAIL: assembly is again the dominant stage "
                  f"({asm} ms p50 vs {others} ms largest other)",
                  file=sys.stderr)
            return 1
        print(f"ok: p99 {lat['p99_us']:.0f} us <= 1000 us; assembly "
              f"p50 {asm} ms (largest other stage {others} ms)",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--assert-meets", action="store_true",
                    help="exit nonzero unless p99 <= 1 ms on CPU and "
                         "assembly is not the dominant stage")
    args = ap.parse_args()
    sys.exit(main(assert_meets=args.assert_meets))
