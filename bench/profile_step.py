"""Decompose the stream decision step into component op timings on the
real device.

The VERDICT r1 mandate: profile first, record where the milliseconds go.
Each op is wrapped in a fori_loop of REPS iterations with an
iteration-dependent input tweak (prevents CSE/hoisting) so the per-op time
dominates the ~100 ms fixed D2H fetch latency of this platform; the loop
carries a data dependency so iterations serialize.  Only a tiny reduction
is fetched.

Run from /root/repo:   python bench/profile_step.py [--small]

``--host-stages`` instead runs the HOST pipeline decomposition (r6): a
small int-key and str-key stream through TpuBatchedStorage with a meter
registry, printing the per-stage timers the storage now records
(ratelimiter.stream.pack / index / layout / enqueue / fetch) — where a
stream chunk's milliseconds go before and after the device.
"""

from __future__ import annotations

import json
import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")

import jax
import jax.numpy as jnp
import numpy as np

S = 1 << 20          # slot-array rows
B_FLAT = 1 << 22     # flat mega-batch (= K*B of the stream path)
K, B = 8, 1 << 19    # stream scan shape
REPS = 8

if "--small" in sys.argv:
    S, B_FLAT, K, B, REPS = 1 << 14, 1 << 16, 4, 1 << 14, 2


def bench(name, make_fn, *args):
    """jit(make_fn), run once (compile), then time one call incl. the tiny
    fetch. make_fn must fold REPS iterations internally."""
    fn = jax.jit(make_fn)
    t0 = time.perf_counter()
    r = np.asarray(fn(*args))
    compile_s = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        r = np.asarray(fn(*args))
        times.append(time.perf_counter() - t0)
    per_op_ms = (min(times) * 1000) / REPS
    print(f"{name:34s} {per_op_ms:9.2f} ms/op   (compile {compile_s:.1f}s, "
          f"checksum {r!r})", flush=True)
    return per_op_ms


def host_stages():
    """Per-stage host pipeline timers over a small stream pair (int +
    str keys), printed as one JSON line per scenario."""
    import numpy as np

    sys.path.insert(0, "/root/repo")
    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    rng = np.random.default_rng(5)
    n = 1 << 20
    ids = (rng.zipf(1.1, size=n).astype(np.int64) % 100_000)
    keys = [f"k{i}" for i in ids]
    for kind in ("ints", "strs"):
        reg = MeterRegistry()
        storage = TpuBatchedStorage(num_slots=1 << 18,
                                    meter_registry=reg)
        lid = storage.register_limiter(
            "tb", RateLimitConfig(max_permits=100, window_ms=60_000,
                                  refill_rate=50.0))
        def go():
            if kind == "ints":
                return storage.acquire_stream_ids("tb", lid, ids, None)
            return storage.acquire_stream_strs("tb", lid, keys)
        go()  # warm compile shapes
        t0 = time.perf_counter()
        go()
        wall = time.perf_counter() - t0
        stages = {
            name.split(".")[-1]: reg.timer(name).snapshot()
            for name in ("ratelimiter.stream.pack",
                         "ratelimiter.stream.index",
                         "ratelimiter.stream.layout",
                         "ratelimiter.stream.enqueue",
                         "ratelimiter.stream.fetch")}
        print(json.dumps({
            "scenario": f"host_stages_{kind}", "n": n,
            "wall_s": round(wall, 4),
            "decisions_per_sec": round(n / wall, 1),
            "stage_totals_ms": {
                k: round(v["mean_us"] * v["count"] / 1000, 3)
                for k, v in stages.items()},
            "stage_counts": {k: v["count"] for k, v in stages.items()},
            "note": ("stage totals span the warmup pass too (compiles "
                     "land in its enqueue) — compare stages against "
                     "each other, not against wall_s"),
        }), flush=True)
        storage.close()


def main():
    if "--host-stages" in sys.argv:
        host_stages()
        return
    print(f"platform={jax.devices()[0].platform} S={S} B_flat={B_FLAT} "
          f"K={K} B={B} reps={REPS}", flush=True)
    rng = np.random.default_rng(0)
    results = {}

    # Zipf-ish slot ids, sorted variants for the scatter/gather candidates.
    raw = rng.zipf(1.1, size=B_FLAT).astype(np.int64) % S
    slots = jnp.asarray(raw.astype(np.int32))
    sorted_slots = jnp.asarray(np.sort(raw.astype(np.int32)))
    packed4 = jnp.zeros((S, 4), dtype=jnp.int32)
    vals4 = jnp.asarray(rng.integers(0, 1 << 30, (B_FLAT, 4), dtype=np.int32))
    permits = jnp.ones(B_FLAT, dtype=jnp.int32)

    # -- fetch floor ---------------------------------------------------------
    tiny = jnp.zeros((8,), jnp.int32)
    t0 = time.perf_counter()
    np.asarray(tiny + 1)
    t0 = time.perf_counter()
    np.asarray(tiny + 2)
    print(f"{'fetch floor (tiny)':34s} {1000*(time.perf_counter()-t0):9.2f} ms",
          flush=True)

    # -- sort variants -------------------------------------------------------
    def f_argsort2(s):
        def body(i, acc):
            order = jnp.argsort(s ^ i, stable=True)
            inv = jnp.argsort(order)
            return acc + order[0] + inv[0]
        return jax.lax.fori_loop(0, REPS, body, jnp.int32(0))
    results["argsort_x2"] = bench("argsort+inv (2 argsorts)", f_argsort2, slots)

    def f_laxsort(s, p):
        def body(i, acc):
            iota = jnp.arange(s.shape[0], dtype=jnp.int32)
            ss, pp, order = jax.lax.sort((s ^ i, p, iota), num_keys=1,
                                         is_stable=True)
            return acc + ss[0] + pp[0] + order[0]
        return jax.lax.fori_loop(0, REPS, body, jnp.int32(0))
    results["laxsort_3op"] = bench("lax.sort 3-operand", f_laxsort, slots, permits)

    # -- gather --------------------------------------------------------------
    def f_gather(st, s):
        def body(i, acc):
            rows = st[(s + i) & (S - 1)]
            return acc + rows[0, 0] + rows[-1, -1]
        return jax.lax.fori_loop(0, REPS, body, jnp.int32(0))
    results["gather_rows4"] = bench("row gather 4-lane (random)", f_gather,
                                    packed4, slots)
    results["gather_rows4_sorted"] = bench("row gather 4-lane (sorted)",
                                           f_gather, packed4, sorted_slots)

    def f_gather1(st, s):
        flat = st[:, 0]
        def body(i, acc):
            return acc + flat[(s + i) & (S - 1)].sum()
        return jax.lax.fori_loop(0, REPS, body, jnp.int32(0))
    results["gather_1lane"] = bench("gather 1-lane i32 (random)", f_gather1,
                                    packed4, slots)

    # -- scatter variants ----------------------------------------------------
    def f_scatter(st, s, v):
        def body(i, carry):
            widx = jnp.where(s >= 0, (s + i) & (S - 1), S)
            return carry.at[widx].set(v + i, mode="drop")
        return jax.lax.fori_loop(0, REPS, body, st)[0].sum()
    results["scatter_rows4"] = bench("row scatter 4-lane (random)", f_scatter,
                                     packed4, slots, vals4)
    results["scatter_rows4_sorted"] = bench("row scatter 4-lane (sorted)",
                                            f_scatter, packed4, sorted_slots,
                                            vals4)

    def f_scatter_sorted_flags(st, s, v):
        import jax.lax as lax
        def body(i, carry):
            widx = jnp.where(s >= 0, (s + i) & (S - 1), S)
            dnums = lax.ScatterDimensionNumbers(
                update_window_dims=(1,), inserted_window_dims=(0,),
                scatter_dims_to_operand_dims=(0,))
            return lax.scatter(carry, widx[:, None], v + i, dnums,
                               indices_are_sorted=True, unique_indices=False,
                               mode=lax.GatherScatterMode.FILL_OR_DROP)
        return jax.lax.fori_loop(0, REPS, body, st)[0].sum()
    results["scatter_sorted_hint"] = bench("row scatter (sorted=True hint)",
                                           f_scatter_sorted_flags, packed4,
                                           sorted_slots, vals4)

    # -- elementwise / scan costs -------------------------------------------
    def f_cumsum(p):
        x = p.astype(jnp.int64)
        def body(i, acc):
            return acc + jax.lax.associative_scan(jnp.add, x + i)[-1]
        return jax.lax.fori_loop(0, REPS, body, jnp.int64(0))
    results["assoc_cumsum_i64"] = bench("associative cumsum i64", f_cumsum,
                                        permits)

    def f_packbits(s):
        def body(i, acc):
            return acc + jnp.packbits((s + i) > 0).astype(jnp.int32)[0]
        return jax.lax.fori_loop(0, REPS, body, jnp.int32(0))
    results["packbits"] = bench("packbits", f_packbits, slots)

    # -- the real steps ------------------------------------------------------
    sys.path.insert(0, "/root/repo")
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.ops.packed import tb_scan_bits
    from ratelimiter_tpu.ops.token_bucket import make_tb_packed, tb_step_p

    table = LimiterTable()
    lid = table.register(RateLimitConfig(max_permits=50, window_ms=5000,
                                         refill_rate=10.0))
    tarr = table.device_arrays

    state = make_tb_packed(S)
    slots_kb = jnp.asarray(raw.astype(np.int32)[: K * B].reshape(K, B))
    now_k = jnp.full((K,), 1_000_000, dtype=np.int64)

    scan = jax.jit(tb_scan_bits)
    t0 = time.perf_counter()
    st2, bits = scan(state, tarr, slots_kb, jnp.int32(lid), None, now_k)
    np.asarray(bits)
    print(f"{'tb_scan_bits compile+run':34s} {time.perf_counter()-t0:9.2f} s",
          flush=True)
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        st2, bits = scan(st2, tarr, slots_kb, jnp.int32(lid), None,
                         now_k + i + 1)
        np.asarray(bits)
        times.append(time.perf_counter() - t0)
    ms = min(times) * 1000
    print(f"{'tb_scan_bits (K=%d,B=%d)' % (K, B):34s} {ms:9.2f} ms/dispatch "
          f"-> {K*B/min(times)/1e6:.1f}M dec/s", flush=True)
    results["tb_scan_bits_ms"] = ms

    # flat mega-batch: one sorted batch of K*B with equal timestamps
    flat = jax.jit(tb_step_p, donate_argnums=0)
    slots_flat = jnp.asarray(raw.astype(np.int32)[: K * B])
    pf = jnp.ones(K * B, dtype=jnp.int64)
    t0 = time.perf_counter()
    st3, out = flat(st2, tarr, slots_flat, jnp.int32(lid), pf,
                    jnp.int64(2_000_000))
    np.asarray(out.allowed)
    print(f"{'tb_step_p flat compile+run':34s} {time.perf_counter()-t0:9.2f} s",
          flush=True)
    times = []
    for i in range(3):
        t0 = time.perf_counter()
        st3, out = flat(st3, tarr, slots_flat, jnp.int32(lid), pf,
                        jnp.int64(2_000_100 + i))
        np.asarray(out.allowed)
        times.append(time.perf_counter() - t0)
    ms = min(times) * 1000
    print(f"{'tb_step_p flat (B=%d)' % (K*B,):34s} {ms:9.2f} ms/dispatch "
          f"-> {K*B/min(times)/1e6:.1f}M dec/s", flush=True)
    results["tb_step_flat_ms"] = ms

    print(json.dumps(results), flush=True)


if __name__ == "__main__":
    main()
