"""Zipf key-coalescing smoke bench (the v5 ingest perf gate).

Repeat-heavy Zipf traffic with per-key-uniform weights is the wire-speed
ingestion shape (ISSUE 18): the coalesced digest folds every within-chunk
repeat into ONE weighted decision per unique key, so device work scales
with uniques instead of requests.  This bench A/Bs the SAME stream with
``RATELIMITER_COALESCE`` on and off (fresh storage each arm, identical
clocks) and checks both claims:

- **perf**: coalesced decisions/s >= 1.0x the uncoalesced path on the
  Zipf chunk (best-of-2 per arm — the digest must never lose to the
  rank-major scan it replaces on the traffic it exists for);
- **exactness**: ZERO mismatches against the sequential oracle replay
  (``semantics/oracle.py``) — coalescing is an encoding, not a policy.

``--assert-ratio`` turns both checks into hard gates (run by verify.sh).
Emits one JSON line; bench.py records it as ``coalesce_smoke``.
Run with cwd=repo root:  python bench/coalesce_smoke.py
Env: BENCH_SCALE=small shrinks the stream (CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_KEYS = 2000          # distinct keys under the Zipf
ZIPF_A = 1.1


def run_arm(coalesce: bool, ids, perms, reps: int) -> dict:
    """One arm: fresh storage, fixed clock schedule, timed stream."""
    import numpy as np

    import ratelimiter_tpu.storage.tpu as tpu_mod
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    tpu_mod._COALESCE = coalesce
    now = [1_753_000_000_000]
    st = TpuBatchedStorage(num_slots=1 << 13, clock_ms=lambda: now[0])
    cfg = RateLimitConfig(max_permits=40, window_ms=1000, refill_rate=25.0)
    lid = st.register_limiter("tb", cfg)
    # Warm on a SEPARATE limiter: keyspaces are per-lid, so compiles
    # fire without mutating the state the oracle replays from scratch.
    lid_warm = st.register_limiter("tb", cfg)
    try:
        st.acquire_stream_ids("tb", lid_warm, ids[:4096], perms[:4096])
        outs = []
        t0 = time.perf_counter()
        for _ in range(reps):
            outs.append(np.asarray(
                st.acquire_stream_ids("tb", lid, ids, perms)))
            now[0] += 500
        wall = time.perf_counter() - t0
    finally:
        st.close()
    n = reps * len(ids)
    return {
        "coalesce": coalesce,
        "decisions": n,
        "wall_s": round(wall, 4),
        "decisions_per_sec": round(n / wall, 1),
        "outs": outs,
    }


def oracle_replay(ids, perms, reps: int, got_per_rep) -> int:
    """Sequential per-request replay; returns the mismatch count."""
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.semantics import TokenBucketOracle

    cfg = RateLimitConfig(max_permits=40, window_ms=1000, refill_rate=25.0)
    oracle = TokenBucketOracle(cfg)
    now = 1_753_000_000_000
    bad = 0
    for rep in range(reps):
        got = got_per_rep[rep]
        for j, k in enumerate(ids):
            want = oracle.try_acquire(f"id:{k}", int(perms[j]),
                                      now).allowed
            bad += int(bool(got[j]) != want)
        now += 500
    return bad


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--assert-ratio", action="store_true",
                        help="gate coalesced >= 1.0x uncoalesced AND zero "
                             "oracle mismatches")
    args = parser.parse_args()

    import numpy as np

    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    small = os.environ.get("BENCH_SCALE", "small") == "small"
    n = 1 << 15 if small else 1 << 18
    reps = 2 if small else 4
    oracle_reps = reps if small else 1

    rng = np.random.default_rng(18)
    ids = (rng.zipf(ZIPF_A, n) % N_KEYS).astype(np.int64)
    # Per-key-deterministic weight: every repeat carries the same
    # permits, so every chunk takes the coalesced digest.
    perms = (ids % 4 + 1).astype(np.int64)

    # Best-of-2 per arm; the uncoalesced arm runs first so its compiles
    # never land inside the coalesced arm's timing.
    off = max((run_arm(False, ids, perms, reps) for _ in range(2)),
              key=lambda r: r["decisions_per_sec"])
    on = max((run_arm(True, ids, perms, reps) for _ in range(2)),
             key=lambda r: r["decisions_per_sec"])

    # Bit-identity: the two arms must agree on every request of every
    # rep, and the coalesced arm must agree with the sequential oracle.
    for rep in range(reps):
        np.testing.assert_array_equal(on["outs"][rep], off["outs"][rep])
    mismatches = oracle_replay(ids, perms, oracle_reps, on["outs"])

    ratio = on["decisions_per_sec"] / max(off["decisions_per_sec"], 1.0)
    out = {
        "bench": "coalesce_smoke",
        "note": ("CPU in-process: coalesced digest vs rank-major scan on "
                 f"Zipf({ZIPF_A}) traffic with per-key-uniform weights"),
        "n_per_rep": n,
        "reps": reps,
        "zipf_a": ZIPF_A,
        "n_keys": N_KEYS,
        "coalesced_decisions_per_sec": on["decisions_per_sec"],
        "uncoalesced_decisions_per_sec": off["decisions_per_sec"],
        "coalesce_ratio": round(ratio, 3),
        "oracle_requests_checked": oracle_reps * n,
        "oracle_mismatches": mismatches,
    }
    print(json.dumps(out))
    if args.assert_ratio:
        assert mismatches == 0, (
            f"{mismatches} coalesced decisions diverged from the "
            "sequential oracle replay")
        assert ratio >= 1.0, (
            f"coalesced stream fell to {ratio:.2f}x of the uncoalesced "
            f"path ({on['decisions_per_sec']:.0f}/s vs "
            f"{off['decisions_per_sec']:.0f}/s) on Zipf traffic — the "
            "1.0x floor failed")


if __name__ == "__main__":
    main()
