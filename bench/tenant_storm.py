"""Tenant-storm gate: adaptive limits hold goodput where static limits collapse.

The ROADMAP item 3 scenario (ARCHITECTURE §15).  A fleet of
well-behaved tenants shares a downstream resource with one storm
tenant whose provisioned ceiling is generous (the usual over-provisioned
real-world shape: the sum of static limits exceeds the downstream
capacity).  When the storm hits, the static arm keeps admitting the
storm tenant at its full ceiling, the aggregate admitted rate blows the
downstream budget, and every tenant's EFFECTIVE goodput (admitted *
downstream scale) collapses.  The adaptive arm runs the
``control/`` AIMD controller: the storm tenant's denied share spikes,
its limit is cut multiplicatively toward the floor, the aggregate drops
back under the budget, and the well-behaved tenants' goodput holds.

Both arms run the REAL device decision path (``acquire_many`` on a
``TpuBatchedStorage`` under a simulated clock, telemetry plane feeding
the controller, live ``set_policy`` actuation), and every decision in
both arms is compared against a generation-aware oracle replay — a
``semantics/oracle.py`` instance per tenant that ``reconfigure``s at
exactly the controller's ``set_policy`` boundaries (subscribed via
``add_policy_listener``).  A single mismatch fails the gate: adaptivity
must not cost bit-identity.

Gate (``--assert-adaptive``, the verify.sh fast variant):

- adaptive arm: mean well-behaved effective goodput over the storm
  (after a 3 s detection grace) >= 0.8x their pre-storm mean;
- static arm: the same metric < 0.8x (the scenario really collapses);
- recovery: post-storm, the storm tenant's AIMD fraction is rising
  again (additive recovery observed);
- zero oracle mismatches in either arm.

    JAX_PLATFORMS=cpu python bench/tenant_storm.py --assert-adaptive
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

T0 = 1_700_000_000_000
WINDOW_MS = 1000
SLICES_PER_S = 4          # sub-second batches so windows interleave


def run_arm(adaptive: bool, args) -> dict:
    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.semantics.oracle import SlidingWindowOracle
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    clock = {"t": T0}
    registry = MeterRegistry()
    st = TpuBatchedStorage(num_slots=1 << 12, clock_ms=lambda: clock["t"],
                           max_delay_ms=0.2, meter_registry=registry,
                           table_capacity=args.well_tenants + 8)
    well_cfg = RateLimitConfig(max_permits=args.well_limit,
                               window_ms=WINDOW_MS)
    storm_cfg = RateLimitConfig(max_permits=args.storm_limit,
                                window_ms=WINDOW_MS)
    well_lids = [st.register_limiter("sw", well_cfg)
                 for _ in range(args.well_tenants)]
    storm_lid = st.register_limiter("sw", storm_cfg)
    oracles = {lid: SlidingWindowOracle(well_cfg) for lid in well_lids}
    oracles[storm_lid] = SlidingWindowOracle(storm_cfg)
    # Generation-aware replay: the oracle reconfigures at EXACTLY the
    # set_policy boundaries the controller actuates.
    st.add_policy_listener(
        lambda lid, algo, cfg, gen: oracles[lid].reconfigure(cfg))

    controller = None
    if adaptive:
        from ratelimiter_tpu.control import (
            AdaptivePolicyController,
            ControlConfig,
        )

        controller = AdaptivePolicyController(
            st,
            ControlConfig(interval_ms=1000.0, window_ms=2000,
                          target_excess=args.target_excess,
                          increase_fraction=0.1, decrease_factor=0.5,
                          floor_fraction=args.floor_fraction,
                          min_load_per_s=1.0),
            registry=registry)

    mismatches = 0

    def drive(lid: int, demand: int) -> int:
        """One tenant's slice of traffic through the real device path,
        replayed against its oracle."""
        nonlocal mismatches
        if demand <= 0:
            return 0
        key = f"tenant-{lid}"
        out = st.acquire_many("sw", [lid] * demand, [key] * demand,
                              [1] * demand)
        oracle = oracles[lid]
        expect = np.fromiter(
            (oracle.try_acquire(key, 1, clock["t"]).allowed
             for _ in range(demand)), dtype=bool, count=demand)
        mismatches += int((out["allowed"] != expect).sum())
        return int(out["allowed"].sum())

    pre_s, storm_s, post_s = args.pre_s, args.storm_s, args.post_s
    total_s = pre_s + storm_s + post_s
    per_sec = []   # (well_goodput_effective, storm_goodput_effective)
    storm_fraction_track = []
    for sec in range(total_s):
        in_storm = pre_s <= sec < pre_s + storm_s
        storm_demand = args.storm_demand if in_storm \
            else args.storm_idle_demand
        allowed = {lid: 0 for lid in well_lids + [storm_lid]}
        for _slice in range(SLICES_PER_S):
            clock["t"] += WINDOW_MS // SLICES_PER_S
            for lid in well_lids:
                allowed[lid] += drive(lid,
                                      args.well_demand // SLICES_PER_S)
            allowed[storm_lid] += drive(storm_lid,
                                        storm_demand // SLICES_PER_S)
        if controller is not None:
            controller.tick()
            storm_fraction_track.append(
                controller.status()["lids"][str(storm_lid)]["fraction"])
        # Downstream capacity model: admitted decisions past the budget
        # degrade EVERYONE proportionally (a saturated shared resource).
        total = sum(allowed.values())
        scale = min(1.0, args.capacity / max(total, 1))
        well = sum(allowed[lid] for lid in well_lids) * scale
        per_sec.append((well, allowed[storm_lid] * scale))

    pre = [w for w, _ in per_sec[:pre_s]]
    storm_meas = [w for w, _ in
                  per_sec[pre_s + args.grace_s: pre_s + storm_s]]
    report = {
        "arm": "adaptive" if adaptive else "static",
        "well_pre_goodput_per_s": round(sum(pre) / len(pre), 1),
        "well_storm_goodput_per_s": round(
            sum(storm_meas) / max(len(storm_meas), 1), 1),
        "mismatches": mismatches,
        "per_sec_well": [round(w, 1) for w, _ in per_sec],
    }
    report["storm_ratio"] = round(
        report["well_storm_goodput_per_s"]
        / max(report["well_pre_goodput_per_s"], 1e-9), 3)
    if controller is not None:
        s = controller.status()
        report["adjustments"] = s["adjustments"]
        report["generation"] = s["generation"]
        report["storm_fraction_track"] = storm_fraction_track
        # Additive recovery: fraction at the end vs at storm end.
        report["storm_fraction_at_cut"] = storm_fraction_track[
            pre_s + storm_s - 1]
        report["storm_fraction_final"] = storm_fraction_track[-1]
        controller.close()
    st.close()
    return report


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--well-tenants", type=int, default=6)
    parser.add_argument("--well-limit", type=int, default=100,
                        help="well-behaved tenants' provisioned ceiling "
                             "(permits per 1 s window)")
    parser.add_argument("--well-demand", type=int, default=48,
                        help="well-behaved demand per second "
                             "(divisible by 4 slices)")
    parser.add_argument("--storm-limit", type=int, default=300,
                        help="storm tenant's (generous) static ceiling")
    parser.add_argument("--storm-demand", type=int, default=2000)
    parser.add_argument("--storm-idle-demand", type=int, default=20)
    parser.add_argument("--capacity", type=float, default=400.0,
                        help="downstream admitted-decisions/s budget")
    parser.add_argument("--target-excess", type=float, default=0.5)
    parser.add_argument("--floor-fraction", type=float, default=0.1)
    parser.add_argument("--pre-s", type=int, default=8)
    parser.add_argument("--storm-s", type=int, default=12)
    parser.add_argument("--post-s", type=int, default=5)
    parser.add_argument("--grace-s", type=int, default=3,
                        help="detection grace at storm onset excluded "
                             "from the storm measurement")
    parser.add_argument("--soak", action="store_true",
                        help="longer timeline (RUN_SLOW variant)")
    parser.add_argument("--band", type=float, default=0.8,
                        help="goodput band: adaptive must hold >= band "
                             "x pre-storm; static must fall below it")
    parser.add_argument("--assert-adaptive", action="store_true")
    args = parser.parse_args()
    if args.soak:
        args.pre_s, args.storm_s, args.post_s = 15, 45, 15

    static = run_arm(False, args)
    adaptive = run_arm(True, args)
    report = {"static": static, "adaptive": adaptive,
              "band": args.band,
              "downstream_capacity_per_s": args.capacity}
    print(json.dumps(report, indent=2))

    if args.assert_adaptive:
        failures = []
        if adaptive["mismatches"] or static["mismatches"]:
            failures.append(
                f"oracle mismatches: static={static['mismatches']} "
                f"adaptive={adaptive['mismatches']} (decisions must stay "
                "bit-identical to the generation-aware oracle)")
        if adaptive["storm_ratio"] < args.band:
            failures.append(
                f"adaptive arm held only {adaptive['storm_ratio']}x "
                f"pre-storm goodput (< {args.band}x band)")
        if static["storm_ratio"] >= args.band:
            failures.append(
                f"static arm held {static['storm_ratio']}x — the storm "
                "scenario did not collapse static limits; the gate "
                "proves nothing")
        if adaptive.get("adjustments", 0) <= 0:
            failures.append("controller actuated no policy updates")
        if adaptive.get("storm_fraction_final", 0.0) \
                < adaptive.get("storm_fraction_at_cut", 1.0) + 0.15:
            failures.append(
                "no post-storm additive recovery observed "
                f"(fraction {adaptive.get('storm_fraction_at_cut')} -> "
                f"{adaptive.get('storm_fraction_final')})")
        if failures:
            for f in failures:
                print(f"ASSERTION FAILED: {f}", file=sys.stderr)
            sys.exit(1)
        print("tenant-storm gate OK: adaptive "
              f"{adaptive['storm_ratio']}x vs static "
              f"{static['storm_ratio']}x (band {args.band}x)",
              file=sys.stderr)


if __name__ == "__main__":
    main()
