"""Lease loopback benchmark: the wire-frame collapse, measured.

Token leases (leases/, ARCHITECTURE §14) exist to stop paying one wire
frame per decision.  This bench runs both ingress shapes over the same
storage on loopback TCP and reports the collapse:

- **v2 pass** (baseline): N pipelining clients stream per-decision
  TRY_ACQUIRE frames through the sidecar — exactly one wire frame per
  decision (the PR 5 ingress, i.e. today's production path);
- **lease pass**: the same clients speak protocol v3 through a
  ``LeaseClient``: budgets are charged once, burned locally, renewed
  one frame per budget — wire frames per decision ~ 1/budget.

``--assert-ratio`` gates BOTH claims (run by verify.sh):

- >= 10x fewer wire frames per decision than the v2 pass, and
- equal or better decision throughput (local burns are memory-speed;
  anything less means the lease path added overhead somewhere it must
  not).

Emits one JSON line; bench.py can record it as ``lease_loopback``.
Run with cwd=repo root:  python bench/lease_loopback.py
Env: BENCH_SCALE=small shrinks the request count (CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_CLIENTS = 4
PIPELINE = 64          # frames per pipelined v2 batch
KEYS_PER_CLIENT = 8    # distinct leased keys per client (one lease each)
BUDGET = 64


def v2_pass(server, lid, reps: int) -> dict:
    """Per-decision baseline: pipelined TRY_ACQUIRE, 1 frame/decision."""
    from ratelimiter_tpu.service.sidecar import SidecarClient

    barrier = threading.Barrier(N_CLIENTS + 1)
    allowed = [0] * N_CLIENTS

    def client_loop(t: int) -> None:
        cli = SidecarClient("127.0.0.1", server.port, protocol=2)
        try:
            keys = [f"v2-c{t}-k{i % KEYS_PER_CLIENT}"
                    for i in range(PIPELINE)]
            cli.acquire_batch(lid, keys)  # warm
            barrier.wait()
            got = 0
            for _ in range(reps):
                res = cli.acquire_batch(lid, keys)
                got += sum(1 for _, a, _ in res if a)
            allowed[t] = got
        finally:
            cli.close()

    threads = [threading.Thread(target=client_loop, args=(t,), daemon=True)
               for t in range(N_CLIENTS)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    n = N_CLIENTS * reps * PIPELINE
    return {
        "decisions": n,
        "allowed": sum(allowed),
        "wall_s": round(wall, 4),
        "decisions_per_sec": round(n / wall, 1),
        # The v2 protocol is one frame per decision by definition.
        "wire_frames": n,
        "frames_per_decision": 1.0,
    }


def lease_pass(server, lid, reps: int) -> dict:
    """Leased clients: local burns, one renewal frame per budget (+ the
    piggybacked response-less telemetry frame, counted honestly)."""
    from ratelimiter_tpu.leases import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient

    barrier = threading.Barrier(N_CLIENTS + 1)
    stats = [None] * N_CLIENTS
    per_client = reps * PIPELINE

    def client_loop(t: int) -> None:
        wire = SidecarClient("127.0.0.1", server.port)
        # Client 0 traces its leases so the bench can assert the full
        # client->sidecar->batcher->shard lineage server-side.
        cli = LeaseClient(wire, lid, budget=BUDGET,
                          trace_lineage=(t == 0))
        try:
            keys = [f"ls-c{t}-k{i}" for i in range(KEYS_PER_CLIENT)]
            assert cli.try_acquire(keys[0])  # warm (compiles the grant)
            barrier.wait()
            got = 0
            for i in range(per_client):
                if cli.try_acquire(keys[i % KEYS_PER_CLIENT]):
                    got += 1
            traces = [cli.trace_of(k) for k in keys]
            cli.release_all()
            stats[t] = {"allowed": got, "wire": cli.wire_ops,
                        "local": cli.local_decisions,
                        "telemetry_frames": cli.telemetry_flushes,
                        "telemetry_dropped": cli.telemetry_dropped,
                        "traces": [x for x in traces if x]}
        finally:
            wire.close()

    threads = [threading.Thread(target=client_loop, args=(t,), daemon=True)
               for t in range(N_CLIENTS)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    n = N_CLIENTS * per_client
    # Decision frames (grant/renew/release/fallback) are the collapse
    # the lease design claims; telemetry frames are a SEPARATE,
    # response-less observability stream — folding them into the same
    # ratio diluted the headline (~48x read as ~27x).  Report both.
    wire = sum(s["wire"] for s in stats)
    telem = sum(s["telemetry_frames"] for s in stats)
    return {
        "decisions": n,
        "allowed": sum(s["allowed"] for s in stats),
        "local_decisions": sum(s["local"] for s in stats),
        "wall_s": round(wall, 4),
        "decisions_per_sec": round(n / wall, 1),
        "wire_frames": wire,
        "wire_frames_with_telemetry": wire + telem,
        "telemetry_frames": telem,
        "telemetry_dropped": sum(s["telemetry_dropped"] for s in stats),
        "frames_per_decision": round(wire / n, 5),
        "frames_per_decision_with_telemetry": round((wire + telem) / n, 5),
        "budget": BUDGET,
        "traces": [t for s in stats for t in s.get("traces", ())],
        # Ground truth for the fleet-reconciliation assertion: every
        # decision this pass made (including the warm one per client).
        "ground_truth_decisions": N_CLIENTS * (per_client + 1),
    }


def direct_shared_pass(server, lid, reps: int) -> dict:
    """Direct leases on SHARED hot keys: the lease table grants one
    lease per (lid, key), so with every client hammering the same key
    set only one client burns locally per key — the rest pay a wire
    frame per decision through the fallback.  This is the ingress shape
    the aggregator tier (ARCHITECTURE §14b) exists to collapse."""
    from ratelimiter_tpu.leases import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient

    barrier = threading.Barrier(N_CLIENTS + 1)
    stats = [None] * N_CLIENTS
    per_client = reps * PIPELINE
    keys = [f"agg-k{i}" for i in range(KEYS_PER_CLIENT)]  # SHARED

    def client_loop(t: int) -> None:
        wire = SidecarClient("127.0.0.1", server.port)
        cli = LeaseClient(wire, lid, budget=BUDGET, telemetry=False,
                          direct_fallback=True)
        try:
            assert cli.try_acquire(keys[t % KEYS_PER_CLIENT])  # warm
            barrier.wait()
            got = 0
            for i in range(per_client):
                if cli.try_acquire(keys[(t + i) % KEYS_PER_CLIENT]):
                    got += 1
            cli.release_all()
            stats[t] = {"allowed": got, "wire": cli.wire_ops,
                        "local": cli.local_decisions}
        finally:
            wire.close()

    threads = [threading.Thread(target=client_loop, args=(t,), daemon=True)
               for t in range(N_CLIENTS)]
    for th in threads:
        th.start()
    barrier.wait()
    t0 = time.perf_counter()
    for th in threads:
        th.join()
    wall = time.perf_counter() - t0
    n = N_CLIENTS * per_client
    wire = sum(s["wire"] for s in stats)
    return {
        "decisions": n,
        "allowed": sum(s["allowed"] for s in stats),
        "local_decisions": sum(s["local"] for s in stats),
        "wall_s": round(wall, 4),
        "decisions_per_sec": round(n / wall, 1),
        "wire_frames": wire,
        "frames_per_decision": round(wire / n, 5),
    }


def aggregator_pass(server, lid, reps: int) -> dict:
    """The same shared hot keys through ONE EdgeAggregator: each client
    burns a sublease locally, the aggregator holds one bulk lease per
    key and renews its whole portfolio in one v6 OP_BULK_RENEW frame
    per flush — every upstream frame rides ONE TCP connection, counted
    at the aggregator (the only place wire traffic exists)."""
    from ratelimiter_tpu.edge import EdgeAggregator
    from ratelimiter_tpu.leases import LeaseClient
    from ratelimiter_tpu.service.sidecar import SidecarClient

    barrier = threading.Barrier(N_CLIENTS + 1)
    stats = [None] * N_CLIENTS
    per_client = reps * PIPELINE
    keys = [f"agg-k{i}" for i in range(KEYS_PER_CLIENT)]  # SHARED
    wire = SidecarClient("127.0.0.1", server.port)
    agg = EdgeAggregator(wire, bulk_budget=N_CLIENTS * BUDGET * 2,
                         slice_budget=BUDGET, flush_ms=50.0)

    def client_loop(t: int) -> None:
        cli = LeaseClient(agg.session(), lid, budget=BUDGET,
                          telemetry=False, direct_fallback=False)
        assert cli.try_acquire(keys[t % KEYS_PER_CLIENT])  # warm
        barrier.wait()
        got = 0
        for i in range(per_client):
            if cli.try_acquire(keys[(t + i) % KEYS_PER_CLIENT]):
                got += 1
        cli.release_all()
        stats[t] = {"allowed": got, "local": cli.local_decisions}

    try:
        threads = [threading.Thread(target=client_loop, args=(t,),
                                    daemon=True)
                   for t in range(N_CLIENTS)]
        for th in threads:
            th.start()
        barrier.wait()
        t0 = time.perf_counter()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t0
        agg.release_all()
        n = N_CLIENTS * per_client
        return {
            "decisions": n,
            "allowed": sum(s["allowed"] for s in stats),
            "local_decisions": sum(s["local"] for s in stats),
            "wall_s": round(wall, 4),
            "decisions_per_sec": round(n / wall, 1),
            "wire_frames": agg.upstream_frames,
            "bulk_renewals": agg.bulk_renewals_total,
            "subleases_granted": agg.slices_granted_total,
            "frames_per_decision": round(agg.upstream_frames / n, 5),
        }
    finally:
        wire.close()


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--assert-ratio", action="store_true",
                        help="gate >=10x wire-frame reduction at equal or "
                             "better decision throughput vs the v2 pass")
    parser.add_argument("--aggregator", action="store_true",
                        help="also run the shared-hot-key arms: direct "
                             "leases (fallback-heavy) vs one edge "
                             "aggregator subleasing bulk budgets; with "
                             "--assert-ratio, gate the >=5x collapse")
    args = parser.parse_args()

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.leases import LeaseManager
    from ratelimiter_tpu.service.sidecar import SidecarServer
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    small = os.environ.get("BENCH_SCALE", "small") == "small"
    reps = 30 if small else 150

    storage = TpuBatchedStorage(num_slots=1 << 14, max_delay_ms=0.3,
                                max_inflight=4)
    server = SidecarServer(storage, host="127.0.0.1").start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1 << 20, window_ms=60_000, refill_rate=1e6))
        server.attach_leases(LeaseManager(
            storage, default_budget=BUDGET, max_budget=BUDGET,
            ttl_ms=60_000.0,
            # Only bulk (aggregator-tier) grants see this cap; the
            # default arms never issue one, so their wire traffic is
            # byte-identical with or without it.
            max_bulk_budget=N_CLIENTS * BUDGET * 4))
        storage.warm_micro_shapes()

        # Best-of-2 each (scheduler noise must not read as a regression).
        v2 = max((v2_pass(server, lid, reps) for _ in range(2)),
                 key=lambda r: r["decisions_per_sec"])
        plane = storage.telemetry
        fleet0 = plane.allowed_total + plane.denied_total
        ls_runs = [lease_pass(server, lid, reps) for _ in range(2)]
        fleet_delta = plane.allowed_total + plane.denied_total - fleet0
        ls = max(ls_runs, key=lambda r: r["decisions_per_sec"])

        # Telemetry round trip: after release_all's final flush, the
        # server-side fleet decision counters must reconcile EXACTLY
        # with the clients' ground-truth decision counts (the staleness
        # bound is one flush interval; at release it is zero).
        expected = sum(r["ground_truth_decisions"] for r in ls_runs)
        telemetry = {
            "fleet_counter_delta": fleet_delta,
            "ground_truth": expected,
            "lease_local_folded": plane.lease_local_total,
            "reports": plane.reports_total,
            "staleness_ms": plane.staleness_ms(),
        }
        assert fleet_delta == expected, (
            f"fleet decision counters ({fleet_delta}) do not reconcile "
            f"with client ground truth ({expected}) after the final "
            "telemetry flush")
        assert plane.reports_total > 0, "no telemetry report was folded"
        # A traced leased key must read back the full distributed
        # lineage: client -> sidecar -> batcher -> shard.
        lineage_ok = False
        for tid in ls_runs[-1]["traces"]:
            hops = set(storage.lineage.hops(tid))
            if {"sidecar", "lease.grant", "client", "batcher",
                    "shard"} <= hops:
                lineage_ok = True
                break
        assert lineage_ok, (
            "no leased trace carried the full client->sidecar->batcher->"
            "shard lineage")

        reduction = (v2["frames_per_decision"]
                     / max(ls["frames_per_decision"], 1e-9))
        reduction_all = (v2["frames_per_decision"]
                         / max(ls["frames_per_decision_with_telemetry"],
                               1e-9))
        speedup = ls["decisions_per_sec"] / max(v2["decisions_per_sec"],
                                                1.0)
        out = {
            "bench": "lease_loopback",
            "note": ("loopback TCP, CPU device in-process: measures the "
                     "wire-frame collapse of token leases vs the "
                     "per-decision v2 ingress over the same storage"),
            "v2": v2,
            "lease": {k: v for k, v in ls.items() if k != "traces"},
            "telemetry": telemetry,
            # Headline = DECISION frames only; the telemetry stream is
            # reported alongside, not folded in (it diluted the ratio).
            "wire_frame_reduction": round(reduction, 1),
            "wire_frame_reduction_with_telemetry": round(reduction_all, 1),
            "throughput_ratio": round(speedup, 2),
        }
        if args.aggregator:
            # Shared-hot-key arms (ARCHITECTURE §14b): direct leases
            # degenerate to per-decision fallback when every client
            # hammers the same keys; one aggregator collapses that
            # ingress multiplicatively.  Same admitted traffic: the
            # generous config admits every burn, so any allowed !=
            # decisions gap is an admission mismatch, not throttling.
            direct = max((direct_shared_pass(server, lid, reps)
                          for _ in range(2)),
                         key=lambda r: r["decisions_per_sec"])
            agg = max((aggregator_pass(server, lid, reps)
                       for _ in range(2)),
                      key=lambda r: r["decisions_per_sec"])
            assert direct["allowed"] == direct["decisions"], (
                f"direct-shared arm admission mismatch: "
                f"{direct['allowed']} != {direct['decisions']}")
            assert agg["allowed"] == agg["decisions"], (
                f"aggregator arm admission mismatch: "
                f"{agg['allowed']} != {agg['decisions']}")
            collapse = (direct["frames_per_decision"]
                        / max(agg["frames_per_decision"], 1e-9))
            out["direct_shared"] = direct
            out["aggregator"] = agg
            out["aggregator_frame_collapse"] = round(collapse, 1)
        print(json.dumps(out))
        if args.assert_ratio:
            assert reduction >= 10.0, (
                f"lease wire-frame reduction {reduction:.1f}x < 10x "
                f"(lease {ls['frames_per_decision']:.4f} frames/decision "
                f"vs v2 {v2['frames_per_decision']:.1f})")
            assert speedup >= 1.0, (
                f"leased decision throughput fell to {speedup:.2f}x of "
                f"the per-decision v2 path ({ls['decisions_per_sec']:.0f}"
                f"/s vs {v2['decisions_per_sec']:.0f}/s)")
            if args.aggregator:
                assert collapse >= 5.0, (
                    f"aggregator frame collapse {collapse:.1f}x < 5x vs "
                    f"the direct-lease arm on the same shared hot keys "
                    f"(agg {agg['frames_per_decision']:.5f} "
                    f"frames/decision vs direct "
                    f"{direct['frames_per_decision']:.5f})")
    finally:
        server.stop()
        storage.close()


if __name__ == "__main__":
    main()
