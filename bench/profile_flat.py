"""Measure the flat mega-batch step on the real device: XLA scatter vs the
Pallas block-scatter, TB and SW, at the bench stream shape (4M requests,
1M slots, Zipf keys).

Run from /root/repo:  python bench/profile_flat.py [--small] [--noblock]
"""

from __future__ import annotations

import os
import sys
import time

os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/root/repo/.jax_cache")
if "--noblock" in sys.argv:
    os.environ["RATELIMITER_BLOCK_SCATTER"] = "0"

import jax
import numpy as np

S = 1 << 20
B = 1 << 22
if "--small" in sys.argv:
    S, B = 1 << 14, 1 << 16

sys.path.insert(0, "/root/repo")
from ratelimiter_tpu.core.config import RateLimitConfig  # noqa: E402
from ratelimiter_tpu.engine.engine import DeviceEngine  # noqa: E402
from ratelimiter_tpu.engine.state import LimiterTable  # noqa: E402
from ratelimiter_tpu.ops.pallas import block_scatter  # noqa: E402


def run(engine, algo, slots, lids, permits, now0):
    fn = (engine.sw_flat_dispatch if algo == "sw"
          else engine.tb_flat_dispatch)
    t0 = time.perf_counter()
    np.asarray(fn(slots, lids, permits, now0))
    print(f"  {algo} compile+run: {time.perf_counter() - t0:.1f}s", flush=True)
    times = []
    for i in range(4):
        t0 = time.perf_counter()
        np.asarray(fn(slots, lids, permits, now0 + 1 + i))
        times.append(time.perf_counter() - t0)
    ms = min(times) * 1000
    print(f"  {algo} flat B={len(slots)}: {ms:.1f} ms -> "
          f"{len(slots)/min(times)/1e6:.1f}M dec/s "
          f"(all: {[f'{t*1000:.0f}' for t in times]})", flush=True)


def main():
    print(f"platform={jax.devices()[0].platform} S={S} B={B} "
          f"block_scatter_flag={block_scatter._FLAG}", flush=True)
    rng = np.random.default_rng(0)
    table = LimiterTable()
    lid_sw = table.register(RateLimitConfig(max_permits=100, window_ms=60_000))
    lid_tb = table.register(RateLimitConfig(max_permits=50, window_ms=5000,
                                            refill_rate=10.0))
    engine = DeviceEngine(S, table)
    print("block_scatter enabled:",
          block_scatter.enabled((S, 4), B), flush=True)

    slots = (rng.zipf(1.1, size=B).astype(np.int64) % S).astype(np.int32)
    run(engine, "tb", slots, lid_tb, None, 1_000_000)
    run(engine, "sw", slots, lid_sw, None, 1_000_000)
    permits = rng.integers(1, 100, B).astype(np.int32)
    run(engine, "tb", slots, lid_tb, permits, 2_000_000)


if __name__ == "__main__":
    main()
