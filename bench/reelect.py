"""Election re-seed sweep (the BENCH round refresher, ISSUE 18 c).

The measured elections — ``pallas.relay_fused_live`` vs the lowered
micro step, block-scatter vs dense one-hot, device-journal placement,
the staged micro-step combine, and the sharded route (host vs device
counting sort) — persist their verdicts on disk so production boots
skip the probe.  Verdicts go stale: a runtime upgrade, a new BLAS, or a
changed kernel can flip a winner, and a stale verdict silently pins the
loser.  This sweep:

1. snapshots then DELETES every persisted verdict (``pallas_elect_*``)
   and device-rate probe (``device_rates_*``) under the repo cache and
   the user cache, so the next dispatch of each path re-measures;
2. re-runs ``bench/sharded_scaling.py`` (a fresh storage per shard
   count re-elects ``sharded.route_elect`` at runtime — that election
   is never disk-cached);
3. runs ``bench.py`` for a full round (its in-process dispatches
   re-elect every pallas path and re-probe device rates) and writes the
   refreshed round to ``BENCH_r06.json`` in the same shape as prior
   rounds (``{n, cmd, rc, tail, parsed}``) plus the refreshed election
   verdicts, the prior (pre-clear) verdicts for diffing, and the
   sharded-scaling points.

Run with cwd=repo root:  python bench/reelect.py
Flags: --skip-bench  (clear + sharded_scaling only; no BENCH_r06.json)
Env: BENCH_SCALE=small keeps the refresh cheap (CI).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

ROUND = 6


def _cache_dirs() -> list:
    """Every directory a verdict or rate probe may persist under."""
    from ratelimiter_tpu.utils.compile_cache import default_cache_dir

    dirs = [os.path.join(_REPO, ".jax_cache"), default_cache_dir()]
    extra = os.environ.get("RATELIMITER_REELECT_EXTRA_DIR")
    if extra:
        dirs.append(extra)
    return [d for d in dirs if os.path.isdir(d)]


def clear_verdicts() -> dict:
    """Snapshot + delete persisted election/rate files; return the
    snapshot keyed by filename (the pre-clear verdicts, for diffing)."""
    prior: dict = {}
    removed = []
    for d in _cache_dirs():
        for pat in ("pallas_elect_*.json", "device_rates_*.json"):
            for path in sorted(glob.glob(os.path.join(d, pat))):
                name = os.path.basename(path)
                try:
                    with open(path) as fh:
                        prior[name] = json.load(fh)
                except Exception as exc:  # noqa: BLE001 — record, still clear
                    prior[name] = {"unreadable": str(exc)}
                os.unlink(path)
                removed.append(path)
    return {"prior_verdicts": prior, "removed": removed}


def refresh_elections() -> dict:
    """Force-resolve every election that can measure on this platform.

    bench.py's in-process report only contains paths its own dispatches
    happened to probe — on CPU the pallas kernels are unsupported (no
    probe fires, by design), so the report would be empty there.  This
    resolves each electable path directly against the now-cleared disk
    cache: the pallas settle (micro / block_scatter / relay_fused — a
    no-op off-TPU), the device-journal placement (measures on every
    backend), and the device step-rate probe the chunk scheduler elects
    plans from."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    from ratelimiter_tpu.engine import device_rates
    from ratelimiter_tpu.ops import pallas as pallas_pkg
    from ratelimiter_tpu.ops.pallas import election
    from ratelimiter_tpu.replication import log as rlog
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    election.reset_for_tests()       # drop in-process memos too
    device_rates._mem_cache.clear()
    pallas_pkg.settle_all()          # TPU: micro/block_scatter/relay_fused
    rlog.device_journal_elected()    # measures host-vs-device everywhere
    rates = device_rates.get_device_rates()
    return {"platform": jax.default_backend(),
            "verdicts": election.report(),
            "device_rates": {k: v for k, v in rates.items()
                             if not k.startswith("_")}}


def _run(cmd_path: str, timeout: int) -> dict:
    """Run one bench script as a subprocess; parse its last JSON line."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    proc = subprocess.run([sys.executable, cmd_path], capture_output=True,
                          timeout=timeout, text=True, cwd=_REPO, env=env)
    out = {"rc": proc.returncode,
           "tail": (proc.stdout + proc.stderr)[-2000:]}
    if proc.returncode == 0 and proc.stdout.strip():
        try:
            out["parsed"] = json.loads(
                proc.stdout.strip().splitlines()[-1])
        except ValueError:
            pass
    return out


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--skip-bench", action="store_true",
                        help="clear verdicts + rerun sharded_scaling only "
                             "(no bench.py round, no BENCH_r06.json)")
    parser.add_argument("--bench-timeout", type=int, default=3600)
    args = parser.parse_args()

    t0 = time.time()
    cleared = clear_verdicts()
    print(f"cleared {len(cleared['removed'])} persisted verdict/rate "
          f"file(s) across {len(_cache_dirs())} cache dir(s)",
          file=sys.stderr)
    refreshed = refresh_elections()
    print(f"re-measured elections on {refreshed['platform']}: "
          f"{sorted(refreshed['verdicts'])}", file=sys.stderr)

    # Fresh storages re-elect the route per boot; nothing persisted to
    # clear for this one, the rerun IS the refresh.
    print("re-running sharded_scaling (route re-election)...",
          file=sys.stderr)
    sharded = _run(os.path.join(_REPO, "bench", "sharded_scaling.py"),
                   timeout=900)
    if args.skip_bench:
        print(json.dumps({"cleared": len(cleared["removed"]),
                          "elections": sorted(refreshed["verdicts"]),
                          "sharded_rc": sharded["rc"]}))
        return

    # Full round: bench.py re-elects every pallas path on first dispatch
    # (the files we just deleted force a fresh measurement) and writes
    # the refreshed verdicts into BENCH_DETAIL.json.
    print("running bench.py (fresh election round)...", file=sys.stderr)
    bench = _run(os.path.join(_REPO, "bench.py"),
                 timeout=args.bench_timeout)

    # Verdicts of record: the force-resolved set, overlaid with
    # anything bench.py's own dispatches probed (on TPU the bench
    # round's in-traffic measurements win over the synthetic probe).
    elections: dict = dict(refreshed["verdicts"])
    try:
        with open(os.path.join(_REPO, "BENCH_DETAIL.json")) as fh:
            bench_elections = json.load(fh).get("pallas", {}).get(
                "elections", {})
        if isinstance(bench_elections, dict):
            elections.update(bench_elections)
    except Exception as exc:  # noqa: BLE001 — round still recorded
        elections["bench_detail_error"] = str(exc)

    record = {
        "n": ROUND,
        "cmd": "python bench/reelect.py  # clears election caches, then "
               "python bench.py",
        "rc": bench["rc"],
        "tail": bench["tail"],
        "parsed": bench.get("parsed"),
        "elections": elections,
        "election_platform": refreshed["platform"],
        "device_rates": refreshed["device_rates"],
        "prior_verdicts": cleared["prior_verdicts"],
        "verdict_files_cleared": [os.path.relpath(p, _REPO)
                                  if p.startswith(_REPO) else p
                                  for p in cleared["removed"]],
        "sharded_scaling": sharded.get("parsed",
                                       {"rc": sharded["rc"],
                                        "tail": sharded["tail"][-500:]}),
        "reelect_wall_s": round(time.time() - t0, 1),
    }
    out_path = os.path.join(_REPO, f"BENCH_r{ROUND:02d}.json")
    with open(out_path, "w") as fh:
        json.dump(record, fh, indent=2)
    print(json.dumps({"round": ROUND, "rc": bench["rc"],
                      "elections": list(elections)
                      if isinstance(elections, dict) else [],
                      "cleared": len(cleared["removed"]),
                      "wrote": os.path.basename(out_path)}))
    if bench["rc"] != 0:
        sys.exit(1)


if __name__ == "__main__":
    main()
