"""Device-only chained-step benchmark (VERDICT r3 #4).

Chains K decision steps inside ONE jit over donated device state and
fetches a single checksum — so the measurement contains the decision
step itself and no per-step wire.  This converts ARCHITECTURE §8b's
"~300M decisions/s device headroom" from cost-model arithmetic into a
measurement on this hardware, and gives the Pallas kernels a verdict:
run the same harness with RATELIMITER_PALLAS=1/0 (subprocess pair from
bench.py — the kernels bind at import).

Two chained steps are measured:
- ``relay``: the unit-permit relay words step (ops/relay.py:
  tb_relay_bits) — the streaming hot path's dominant dispatch.  No
  sort, no solver; slots rotate per step so every iteration touches a
  different 512K-slot subset of the 1M-slot state.
- ``flat``: the sorted flat step with weighted permits (ops/flat.py:
  tb_flat_bits) — the path that exercises the Pallas sandwich solver
  and (via scatter_rows_sorted) the block-scatter kernel.

Prints ONE JSON line.  Run with cwd=repo root.
"""

from __future__ import annotations

import functools
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main() -> None:
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))

    import jax
    import jax.numpy as jnp

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.ops import flat, relay

    num_slots = 1 << 20
    B = 1 << 19
    table = LimiterTable()
    lid = table.register(RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    eng = DeviceEngine(num_slots, table)
    rb = eng.rank_bits
    tarr = table.device_arrays
    lid_dev = jnp.int32(lid)

    # RTT floor so the fetch's fixed cost can be subtracted out.
    tiny = jax.jit(lambda v: v.sum())
    np.asarray(tiny(jnp.zeros(8, jnp.int32)))  # compile + settle
    t0 = time.perf_counter()
    for _ in range(3):
        np.asarray(tiny(jnp.zeros(8, jnp.int32)))
    rtt_s = (time.perf_counter() - t0) / 3

    base = jnp.arange(B, dtype=jnp.int32) * (num_slots // B)

    def relay_chain(K):
        def run(packed, now0):
            def body(i, carry):
                packed, acc = carry
                slots = (base + i * jnp.int32(7919)) % num_slots
                words = (slots.astype(jnp.uint32)
                         << np.uint32(rb + 1)) | np.uint32(1)
                packed, bits = relay.tb_relay_bits(
                    packed, tarr, words, lid_dev, now0 + i, rank_bits=rb)
                return packed, acc + jnp.sum(bits.astype(jnp.int64))
            packed, acc = jax.lax.fori_loop(0, K, body,
                                            (packed, jnp.int64(0)))
            return packed, acc
        return jax.jit(run, donate_argnums=0)

    # Weighted flat with duplicates: base has stride 2, so
    # (base >> 3) * 8 maps every 4 consecutive lanes to one slot —
    # 4-deep segments driving the segmented solver through real work.
    perms = jnp.asarray(
        (np.random.default_rng(5).integers(1, 9, B)).astype(np.int32))

    def flat_chain(K):
        def run(packed, now0):
            def body(i, carry):
                packed, acc = carry
                slots = ((base >> 3) * 8 + i * jnp.int32(7919)) % num_slots
                packed, bits = flat.tb_flat_bits(
                    packed, tarr, slots, lid_dev, perms, now0 + i)
                return packed, acc + jnp.sum(bits.astype(jnp.int64))
            packed, acc = jax.lax.fori_loop(0, K, body,
                                            (packed, jnp.int64(0)))
            return packed, acc
        return jax.jit(run, donate_argnums=0)

    def measure(make_chain, packed0):
        # Calibrate with a short chain, then re-run sized for ~2-4 s of
        # device time so the round trip amortizes away.
        K0 = 8
        fn = make_chain(K0)
        packed, acc = fn(packed0, jnp.int64(1_000_000))
        int(np.asarray(acc))  # settle compile + first run
        t0 = time.perf_counter()
        packed, acc = fn(packed, jnp.int64(2_000_000))
        int(np.asarray(acc))
        dt0 = time.perf_counter() - t0
        per_step = max((dt0 - rtt_s) / K0, 1e-5)
        K = int(min(max(2.0 / per_step, K0), 1024))
        fn = make_chain(K)
        packed, acc = fn(packed, jnp.int64(3_000_000))
        int(np.asarray(acc))  # compile the real K untimed
        t0 = time.perf_counter()
        packed, acc = fn(packed, jnp.int64(4_000_000))
        checksum = int(np.asarray(acc))
        dt = time.perf_counter() - t0
        dev_s = max(dt - rtt_s, 1e-9)
        return {
            "steps": K, "lanes_per_step": B,
            "decisions": K * B,
            "wall_s": round(dt, 4),
            "device_s": round(dev_s, 4),
            "decisions_per_sec": round(K * B / dev_s, 1),
            "ns_per_decision": round(dev_s / (K * B) * 1e9, 3),
            "checksum": checksum,
        }

    # Digest counts step, slot-SORTED (presorted dense block sweep) vs
    # unsorted (XLA per-index scatter) — the r4 sorted-digest change's
    # on-device verdict.  One uword per unique, count 1; slots fixed per
    # chain (strided ascending for sorted, a fixed permutation for
    # unsorted — HBM has no cache to warm either way).
    uslots_sorted = np.arange(B, dtype=np.uint32) * (num_slots // B)
    uslots_shuf = np.random.default_rng(9).permutation(
        uslots_sorted).astype(np.uint32)

    def digest_chain(slots_np, sorted_flag):
        uw = jnp.asarray((slots_np << np.uint32(rb + 1))
                         | np.uint32(1 << 1))

        def make(K):
            def run(packed, now0):
                def body(i, carry):
                    packed, acc = carry
                    packed, counts = relay.tb_relay_counts(
                        packed, tarr, uw, lid_dev, now0 + i,
                        rank_bits=rb, out_dtype=jnp.uint8,
                        slots_sorted=sorted_flag)
                    return packed, acc + jnp.sum(
                        counts.astype(jnp.int64))
                packed, acc = jax.lax.fori_loop(0, K, body,
                                                (packed, jnp.int64(0)))
                return packed, acc
            return jax.jit(run, donate_argnums=0)
        return make

    # Micro-batch step at the batcher's bucket shapes (VERDICT r4 #3):
    # K chained relay steps in one jit — the per-step figure is the
    # DEVICE term of a local-attached deployment's per-request latency
    # floor (flush deadline + this + PCIe round trip), measured instead
    # of projected.  Measured at 256 lanes (the r4 figure) AND at the
    # r6 _MICRO_FLOOR (32 lanes — the shape interactive micro-batches
    # actually dispatch at now).
    def micro_chain_lanes(K, mb):
        mbase = jnp.arange(mb, dtype=jnp.int32) * (num_slots // mb)

        def run(packed, now0):
            def body(i, carry):
                packed, acc = carry
                slots = (mbase + i * jnp.int32(7919)) % num_slots
                words = (slots.astype(jnp.uint32)
                         << np.uint32(rb + 1)) | np.uint32(1)
                packed, bits = relay.tb_relay_bits(
                    packed, tarr, words, lid_dev, now0 + i, rank_bits=rb)
                return packed, acc + jnp.sum(bits.astype(jnp.int64))
            packed, acc = jax.lax.fori_loop(0, K, body,
                                            (packed, jnp.int64(0)))
            return packed, acc
        return jax.jit(run, donate_argnums=0)

    def measure_micro(mb=256):
        from ratelimiter_tpu.ops.token_bucket import make_tb_packed

        # 32K chained steps: a 256-lane step is sub-microsecond on TPU
        # (a 512-step chain vanished inside the tunnel's RTT jitter), so
        # the chain must run tens of ms to measure above it.
        K = 32768
        fn = micro_chain_lanes(K, mb)
        # Fresh state: eng.tb_packed is the relay chain's (donated there).
        packed, acc = fn(make_tb_packed(num_slots), jnp.int64(1_000_000))
        int(np.asarray(acc))  # compile + settle
        t0 = time.perf_counter()
        packed, acc = fn(packed, jnp.int64(2_000_000))
        checksum = int(np.asarray(acc))
        dt = time.perf_counter() - t0
        per_step_us = max(dt - rtt_s, 1e-9) / K * 1e6
        return {"steps": K, "lanes_per_step": mb,
                "us_per_step": round(per_step_us, 3),
                "checksum": checksum,
                "note": ("device term of the local-attachment per-"
                         "request floor: flush deadline + this + "
                         "interconnect round trip")}

    from ratelimiter_tpu.ops.pallas import block_scatter, solver

    out = {
        "pallas_flag": os.environ.get("RATELIMITER_PALLAS", "1"),
        "solver_live": bool(solver.settle()),
        "block_scatter_live": bool(block_scatter.settle()),
        "rtt_ms": round(rtt_s * 1000, 1),
        "microbatch_256": measure_micro(256),
        "microbatch_32": measure_micro(32),
        "relay": measure(relay_chain, eng.tb_packed),
    }
    # Local-SLO floor guard (ISSUE r6 satellite): the micro-batch device
    # step must sit below the 0.697 ms figure the r5 SLO decomposition
    # attributed to the device — a regression here silently re-opens the
    # p50 miss, so it fails the bench loudly instead.
    slo_floor_ms = 0.697
    out["micro_step_slo"] = {
        "floor_ms": slo_floor_ms,
        "us_per_step_32": out["microbatch_32"]["us_per_step"],
        "meets": bool(out["microbatch_32"]["us_per_step"] / 1000.0
                      < slo_floor_ms),
    }
    assert out["micro_step_slo"]["meets"], (
        f"32-lane micro step {out['microbatch_32']['us_per_step']} us "
        f">= SLO floor {slo_floor_ms} ms")
    # Later chains start from fresh state (prior chains donated theirs).
    from ratelimiter_tpu.ops.token_bucket import make_tb_packed

    # Steady-state micro-loop recompile guard (r11 satellite): warm the
    # double-buffered staged shapes (both in-flight buffers), then drive
    # a steady interactive loop at jittered lane counts inside the
    # warmed buckets and assert ZERO new XLA compiles fire — a compile
    # inside the steady loop is a multi-hundred-ms p99 spike the warmup
    # exists to prevent.
    from ratelimiter_tpu.engine.engine import MICRO_STAGE_ROWS

    eng.tb_packed = make_tb_packed(num_slots)  # relay chain donated it
    eng.warm_micro_shapes(sizes=(32, 64, 128))
    compiles_before = eng.micro_compile_count()
    bufs = []
    for cap in (32, 64, 128, 32):  # the double buffer's two halves
        b = np.empty((MICRO_STAGE_ROWS, cap), dtype=np.int64)
        b[0] = -1
        b[1] = lid
        b[2] = 1
        bufs.append(b)
    steps = 200
    t0 = time.perf_counter()
    for i in range(steps):
        b = bufs[i % len(bufs)]
        algo = "tb" if i % 2 else "sw"
        n = 1 + (i * 13) % b.shape[1]
        b[0, :n] = (np.arange(n) * 7919 + i) % num_slots
        b[3, 0] = 3_000_000 + i
        h = eng.micro_staged_dispatch(algo, b, n)
        eng.micro_staged_drain(algo, h, n)
        b[0, :n] = -1
    dt = time.perf_counter() - t0
    compiles_after = eng.micro_compile_count()
    out["micro_staged"] = {
        "steps": steps,
        "ms_per_dispatch_drain": round(dt / steps * 1000, 3),
        "compiles_before": compiles_before,
        "compiles_after": compiles_after,
        "recompiled": bool(compiles_after != compiles_before),
    }
    assert not out["micro_staged"]["recompiled"], (
        f"steady-state micro loop recompiled: {compiles_before} -> "
        f"{compiles_after} staged-step executables (warm_micro_shapes "
        "no longer covers the batcher's dispatch buckets)")

    out["flat_weighted"] = measure(flat_chain, make_tb_packed(num_slots))
    out["digest_sorted"] = measure(digest_chain(uslots_sorted, True),
                                   make_tb_packed(num_slots))
    out["digest_unsorted"] = measure(digest_chain(uslots_shuf, False),
                                     make_tb_packed(num_slots))

    # Fused Pallas relay step (ops/pallas/relay_step.py): the same
    # sorted digest traffic through the single-pass gather+update+
    # scatter kernel, directly comparable to digest_sorted (composed
    # XLA + presorted sweep) and to the relay words step.
    from ratelimiter_tpu.ops.pallas import election as pallas_election
    from ratelimiter_tpu.ops.pallas import relay_step as fused_relay

    out["relay_fused_live"] = bool(fused_relay.settle())
    if fused_relay.enabled((num_slots, 4), B, rb):
        uw_f = jnp.asarray((uslots_sorted << np.uint32(rb + 1))
                           | np.uint32(1 << 1))

        def fused_chain(K):
            def run(packed, now0):
                def body(i, carry):
                    packed, acc = carry
                    packed, counts = fused_relay.tb_relay_counts_fused(
                        packed, tarr, uw_f, lid_dev, now0 + i,
                        rank_bits=rb,
                        interpret=fused_relay.interpret_mode())
                    return packed, acc + jnp.sum(counts.astype(jnp.int64))
                packed, acc = jax.lax.fori_loop(0, K, body,
                                                (packed, jnp.int64(0)))
                return packed, acc
            return jax.jit(run, donate_argnums=0)

        out["digest_fused"] = measure(fused_chain, make_tb_packed(num_slots))

    # Per-path election records + the elected-never-slower gate
    # (VERDICT #7): the backend the engine actually dispatches for the
    # sorted relay/digest step must not be measurably slower than the
    # XLA path on this device.  1.10 margin absorbs run-to-run noise;
    # a real inversion (an election serving a slower kernel) fails the
    # bench loudly.
    out["pallas_elections"] = pallas_election.report()
    serves_fused = out["relay_fused_live"] and "digest_fused" in out
    elected = out["digest_fused"] if serves_fused else out["digest_sorted"]
    out["relay_election_check"] = {
        "elected_backend": "pallas_fused" if serves_fused else "xla",
        "elected_ns_per_unique": elected["ns_per_decision"],
        "xla_sorted_ns_per_unique": out["digest_sorted"][
            "ns_per_decision"],
        "xla_relay_words_ns_per_lane": out["relay"]["ns_per_decision"],
        "ok": bool(elected["ns_per_decision"]
                   <= 1.10 * out["digest_sorted"]["ns_per_decision"]),
    }
    assert out["relay_election_check"]["ok"], (
        f"elected relay step {elected['ns_per_decision']} ns/unique is "
        f"slower than the XLA sorted digest "
        f"{out['digest_sorted']['ns_per_decision']} ns/unique — the "
        f"per-path election served a losing backend")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
