"""Scenario 1 with LOCAL device attachment: the reference's regime.

The reference's published 80,192 req/s (README.md single-key sliding
window, cache on) lives in a regime where the storage round trip
(~0.8 ms Redis RTT) is far below the 100 ms local-cache TTL.  The dev
tunnel inverts that (~110 ms device RTT > TTL), so the main bench's
scenario 1 measures the link.  This subprocess pins jax to the
in-process CPU device — RTT ~ 0, the regime a production host with a
local-attached TPU sees — and reruns the same limiter + micro-batcher
code.  bench.py records the output as sw_single_key_threaded_local.

Run from the repo root (subprocess of bench.py).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    import jax

    # Must be pinned before any device op: the axon TPU plugin otherwise
    # claims the default backend (the parent bench process owns the TPU).
    jax.config.update("jax_platforms", "cpu")
    import jax.extend

    jax.extend.backend.clear_backends()

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import SlidingWindowRateLimiter
    from ratelimiter_tpu.bench.harness import bench_threaded
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage import TpuBatchedStorage

    sw_cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                             enable_local_cache=True, local_cache_ttl_ms=100)
    storage = TpuBatchedStorage(num_slots=1 << 12, max_delay_ms=0.3)
    limiter = SlidingWindowRateLimiter(storage, sw_cfg, MeterRegistry())

    # Warm the batcher's compile shapes + the cache path untimed.
    for _ in range(50):
        limiter.try_acquire("hot-key")

    t0 = time.perf_counter()
    for _ in range(3):
        limiter.try_acquire("rtt-probe-key")
    rtt_ms = (time.perf_counter() - t0) / 3 * 1000

    res = bench_threaded(
        limiter,
        keys_per_thread=lambda t: ["hot-key"],
        n_threads=10,
        requests_per_thread=10_000,
    )
    res["device_round_trip_ms"] = round(rtt_ms, 2)
    res["device"] = "cpu-in-process"
    res["note"] = ("same limiter/batcher code as sw_single_key_threaded, "
                   "zero-RTT attachment: the regime where the local cache "
                   "TTL (100 ms) >> storage round trip, as the reference "
                   "operates (BASELINE.md 80,192 req/s target)")
    storage.close()
    print(json.dumps(res))


if __name__ == "__main__":
    sys.exit(main())
