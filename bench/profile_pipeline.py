"""Profile the link-adaptive chunk-plan election on the live link
(VERDICT r3 #1 development harness — run from the repo root).

Reproduces the headline scenario (1M-key TB Zipf stream) and scenario 5
(weighted burst), printing per-pass phase breakdowns and the elected
plans, with the plan election togglable for A/B:

    python bench/profile_pipeline.py [--no-plan] [--n N_REQUESTS]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-plan", action="store_true")
    ap.add_argument("--n", type=int, default=1 << 24)
    ap.add_argument("--scenario", default="zipf",
                    choices=["zipf", "burst", "uniform10m"])
    ap.add_argument("--passes", type=int, default=2)
    args = ap.parse_args()

    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.bench.harness import uniform_stream, zipf_stream
    from ratelimiter_tpu.storage import TpuBatchedStorage

    rng = np.random.default_rng(42)
    if args.scenario == "zipf":
        num_keys, algo = 1_000_000, "tb"
        cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                              refill_rate=50.0)
        ids = zipf_stream(rng, num_keys, args.n)
        perms = None
        slots = num_keys * 2
    elif args.scenario == "burst":
        num_keys, algo = 1_000_000, "tb"
        cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                              refill_rate=100.0)
        ids = uniform_stream(rng, num_keys, args.n)
        perms = rng.integers(1, 101, size=args.n).astype(np.int64)
        slots = num_keys * 2
    else:
        num_keys, algo = 10_000_000, "sw"
        cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                              enable_local_cache=False)
        ids = uniform_stream(rng, num_keys, args.n)
        perms = None
        slots = int(num_keys * 1.25)

    from ratelimiter_tpu.ops.pallas.block_scatter import align_slots

    st = TpuBatchedStorage(num_slots=align_slots(max(slots, 1 << 16)))
    lid = st.register_limiter(algo, cfg)
    if not args.no_plan:
        prof = st.probe_link()
        print(f"link: {prof[0] / 1e6:.1f} MB/s up, "
              f"rtt {prof[1] * 1e3:.1f} ms", flush=True)

    for p in range(args.passes + 2):
        st.stream_stats = stats = []
        t0 = time.perf_counter()
        out = st.acquire_stream_ids(algo, lid, ids, perms)
        wall = time.perf_counter() - t0
        st.stream_stats = None
        agg = {
            "chunks": len(stats),
            "assign_s": round(sum(r.get("assign_s", 0) for r in stats), 3),
            "walk_s": round(max((r.get("walk_s", 0) for r in stats),
                                default=0), 3),
            "host_s": round(sum(r.get("host_s", 0) for r in stats), 3),
            "fetch_s": round(sum(r.get("fetch_s", 0) for r in stats), 3),
            "wire_mb": round(sum(r.get("wire_bytes", 0)
                                 for r in stats) / 1e6, 2),
        }
        print(f"pass {p}: wall {wall:.3f}s  "
              f"{args.n / wall / 1e6:.2f}M/s  {agg}", flush=True)
        print(f"  plans: {st._chunk_plans}", flush=True)
    print(json.dumps({"allowed": int(out.sum())}))
    st.close()


if __name__ == "__main__":
    main()
