"""Idle cost of the fleet NodeManager on the decision path.

The NodeManager (fleet/manager.py) probes every managed node on its
own cadence thread — one muxed ``probe_all`` control RPC per NODE per
tick (control.py:mux_handlers answers every shard in a single round
trip).  The ISSUE 16 contract is that an ENABLED-but-idle manager —
healthy nodes, no re-seed jobs in flight — costs <= 2% of the headline
TB-Zipf stream.  This gate keeps it that way: a future probe that
fans out per-shard RPCs, or an autopilot tick that polls receivers on
the hot path, blows the budget loudly here.

Measurement method (bench/orchestrator_overhead.py pattern):

- baseline and managed modes run INTERLEAVED, order rotated per round,
  so drift and cache warmth cancel;
- the GATED number is the **steady-state manager fraction**:
  ``tick`` is wrapped with a wall-clock accumulator and the gate
  bounds ``mean_tick_seconds * ticks_per_second`` — the CPU fraction
  the probe loop consumes at its configured cadence.  Deterministic
  where the end-to-end paired diff is noise-bound, and conservative:
  the probes run on their own thread, so a fully-overlapped tick
  still counts;
- the managed nodes are loopback ``ControlServer``s answering the
  REAL muxed ``probe_all`` op per shard — the wire + scheduling cost
  of the cross-host probe path without subprocess boots in the gate.

    JAX_PLATFORMS=cpu python bench/fleet_overhead.py \
        --n 262144 --assert-budget 0.02
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class TickMeter:
    """Wraps the manager's tick with a wall-clock accumulator."""

    def __init__(self, mgr):
        self.seconds = 0.0
        self.ticks = 0
        self._lock = threading.Lock()
        inner = mgr.tick

        def timed():
            t0 = time.perf_counter()
            try:
                return inner()
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.seconds += dt
                    self.ticks += 1

        mgr.tick = timed


def timed_pass(storage, lid, key_ids) -> float:
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        return time.perf_counter() - t0
    finally:
        gc.enable()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 18,
                        help="requests per stream pass")
    parser.add_argument("--keys", type=int, default=1 << 14)
    parser.add_argument("--rounds", type=int, default=5)
    parser.add_argument("--nodes", type=int, default=3,
                        help="managed loopback nodes")
    parser.add_argument("--shards-per-node", type=int, default=2)
    parser.add_argument("--num-slots", type=int, default=1 << 14)
    # Gate at the shipped cadence (ratelimiter.fleet.probe_interval_ms
    # defaults to 500): the muxed probe RPC costs ~1 ms of wall clock
    # per node under GIL contention with a saturated serving core, so
    # the budget math is cadence-bound, not RPC-bound.
    parser.add_argument("--probe-interval-ms", type=float, default=500.0)
    parser.add_argument("--assert-budget", type=float, default=None,
                        metavar="FRAC",
                        help="fail if the manager's steady-state probe "
                             "fraction exceeds this (e.g. 0.02)")
    args = parser.parse_args()

    # Same rationale as bench/orchestrator_overhead.py: the default
    # 5 ms GIL switch interval turns a ~100 us loopback RPC into
    # multi-ms scheduling stalls on a saturated core.
    sys.setswitchinterval(0.001)

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.fleet import NodeManager
    from ratelimiter_tpu.replication.control import (
        ControlServer,
        mux_handlers,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(42)
    key_ids = rng.integers(0, args.keys, size=args.n)
    cfg = RateLimitConfig(max_permits=1000, window_ms=1000,
                          refill_rate=500.0)

    storage = TpuBatchedStorage(num_slots=args.num_slots)
    lid = storage.register_limiter("tb", cfg)

    # Loopback nodes: each answers the real muxed probe_all from a
    # ControlServer — the per-node RPC unit the manager pays per tick.
    servers = []
    mgr = NodeManager(probe_interval_ms=args.probe_interval_ms,
                      probe_timeout_s=1.0)
    for i in range(args.nodes):
        per_shard = {
            q: {"probe": (lambda: {"available": True, "promoted": False})}
            for q in range(args.shards_per_node)
        }
        server = ControlServer(mux_handlers(per_shard)).start()
        servers.append(server)
        mgr.adopt(f"node-{i}", {
            "ready": True, "role": "primary",
            "control_port": server.port,
            "shards": args.shards_per_node, "version": "v1",
        })
    meter = TickMeter(mgr)
    mgr.start()

    for _ in range(2):
        storage.acquire_stream_ids("tb", lid, key_ids)  # warm shapes

    walls = {"off": [], "on": []}
    modes = ["off", "on"]
    for r in range(args.rounds):
        for mode in modes[r % 2:] + modes[:r % 2]:
            if mode == "on":
                if mgr._thread is None:
                    mgr.start()
                wall = timed_pass(storage, lid, key_ids)
            else:
                mgr.stop()
                wall = timed_pass(storage, lid, key_ids)
            walls[mode].append(wall)

    # Accumulate tick samples UNDER a saturated core: at the shipped
    # 500 ms cadence a single ~6 ms pass rarely overlaps a tick, so
    # keep the serving loop hot until enough ticks landed for a stable
    # mean (this is the contended cost the gate must bound).
    if mgr._thread is None:
        mgr.start()
    deadline = time.monotonic() + 20.0
    while meter.ticks < 8 and time.monotonic() < deadline:
        storage.acquire_stream_ids("tb", lid, key_ids)

    # Sanity: the manager actually probed, every node stayed live, and
    # no node was declared FAILED on a healthy loopback fleet.
    assert meter.ticks > 0, "manager never ticked during the bench"
    st = mgr.status()
    assert sorted(st["nodes"]) == sorted(
        f"node-{i}" for i in range(args.nodes)), st
    assert all(v["state"] == "READY" for v in st["nodes"].values()), st
    assert all(v["probe_fail_streak"] == 0
               for v in st["nodes"].values()), st

    best = {m: min(v) for m, v in walls.items()}
    ratios = sorted(walls["on"][r] / walls["off"][r]
                    for r in range(args.rounds))
    paired_pct = round(100.0 * (ratios[len(ratios) // 2] - 1.0), 2)
    mean_tick_s = meter.seconds / meter.ticks
    steady_frac = mean_tick_s * (1000.0 / args.probe_interval_ms)
    report = {
        "n_per_pass": args.n,
        "nodes": args.nodes,
        "shards_per_node": args.shards_per_node,
        "rounds": args.rounds,
        "probe_interval_ms": args.probe_interval_ms,
        "off_rps": round(args.n / best["off"]),
        "on_rps": round(args.n / best["on"]),
        "paired_overhead_pct": paired_pct,
        "mean_tick_us": round(1e6 * mean_tick_s, 1),
        "fleet_steady_pct": round(100.0 * steady_frac, 3),
        "ticks_during_bench": meter.ticks,
    }
    mgr.close(terminate=False)
    for server in servers:
        server.stop()
    storage.close()
    print(json.dumps(report, indent=2))
    if args.assert_budget is not None:
        budget_pct = 100.0 * args.assert_budget
        got = report["fleet_steady_pct"]
        if got > budget_pct:
            raise SystemExit(
                f"fleet manager idle-probe cost {got}% exceeds the "
                f"{budget_pct}% budget")
        print(f"fleet manager idle-probe cost {got}% within the "
              f"{budget_pct}% budget")


if __name__ == "__main__":
    main()
