"""Pallas A/B probe: what the kernels buy on this link (one flag state).

RATELIMITER_PALLAS is read at import time, so bench.py runs this script
twice — once with the flag on, once off — and records both outputs side
by side (VERDICT r2 #6: the Pallas axis must be falsifiable from the
artifacts).  The drive targets the path the Pallas solver actually
serves: micro-batcher-sized fused dispatches (<= 16K lanes) with
duplicate keys in-batch, where the threshold recurrence runs per
segment.  Larger stream dispatches use the relay/digest closed form or
the XLA solver and never touch Pallas.

Run from the repo root (subprocess of bench.py).
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.ops.pallas import block_scatter, solver
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), ".jax_cache"))

    storage = TpuBatchedStorage(num_slots=1 << 16)
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    _ = MeterRegistry()

    rng = np.random.default_rng(11)
    batch = 1 << 13  # micro-batcher bucket size; under the Pallas lane cap
    n_batches = 24
    # Zipf-ish duplicates so segments are real (the recurrence has work).
    ids = rng.integers(0, 2000, size=(n_batches + 4, batch)).astype(np.int64)
    perms = rng.integers(1, 5, size=(n_batches + 4, batch)).astype(np.int64)

    for i in range(4):  # warm compile + state
        storage.acquire_many_ids("tb", lid, ids[i], perms[i])
    t0 = time.perf_counter()
    for i in range(4, 4 + n_batches):
        storage.acquire_many_ids("tb", lid, ids[i], perms[i])
    wall = time.perf_counter() - t0
    out = {
        "pallas_flag": os.environ.get("RATELIMITER_PALLAS", "1"),
        "solver_live": bool(solver.settle()),
        "block_scatter_live": bool(block_scatter.settle()),
        "batch": batch,
        "n_batches": n_batches,
        "decisions": batch * n_batches,
        "wall_s": round(wall, 4),
        "decisions_per_sec": round(batch * n_batches / wall, 1),
        "note": ("synchronous per-batch round trips; on the dev tunnel the "
                 "RTT dominates, so the on/off delta bounds the kernel's "
                 "contribution on THIS link, not on local attachment"),
    }
    storage.close()
    print(json.dumps(out))


if __name__ == "__main__":
    sys.exit(main())
