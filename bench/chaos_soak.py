"""Chaos soak gate: run seeded conductor schedules against the full
in-process fleet and assert the invariant catalog stays clean.

Fast gate (verify.sh):

    python bench/chaos_soak.py --seeds 3 --assert-invariants

Long soak (RUN_SLOW=1 verify.sh):

    python bench/chaos_soak.py --seeds 8 --steps 48 --soak \
        --assert-invariants

On a violation the failing schedule is minimized and written as a
replayable artifact; the gate prints the artifact path so the failure
can be re-run exactly:

    python -m ratelimiter_tpu.chaos.replay --artifact <path>
"""

import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("RATELIMITER_RATE_PROBE", "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from ratelimiter_tpu.chaos.minimize import minimize  # noqa: E402
from ratelimiter_tpu.chaos.plan import FaultPlan  # noqa: E402
from ratelimiter_tpu.chaos.replay import dump_artifact  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=3,
                    help="number of seeded schedules to run")
    ap.add_argument("--base-seed", type=int, default=0,
                    help="first seed (schedules use base..base+seeds-1)")
    ap.add_argument("--steps", type=int, default=24,
                    help="conductor steps per schedule")
    ap.add_argument("--fault-rate", type=float, default=0.5,
                    help="per-step fault probability for the generator")
    ap.add_argument("--edge", choices=["direct", "tcp"], default="direct",
                    help="edge upstream topology (tcp = real proxy wire)")
    ap.add_argument("--soak", action="store_true",
                    help="long-soak shape: larger steps floor, both "
                         "edge topologies alternate across seeds")
    ap.add_argument("--assert-invariants", action="store_true",
                    help="exit non-zero on any invariant violation")
    ap.add_argument("--artifact-dir", default="/tmp",
                    help="where failing schedules are written")
    args = ap.parse_args()

    from ratelimiter_tpu.chaos.harness import run_plan

    steps = max(args.steps, 48) if args.soak else args.steps
    failures = []
    t0 = time.time()
    for i in range(args.seeds):
        seed = args.base_seed + i
        edge = args.edge
        if args.soak and i % 2 == 1:
            edge = "tcp" if edge == "direct" else "direct"
        plan = FaultPlan.generate(seed, steps=steps,
                                  fault_rate=args.fault_rate,
                                  topology={"edge": edge})
        t1 = time.time()
        report = run_plan(plan)
        dt = time.time() - t1
        v = report.get("violation")
        status = (f"VIOLATION [{v['invariant']}] step {v['step']}"
                  if v else "ok")
        print(f"seed {seed:>3} edge={edge:<6} "
              f"actions={len(plan.actions):>3} "
              f"decisions={report['decisions']:>5} "
              f"promotions={sum(report['promotions'])} "
              f"zombies_fenced={report['zombies_fenced']} "
              f"{dt:6.1f}s  {status}")
        if v is None:
            continue
        res = minimize(plan, max_runs=24)
        art = os.path.join(args.artifact_dir,
                           f"chaos_failure_seed{seed}.json")
        dump_artifact(art, res["plan"], res["violation"] or v,
                      minimized=res["reproduced"],
                      original_actions=res["reduced_from"])
        print(f"  minimized {res['reduced_from']} -> "
              f"{len(res['plan'].actions)} action(s) in {res['runs']} "
              f"runs; artifact: {art}")
        print(f"  replay: python -m ratelimiter_tpu.chaos.replay "
              f"--artifact {art}")
        failures.append({"seed": seed, "violation": v, "artifact": art})

    total = time.time() - t0
    print(f"\n{args.seeds} schedule(s), {len(failures)} violation(s), "
          f"{total:.1f}s total")
    print(json.dumps({"schedules": args.seeds, "steps": steps,
                      "violations": failures}, default=str))
    if failures and args.assert_invariants:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
