"""Isolate compile-time and runtime of the flat-step building blocks at
increasing mega-batch sizes on the real device.

Run: python bench/profile_compile.py [sizes...]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

S = 1 << 20


def timed_compile(name, fn, *args):
    t0 = time.perf_counter()
    c = jax.jit(fn).lower(*args).compile()
    tc = time.perf_counter() - t0
    t0 = time.perf_counter()
    out = c(*args)
    jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
    t1 = time.perf_counter() - t0
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = c(*args)
        jax.tree_util.tree_map(lambda a: a.block_until_ready(), out)
        times.append(time.perf_counter() - t0)
    print(f"  {name}: compile {tc:6.1f}s  run {min(times)*1000:7.1f} ms",
          flush=True)


def main():
    sizes = [int(x) for x in sys.argv[1:]] or [1 << 19, 1 << 20, 1 << 21]
    rng = np.random.default_rng(0)
    for B in sizes:
        print(f"B={B}", flush=True)
        slots = jnp.asarray(
            (rng.zipf(1.1, size=B).astype(np.int64) % S).astype(np.int32))
        iota = jnp.arange(B, dtype=jnp.int32)
        state = jnp.zeros((S, 2), dtype=jnp.int32)
        rows = jnp.zeros((B, 2), dtype=jnp.int32)
        mask = jnp.asarray(rng.random(B) < 0.5)

        timed_compile("sort2", lambda s, i: jax.lax.sort((s, i), num_keys=1,
                                                         is_stable=True),
                      slots, iota)
        timed_compile("cummax", lambda s: jax.lax.associative_scan(
            jnp.maximum, s), slots)
        timed_compile("gather", lambda st, s: st[s], state, slots)
        timed_compile("xla_scatter",
                      lambda st, s, m, r: st.at[jnp.where(m, s, S)].set(
                          r, mode="drop"),
                      state, slots, mask, rows)
        timed_compile("packbits", lambda m: jnp.packbits(m), mask)

        from ratelimiter_tpu.ops.pallas import block_scatter
        if block_scatter.supported((S, 2), B):
            srt = jnp.sort(slots)
            timed_compile("pallas_block_scatter",
                          lambda st, s, m, r: block_scatter.scatter_rows(
                              st, s, m, r),
                          state, srt, mask, rows)


if __name__ == "__main__":
    main()
