"""Characterize H2D upload cost over the axon tunnel: size scaling, API
variants, dtype, and concurrency.  Completion is forced by fetching an
8-byte reduction of the uploaded buffer.

Run: python bench/profile_upload.py
"""

from __future__ import annotations

import concurrent.futures as cf
import time

import jax
import jax.numpy as jnp
import numpy as np


def main():
    rng = np.random.default_rng(0)
    dev = jax.devices()[0]
    csum = {}

    def force(x):
        n = x.size * x.dtype.itemsize
        key = (x.shape, str(x.dtype))
        if key not in csum:
            csum[key] = jax.jit(lambda v: v.astype(jnp.int32).sum()).lower(
                jax.ShapeDtypeStruct(x.shape, x.dtype)).compile()
        return np.asarray(csum[key](x)), n

    # RTT baseline: resident array reduce+fetch
    res = jnp.zeros(1024, jnp.int32)
    force(res)
    t0 = time.perf_counter()
    for _ in range(5):
        force(res)
    rtt = (time.perf_counter() - t0) / 5
    print(f"rtt floor: {rtt*1000:.0f} ms", flush=True)

    def t_upload(name, make, n_rep=3):
        ts = []
        for _ in range(n_rep):
            arr = make()
            t0 = time.perf_counter()
            x = jnp.asarray(arr) if not isinstance(arr, jnp.ndarray) else arr
            _, nbytes = force(x)
            ts.append(time.perf_counter() - t0)
        t = sorted(ts)[len(ts) // 2] - rtt
        print(f"  {name}: {t*1000:7.0f} ms  "
              f"{nbytes/max(t,1e-9)/1e6:8.1f} MB/s", flush=True)

    for mb in (1, 4, 16):
        n = mb << 20
        print(f"upload {mb} MB:", flush=True)
        t_upload("asarray_i32",
                 lambda n=n: rng.integers(0, 1 << 20, n // 4).astype(np.int32))
        t_upload("device_put_i32",
                 lambda n=n: jax.device_put(
                     rng.integers(0, 1 << 20, n // 4).astype(np.int32), dev))
        t_upload("asarray_u8",
                 lambda n=n: rng.integers(0, 255, n).astype(np.uint8))
        t_upload("zeros_i32 (compressible?)",
                 lambda n=n: np.zeros(n // 4, dtype=np.int32))

    # concurrency: 4 parallel 4MB uploads
    print("4 x 4MB parallel uploads:", flush=True)
    arrs = [rng.integers(0, 1 << 20, 1 << 20).astype(np.int32)
            for _ in range(4)]
    t0 = time.perf_counter()
    with cf.ThreadPoolExecutor(4) as ex:
        handles = list(ex.map(lambda a: jnp.asarray(a), arrs))
    for h in handles:
        force(h)
    t = time.perf_counter() - t0
    print(f"  total {t*1000:.0f} ms -> {16/max(t,1e-9):.1f} MB/s aggregate",
          flush=True)


if __name__ == "__main__":
    main()
