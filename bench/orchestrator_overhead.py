"""Idle cost of the failover orchestrator on the decision path.

The orchestrator (replication/orchestrator.py) runs a probe loop on its
own cadence thread; the ISSUE 9 contract is that an ENABLED-but-idle
orchestrator — healthy shards, nothing suspect — costs <= 2% of the
headline sharded TB-Zipf stream.  Its tick is O(n_shards) attribute
checks plus one ``is_available`` device round-trip per shard, all off
the decision path, so the budget is generous; this gate exists to keep
it that way (a future probe that flushes the batcher or snapshots state
per tick would blow it loudly here).

Measurement method (bench/observability_overhead.py pattern):

- baseline and orchestrated modes run INTERLEAVED, order rotated per
  round, so drift and cache warmth cancel;
- the GATED number is the **steady-state orchestrator fraction**: the
  orchestrator's ``tick`` is wrapped with a wall-clock accumulator, and
  the gate bounds ``mean_tick_seconds * ticks_per_second`` — the CPU
  fraction the probe loop consumes at its configured cadence.  This is
  deterministic where the end-to-end paired diff is noise-bound on a
  small shared host, and errs conservative: the probes run on their own
  thread, so a fully-overlapped tick still counts;
- the paired per-round end-to-end ratio is also reported (unGATED).

    JAX_PLATFORMS=cpu python bench/orchestrator_overhead.py \
        --n 1048576 --assert-budget 0.02
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# The sharded topology needs virtual devices BEFORE jax initializes.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()


class TickMeter:
    """Wraps the orchestrator's tick with a wall-clock accumulator."""

    def __init__(self, orch):
        self.seconds = 0.0
        self.ticks = 0
        self._lock = threading.Lock()
        inner = orch.tick

        def timed():
            t0 = time.perf_counter()
            try:
                return inner()
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.seconds += dt
                    self.ticks += 1

        orch.tick = timed


def timed_pass(storage, lid, key_ids) -> float:
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        return time.perf_counter() - t0
    finally:
        gc.enable()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 20,
                        help="requests per stream pass")
    parser.add_argument("--keys", type=int, default=1 << 14)
    parser.add_argument("--rounds", type=int, default=5)
    parser.add_argument("--shards", type=int, default=4)
    parser.add_argument("--slots-per-shard", type=int, default=1 << 14)
    parser.add_argument("--probe-interval-ms", type=float, default=100.0)
    parser.add_argument("--probe-rpc", action="store_true",
                        help="route every liveness probe through a "
                             "loopback control-RPC round trip "
                             "(replication/control.py) — the cross-host "
                             "topology's probe path; the same steady-"
                             "state budget must hold")
    parser.add_argument("--assert-budget", type=float, default=None,
                        metavar="FRAC",
                        help="fail if the direct orchestrator fraction "
                             "of the orchestrated pass exceeds this "
                             "(e.g. 0.02)")
    args = parser.parse_args()

    # Thread wakeup latency dominates a loopback RPC on a saturated
    # core: the default 5 ms GIL switch interval turns a ~100 us round
    # trip into multi-ms scheduling stalls.  1 ms is the same setting
    # bench/local_latency_slo.py uses for the same reason.
    sys.setswitchinterval(0.001)

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh
    from ratelimiter_tpu.replication import (
        FailoverOrchestrator,
        OrchestratorConfig,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardFailoverRouter,
        ShardStandbySet,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(42)
    key_ids = rng.integers(0, args.keys, size=args.n)
    cfg = RateLimitConfig(max_permits=1000, window_ms=1000,
                          refill_rate=500.0)

    def build(orchestrated: bool):
        engine = ShardedDeviceEngine(
            slots_per_shard=args.slots_per_shard, table=LimiterTable(),
            mesh=make_mesh(n_devices=args.shards))
        storage = TpuBatchedStorage(engine=engine)
        lid = storage.register_limiter("tb", cfg)
        handle = None
        if orchestrated:
            def factory():
                return TpuBatchedStorage(num_slots=args.slots_per_shard)

            mesh_set = ShardStandbySet(args.shards, factory)
            repl = ShardedReplicator(
                ShardedReplicationLog(storage),
                mesh_set.in_process_sinks(),
                # The replication stream itself is gated separately
                # (bench/replication_overhead.py); park its cadence so
                # this gate isolates the ORCHESTRATOR's probes.
                interval_ms=3_600_000.0)
            router = ShardFailoverRouter(storage)
            probe = None
            rpc = None
            if args.probe_rpc:
                # The cross-host probe path: ONE control-RPC round trip
                # per node per tick against a loopback ControlServer
                # answering every shard's verdict from the router's
                # non-blocking health view (exactly the unit the remote
                # topology pays: the orchestrator probes a NODE's
                # control port, not each shard separately) — the wire +
                # scheduling cost without a second process in the gate.
                from ratelimiter_tpu.replication.control import (
                    ControlClient,
                    ControlServer,
                )

                def probe_all() -> dict:
                    return {"healthy": {
                        str(q): v != "failed"
                        for q, v in router.shard_health().items()}}

                server = ControlServer({"probe_all": probe_all}).start()
                client = ControlClient("127.0.0.1", server.port,
                                       timeout=1.0)
                rpc = (server, client)
                cache = {"at": -1e9, "verdicts": {}}

                def probe(q):
                    now = time.monotonic()
                    if (now - cache["at"]) * 1000.0 \
                            >= args.probe_interval_ms / 2.0:
                        cache["at"] = now
                        try:
                            cache["verdicts"] = client.call(
                                "probe_all").get("healthy", {})
                        except Exception:  # noqa: BLE001 — probe failure
                            cache["verdicts"] = {}
                    return bool(cache["verdicts"].get(str(q), False))
            orch = FailoverOrchestrator(
                router, mesh_set, repl, standby_factory=factory,
                probe=probe,
                config=OrchestratorConfig(
                    probe_interval_ms=args.probe_interval_ms))
            meter = TickMeter(orch)
            orch.start()
            handle = (orch, repl, mesh_set, router, meter, rpc)
        return storage, lid, handle

    base_storage, base_lid, _ = build(False)
    orch_storage, orch_lid, handle = build(True)
    orch, repl, mesh_set, router, meter, rpc = handle
    for s, l in ((base_storage, base_lid), (orch_storage, orch_lid)):
        for _ in range(2):
            s.acquire_stream_ids("tb", l, key_ids)  # warm shapes/plans

    walls = {"off": [], "on": []}
    tick_s = []
    modes = ["off", "on"]
    for r in range(args.rounds):
        for mode in modes[r % 2:] + modes[:r % 2]:
            if mode == "on":
                pre = meter.seconds
                wall = timed_pass(orch_storage, orch_lid, key_ids)
                tick_s.append(meter.seconds - pre)
            else:
                wall = timed_pass(base_storage, base_lid, key_ids)
            walls[mode].append(wall)

    # Sanity: the orchestrator actually probed during the measurement,
    # stayed idle (no false promotion on a healthy mesh), and the gauge
    # would read healthy.
    assert meter.ticks > 0, "orchestrator never ticked during the bench"
    st = orch.status()
    assert st["promotions"] == 0 and st["false_alarms"] == 0, st
    assert all(s["state"] == "MONITORING" for s in st["shards"].values())

    best = {m: min(v) for m, v in walls.items()}
    ratios = sorted(walls["on"][r] / walls["off"][r]
                    for r in range(args.rounds))
    paired_pct = round(100.0 * (ratios[len(ratios) // 2] - 1.0), 2)
    # Steady-state CPU fraction of the probe loop at its cadence.
    mean_tick_s = meter.seconds / meter.ticks
    steady_frac = mean_tick_s * (1000.0 / args.probe_interval_ms)
    report = {
        "n_per_pass": args.n,
        "shards": args.shards,
        "rounds": args.rounds,
        "probe_interval_ms": args.probe_interval_ms,
        "probe_path": "control-rpc" if args.probe_rpc else "in-process",
        "off_rps": round(args.n / best["off"]),
        "on_rps": round(args.n / best["on"]),
        "paired_overhead_pct": paired_pct,
        "mean_tick_us": round(1e6 * mean_tick_s, 1),
        "orchestrator_steady_pct": round(100.0 * steady_frac, 3),
        "ticks_during_bench": meter.ticks,
        "tick_s_in_passes": round(sum(tick_s), 4),
    }
    orch.close()
    repl.close()
    router.close()
    mesh_set.close()
    base_storage.close()
    if rpc is not None:
        rpc[1].close()
        rpc[0].stop()
    print(json.dumps(report, indent=2))
    if args.assert_budget is not None:
        budget_pct = 100.0 * args.assert_budget
        got = report["orchestrator_steady_pct"]
        if got > budget_pct:
            raise SystemExit(
                f"orchestrator idle-probe cost {got}% exceeds the "
                f"{budget_pct}% budget")
        print(f"orchestrator idle-probe cost {got}% within the "
              f"{budget_pct}% budget")


if __name__ == "__main__":
    main()
