"""Hot-path cost of replication: decision rate with the journal off vs on.

The replication design promise (ISSUE: "asynchronously off the decision
path") cashes out here: with replication enabled the hot path pays ONE
boolean scatter per dispatched chunk (SlotJournal.mark) while the
replicator thread cuts/ships epochs concurrently.  This bench measures
the streaming decision rate (acquire_stream_ids, the hyperscale path)
three ways — journal detached, journal attached but idle, and journal
attached with the async replicator shipping to an in-process standby —
and reports the overhead percentage.  Acceptance: <= 10% with
replication on.

    JAX_PLATFORMS=cpu python bench/replication_overhead.py --n 262144
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_passes(storage, lid, key_ids, passes: int) -> float:
    """Best decisions/s over ``passes`` timed stream passes."""
    best = 0.0
    for _ in range(passes):
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        dt = time.perf_counter() - t0
        best = max(best, len(key_ids) / dt)
    return best


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 18,
                        help="requests per stream pass")
    parser.add_argument("--keys", type=int, default=1 << 14,
                        help="distinct tenant keys")
    parser.add_argument("--passes", type=int, default=3)
    parser.add_argument("--num-slots", type=int, default=1 << 16)
    parser.add_argument("--interval-ms", type=float, default=50.0,
                        help="replicator ship interval")
    args = parser.parse_args()

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        StandbyReceiver,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(42)
    key_ids = rng.integers(0, args.keys, size=args.n)
    storage = TpuBatchedStorage(num_slots=args.num_slots)
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=1000, window_ms=1000, refill_rate=500.0))

    storage.acquire_stream_ids("tb", lid, key_ids)  # compile + warm index

    off_rps = run_passes(storage, lid, key_ids, args.passes)

    log = ReplicationLog(storage)
    journal_rps = run_passes(storage, lid, key_ids, args.passes)

    standby = TpuBatchedStorage(num_slots=args.num_slots)
    repl = Replicator(log, InProcessSink(StandbyReceiver(standby)),
                      interval_ms=args.interval_ms).start()
    on_rps = run_passes(storage, lid, key_ids, args.passes)
    repl.stop(final_ship=True)

    report = {
        "n_per_pass": args.n,
        "distinct_keys": args.keys,
        "off_rps": round(off_rps),
        "journal_only_rps": round(journal_rps),
        "replicating_rps": round(on_rps),
        "journal_overhead_pct": round(100 * (1 - journal_rps / off_rps), 2),
        "replication_overhead_pct": round(100 * (1 - on_rps / off_rps), 2),
        "frames_shipped": repl.frames_shipped,
        "bytes_shipped": repl.bytes_shipped,
        "epoch": log.epoch,
    }
    repl.close()
    storage.close()
    standby.close()
    print(json.dumps(report, indent=2))
    if report["replication_overhead_pct"] > 10.0:
        raise SystemExit(
            f"replication overhead {report['replication_overhead_pct']}% "
            "exceeds the 10% budget")


if __name__ == "__main__":
    main()
