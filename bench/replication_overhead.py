"""Hot-path cost of replication: decision rate with the journal off vs on.

The replication design promise ("asynchronously off the decision path")
cashes out here.  The decision path pays only the dirty-slot JOURNAL;
everything else (epoch cuts, encode, ship, standby apply) runs on the
replicator thread.  Two journal backends exist (engine/state.py):

- ``host``   — the original numpy boolean scatter per dispatch;
- ``device`` — the touched-slot bitmap lives on the device and is
  updated by an async scatter over the dispatch's own uploaded lanes
  (the PR 6 delta-extraction pass; elected vs host per device).

Measurement method: the three journal modes (off / host / device) run
INTERLEAVED — one pass each per round, best-of across rounds — so drift
and cache warmth cancel instead of biasing whichever mode ran last
(noise on a shared host is one-sided: stray work slows a pass, nothing
speeds one up, so best-of is the stable estimator).
Each journaled pass includes a journal sync inside the timed window, so
the device journal's async marks are charged to it, not to the next
mode.  The full replicating pipeline (async replicator + in-process
standby) is measured as its own phase; note that on a small host this
number co-schedules BOTH ends of the link plus the cut work on the
primary's cores — in production the standby is another machine — so the
gated budget applies to the journal (decision-path) overhead of the
journal the ELECTION chose for this device (the serving configuration:
the device bitmap where its async pass wins — real accelerators — and
the host scatter where it doesn't, e.g. a 1-core CPU backend where
"device" work lands on the same core):

    --assert-budget 0.02   # elected-journal overhead must stay <= 2%

``--sharded N`` measures the same ladder on an N-shard CPU-mesh engine
with per-shard replication (replication/sharded.py): per-shard epoch
streams into an in-process standby mesh.

    JAX_PLATFORMS=cpu python bench/replication_overhead.py --n 1048576 \
        --assert-budget 0.02
"""

from __future__ import annotations

import argparse
import json
import os

import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class TimedJournal:
    """Wraps a journal and accumulates the wall seconds its mark surface
    spends ON the decision path — the exact quantity the <2% budget
    bounds.  (The end-to-end pass diff also exists in the report, but on
    a small shared host its noise floor exceeds the budget itself; the
    direct measurement is deterministic.)"""

    def __init__(self, inner):
        self._inner = inner
        self.seconds = 0.0

    def _timed(self, name, *args, **kw):
        t0 = time.perf_counter()
        try:
            return getattr(self._inner, name)(*args, **kw)
        finally:
            self.seconds += time.perf_counter() - t0

    def mark(self, *a, **kw):
        return self._timed("mark", *a, **kw)

    def mark_words(self, *a, **kw):
        return self._timed("mark_words", *a, **kw)

    def mark_matrix(self, *a, **kw):
        return self._timed("mark_matrix", *a, **kw)

    def mark_words_matrix(self, *a, **kw):
        return self._timed("mark_words_matrix", *a, **kw)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def timed_pass(storage, lid, key_ids, journal) -> float:
    """One timed stream pass; journaled passes sync the journal inside
    the window so async device marks are charged here.  GC is collected
    before and disabled during the window so a collection triggered by
    one mode's garbage doesn't land in another mode's timing."""
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        if journal is not None:
            journal.pending()  # forces any in-flight marks to completion
        return len(key_ids) / (time.perf_counter() - t0)
    finally:
        gc.enable()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 22,
                        help="requests per stream pass (long passes "
                             "average scheduler noise; short ones gate "
                             "flakily)")
    parser.add_argument("--keys", type=int, default=1 << 14,
                        help="distinct tenant keys")
    parser.add_argument("--rounds", type=int, default=9,
                        help="interleaved off/host/device rounds "
                             "(mean-of-top-third estimator)")
    parser.add_argument("--repl-passes", type=int, default=3,
                        help="passes for the full replicating phase")
    parser.add_argument("--num-slots", type=int, default=1 << 16)
    parser.add_argument("--interval-ms", type=float, default=200.0,
                        help="replicator ship interval")
    parser.add_argument("--sharded", type=int, default=0, metavar="N",
                        help="measure the N-shard engine + per-shard "
                             "replication instead of the flat one")
    parser.add_argument("--assert-budget", type=float, default=None,
                        metavar="FRAC",
                        help="fail if the ELECTED journal's overhead "
                             "exceeds this fraction (e.g. 0.02)")
    args = parser.parse_args()

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import DeviceSlotJournal, SlotJournal
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(42)
    key_ids = rng.integers(0, args.keys, size=args.n)

    if args.sharded:
        from ratelimiter_tpu.engine.state import LimiterTable
        from ratelimiter_tpu.parallel import ShardedDeviceEngine, make_mesh

        sps = args.num_slots // args.sharded
        engine = ShardedDeviceEngine(
            slots_per_shard=sps, table=LimiterTable(),
            mesh=make_mesh(n_devices=args.sharded))
        storage = TpuBatchedStorage(engine=engine)
    else:
        storage = TpuBatchedStorage(num_slots=args.num_slots)
    lid = storage.register_limiter("tb", RateLimitConfig(
        max_permits=1000, window_ms=1000, refill_rate=500.0))

    # Warm: compile shapes, settle the index, elect chunk plans.
    for _ in range(2):
        storage.acquire_stream_ids("tb", lid, key_ids)

    num_slots = storage.engine.num_slots
    host_j = TimedJournal(SlotJournal(num_slots))
    dev_j = TimedJournal(DeviceSlotJournal(num_slots))
    modes = [("off", None), ("host", host_j), ("device", dev_j)]
    rps = {m: [] for m, _ in modes}
    pass_s = {m: 0.0 for m, _ in modes}
    for r in range(args.rounds):
        # Rotate the order each round so allocator/cache state left by
        # one mode doesn't systematically tax the same successor.
        for mode, journal in modes[r % 3:] + modes[:r % 3]:
            storage.engine.journal = journal
            got = timed_pass(storage, lid, key_ids, journal)
            rps[mode].append(got)
            pass_s[mode] += args.n / got
    storage.engine.journal = None
    # Direct decision-path fraction: seconds spent inside the journal's
    # mark surface over the journaled passes' total wall.
    direct_pct = {
        "host": round(100 * host_j.seconds / pass_s["host"], 3),
        "device": round(100 * dev_j.seconds / pass_s["device"], 3),
    }
    # Estimators.  Rates: best-of per mode (one-sided noise).  The GATED
    # overheads are PAIRED per round — each round's journaled pass is
    # compared to the SAME round's off pass, and the median ratio wins —
    # so slow drift (frequency scaling, cache pressure) cancels instead
    # of landing on whichever mode drew the unlucky rounds.
    med = {m: max(v) for m, v in rps.items()}

    def paired_overhead_pct(mode: str) -> float:
        ratios = sorted(rps[mode][r] / rps["off"][r]
                        for r in range(args.rounds))
        return round(100 * (1 - ratios[len(ratios) // 2]), 2)

    # Full pipeline: async replicator into an in-process standby (mesh).
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        ShardedReplicationLog,
        ShardedReplicator,
        ShardStandbySet,
        StandbyReceiver,
    )

    if args.sharded:
        log = ShardedReplicationLog(storage)
        mesh_set = ShardStandbySet(
            args.sharded, lambda: TpuBatchedStorage(num_slots=sps))
        repl = ShardedReplicator(log, mesh_set.in_process_sinks(),
                                 interval_ms=args.interval_ms).start()
    else:
        log = ReplicationLog(storage)
        standby = TpuBatchedStorage(num_slots=args.num_slots)
        repl = Replicator(log, InProcessSink(StandbyReceiver(standby)),
                          interval_ms=args.interval_ms).start()
    repl_rps = max(
        timed_pass(storage, lid, key_ids, log.journal)
        for _ in range(args.repl_passes))
    repl.stop(final_ship=True)

    def overhead(on: float) -> float:
        return round(100 * (1 - on / med["off"]), 2)

    elected = log.journal_kind  # the journal the election chose here
    report = {
        "mode": f"sharded-{args.sharded}" if args.sharded else "flat",
        "n_per_pass": args.n,
        "distinct_keys": args.keys,
        "rounds": args.rounds,
        "elected_journal": elected,
        "off_rps": round(med["off"]),
        "host_journal_rps": round(med["host"]),
        "device_journal_rps": round(med["device"]),
        "replicating_rps": round(repl_rps),
        # End-to-end paired pass diffs (noisy on a shared host) ...
        "host_journal_overhead_pct": paired_overhead_pct("host"),
        "device_journal_overhead_pct": paired_overhead_pct("device"),
        # ... and the DIRECT decision-path fraction (deterministic; the
        # seconds the pass actually spent inside the mark surface).
        "host_journal_markpath_pct": direct_pct["host"],
        "device_journal_markpath_pct": direct_pct["device"],
        "elected_journal_markpath_pct": direct_pct[elected],
        "replicating_overhead_pct": overhead(repl_rps),
        "frames_shipped": repl.frames_shipped,
        "bytes_shipped": repl.bytes_shipped,
        "epoch": (max(log.epochs) if args.sharded else log.epoch),
    }
    repl.close()
    storage.close()
    if args.sharded:
        mesh_set.close()
    else:
        standby.close()
    print(json.dumps(report, indent=2))
    if args.assert_budget is not None:
        budget_pct = 100.0 * args.assert_budget
        got = report["elected_journal_markpath_pct"]
        if got > budget_pct:
            raise SystemExit(
                f"elected ({elected}) journal decision-path cost {got}% "
                f"exceeds the {budget_pct}% budget")
        print(f"elected ({elected}) journal decision-path cost {got}% "
              f"within the {budget_pct}% budget")


if __name__ == "__main__":
    main()
