"""Round-5 stream profiling: where do headline / scenario-3 passes spend?

Mirrors bench.py's scenario 2 (TB 1M Zipf) and scenario 3 (SW 10M
uniform) shapes, runs the warmup/plan-settling discipline, then prints
per-chunk stream_stats records with the r5 sub-phase timers
(rebuild_s / dispatch_s) so host_s stops being a mystery number.

Usage:  python bench/profile_stream_r5.py [headline|sc3|both] [reps]
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main() -> None:
    which = sys.argv[1] if len(sys.argv) > 1 else "both"
    reps = int(sys.argv[2]) if len(sys.argv) > 2 else 2

    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.algorithms import (
        SlidingWindowRateLimiter,
        TokenBucketRateLimiter,
    )
    from ratelimiter_tpu.bench.harness import uniform_stream, zipf_stream
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.ops.pallas.block_scatter import align_slots
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.link import measure_link

    up_bps, rtt_s, down_bps = measure_link()
    print(f"link: up {up_bps / (1 << 20):.1f} MB/s rtt {rtt_s * 1e3:.0f} ms "
          f"down {down_bps / (1 << 20):.1f} MB/s", flush=True)

    rng = np.random.default_rng(42)
    B, K = 1 << 19, 8
    n = B * K * 4  # 16.7M, bench parity

    def run(name, storage, limiter, key_ids, permits=None):
        nn = len(key_ids)
        storage.set_link_profile(up_bps, rtt_s, down_bps)
        print(f"== {name}: warmup ==", flush=True)
        for i in range(4):
            t0 = time.perf_counter()
            limiter.try_acquire_stream_ids(key_ids, permits, batch=B,
                                           subbatches=K)
            print(f"  warm {i}: {time.perf_counter() - t0:.3f} s "
                  f"plans={storage._chunk_plans}", flush=True)
        for r in range(reps):
            storage.stream_stats = stats = []
            t0 = time.perf_counter()
            limiter.try_acquire_stream_ids(key_ids, permits, batch=B,
                                           subbatches=K)
            wall = time.perf_counter() - t0
            storage.stream_stats = None
            print(f"-- {name} pass {r}: wall {wall:.3f} s "
                  f"({nn / wall / 1e6:.2f} M/s)", flush=True)
            for rec in stats:
                print("   " + json.dumps(rec), flush=True)

    if which in ("headline", "both"):
        storage = TpuBatchedStorage(num_slots=align_slots(2_000_000))
        tb = TokenBucketRateLimiter(
            storage,
            RateLimitConfig(max_permits=100, window_ms=60_000,
                            refill_rate=50.0),
            MeterRegistry())
        run("headline", storage, tb, zipf_stream(rng, 1_000_000, n))
        storage.close()

    if which in ("burst",):
        storage = TpuBatchedStorage(num_slots=align_slots(2_000_000))
        tb = TokenBucketRateLimiter(
            storage,
            RateLimitConfig(max_permits=100, window_ms=60_000,
                            refill_rate=100.0),
            MeterRegistry())
        n5 = B * K * 3
        perms = rng.integers(1, 101, size=n5).astype(np.int64)
        run("burst", storage, tb,
            uniform_stream(rng, 1_000_000, n5), perms)
        storage.close()

    if which in ("strs",):
        storage = TpuBatchedStorage(num_slots=align_slots(2_000_000))
        tb = TokenBucketRateLimiter(
            storage,
            RateLimitConfig(max_permits=100, window_ms=60_000,
                            refill_rate=50.0),
            MeterRegistry())
        storage.set_link_profile(up_bps, rtt_s, down_bps)
        ids = zipf_stream(rng, 1_000_000, 2_000_000)
        keys = [f"k{i}" for i in ids]
        tb.try_acquire_many(keys, None)  # warm shapes
        for i in range(3):
            storage.stream_stats = stats = []
            t0 = time.perf_counter()
            tb.try_acquire_many(keys, None)
            wall = time.perf_counter() - t0
            storage.stream_stats = None
            print(f"  strs pass {i}: {len(keys) / wall / 1e6:.2f} M/s "
                  f"(wall {wall:.3f} s)", flush=True)
            for rec in stats:
                print("   " + json.dumps(rec), flush=True)
        storage.close()

    if which in ("sc3", "both"):
        storage = TpuBatchedStorage(num_slots=align_slots(12_500_000))
        sw = SlidingWindowRateLimiter(
            storage,
            RateLimitConfig(max_permits=100, window_ms=60_000,
                            enable_local_cache=False),
            MeterRegistry())
        run("sc3", storage, sw, uniform_stream(rng, 10_000_000, n))
        storage.close()


if __name__ == "__main__":
    main()
