"""Steady-state cost of the adaptive policy control plane.

The ISSUE 15 contract (the PR 9/13 gate idiom): an ENABLED controller
over a converged fleet — every tenant healthy, no actuations — costs
<= 2% of steady-state CPU at its configured cadence, including the
generation checks the lease path pays per grant/renewal.

Measurement (bench/orchestrator_overhead.py pattern): the GATED number
is the **direct steady-state fraction** — mean wall seconds of a
controller ``tick()`` over a realistically-populated telemetry plane
(``--tenants`` tenants tracked, fed by a real device stream pass)
times the tick rate, plus the per-grant generation check
(``LimiterTable.row_generation`` + ``policy_info``) at a pessimistic
grant rate.  This is deterministic where an end-to-end paired diff is
noise-bound on a small shared host, and errs conservative: the ticks
run on their own thread in production, so a fully-overlapped tick
still counts.  The paired end-to-end ratio is also reported (unGATED).

    JAX_PLATFORMS=cpu python bench/control_overhead.py \
        --assert-budget 0.02

``--fleet`` swaps the single-storage controller for the ISSUE 17
fleet plane: N member nodes each serving ``controller_handlers`` over
a REAL control-RPC socket, one elected ``FleetControlPlane`` leader,
and the gated tick is the whole fleet cadence — election maintenance
(majority seat renewal), the fleet-summed signals sweep, and the
controller's AIMD pass — at the configured interval.  The per-grant
generation check is unchanged in fleet mode (nodes check their own
local table), so the same pessimistic grant-rate term applies.

    JAX_PLATFORMS=cpu python bench/control_overhead.py \
        --fleet --assert-budget 0.02
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def timed_pass(storage, lid, key_ids) -> float:
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        return time.perf_counter() - t0
    finally:
        gc.enable()


def fleet_main(args) -> None:
    """Fleet-mode arm: the controller ticks over an elected
    FleetControlPlane whose members are real control-RPC sockets."""
    import numpy as np

    from ratelimiter_tpu.control import (
        AdaptivePolicyController,
        ControlConfig,
        ControllerElection,
        FleetControlPlane,
    )
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.replication.control import (
        ControlClient,
        ControlServer,
        controller_handlers,
    )
    from ratelimiter_tpu.replication.remote import RemoteBackend
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    registry = MeterRegistry()
    cfg = RateLimitConfig(max_permits=1000, window_ms=60_000,
                          refill_rate=100.0)
    storages, servers, members = [], [], {}
    lids = None
    for i in range(args.fleet_nodes):
        st = TpuBatchedStorage(num_slots=1 << 14,
                               table_capacity=args.tenants + 8)
        node_lids = [st.register_limiter("tb", cfg)
                     for _ in range(args.tenants)]
        if lids is None:
            lids = node_lids
        assert node_lids == lids, "members must register identically"
        # Populate every node's telemetry plane: the fleet signals
        # sweep serializes O(tenants) rows per member per tick.
        for lid in node_lids:
            st.acquire_many_ids("tb", lid,
                                np.arange(64, dtype=np.int64),
                                np.ones(64, dtype=np.int64))
        srv = ControlServer(controller_handlers(st)).start()
        members[f"n{i}"] = RemoteBackend(
            ControlClient("127.0.0.1", srv.port, timeout=5.0),
            label=f"n{i}")
        storages.append(st)
        servers.append(srv)

    plane = FleetControlPlane(
        "ctrl-bench", members,
        limiters={lid: ("tb", cfg) for lid in lids})
    election = ControllerElection([plane], registry=registry)
    election.tick()
    assert plane.is_leader, "bench plane failed to elect"
    controller = AdaptivePolicyController(
        plane, ControlConfig(interval_ms=args.interval_ms),
        registry=registry)
    election.tick()
    controller.tick()  # warm (adopts every lid fleet-wide)

    # -- gated: direct steady-state fraction (whole fleet cadence) ---------
    t0 = time.perf_counter()
    for _ in range(args.ticks):
        election.tick()     # majority seat renewal
        controller.tick()   # fleet signals sweep + AIMD pass
    tick_s = (time.perf_counter() - t0) / args.ticks

    # Per-grant generation check: node-LOCAL in fleet mode too.
    table = storages[0].table
    reps = 20000
    t0 = time.perf_counter()
    for i in range(reps):
        table.row_generation(lids[i % len(lids)])
    gen_check_s = (time.perf_counter() - t0) / reps

    ticks_per_s = 1000.0 / max(args.interval_ms, 1.0)
    fraction = tick_s * ticks_per_s + gen_check_s * args.grants_per_s

    report = {
        "mode": "fleet",
        "nodes": args.fleet_nodes,
        "tenants": args.tenants,
        "leader": plane.node,
        "epoch": plane.epoch,
        "fleet_tick_us": round(tick_s * 1e6, 1),
        "gen_check_us": round(gen_check_s * 1e6, 3),
        "ticks_per_s": ticks_per_s,
        "grants_per_s": args.grants_per_s,
        "steady_state_fraction": round(fraction, 6),
        "adjustments": controller.adjustments_total,
        "rpc_requests_served": sum(s.requests_served for s in servers),
    }
    print(json.dumps(report, indent=2))
    controller.close()
    election.close()
    plane.close()
    for srv in servers:
        srv.stop()
    for st in storages:
        st.close()

    if args.assert_budget is not None \
            and fraction > args.assert_budget:
        print(f"ASSERTION FAILED: fleet controller steady-state fraction "
              f"{fraction:.4f} > budget {args.assert_budget}",
              file=sys.stderr)
        sys.exit(1)


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 19,
                        help="requests per stream pass")
    parser.add_argument("--keys", type=int, default=1 << 13)
    parser.add_argument("--rounds", type=int, default=3)
    parser.add_argument("--tenants", type=int, default=64)
    parser.add_argument("--interval-ms", type=float, default=1000.0)
    parser.add_argument("--ticks", type=int, default=200,
                        help="tick() calls to average")
    parser.add_argument("--grants-per-s", type=float, default=1000.0,
                        help="pessimistic lease grant/renewal rate for "
                             "the generation-check term")
    parser.add_argument("--assert-budget", type=float, default=None,
                        metavar="FRAC")
    parser.add_argument("--fleet", action="store_true",
                        help="measure the ISSUE 17 fleet plane instead: "
                             "elected leader over real control-RPC "
                             "member sockets")
    parser.add_argument("--fleet-nodes", type=int, default=2)
    args = parser.parse_args()

    if args.fleet:
        fleet_main(args)
        return

    import numpy as np

    from ratelimiter_tpu.control import (
        AdaptivePolicyController,
        ControlConfig,
    )
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    registry = MeterRegistry()
    st = TpuBatchedStorage(num_slots=1 << 16, meter_registry=registry,
                           table_capacity=args.tenants + 8)
    lids = [st.register_limiter(
        "tb", RateLimitConfig(max_permits=1000, window_ms=60_000,
                              refill_rate=100.0))
        for _ in range(args.tenants)]
    rng = np.random.default_rng(7)
    key_ids = rng.zipf(1.1, size=args.n).astype(np.int64) % args.keys

    # Populate the telemetry plane: every tenant tracked (the tick's
    # all_signals sweep is O(tenants)), via real dispatch accounting.
    for lid in lids:
        st.acquire_many_ids("tb", lid,
                            np.arange(64, dtype=np.int64),
                            np.ones(64, dtype=np.int64))

    controller = AdaptivePolicyController(
        st, ControlConfig(interval_ms=args.interval_ms),
        registry=registry)
    controller.tick()  # warm (adopts every lid)

    # -- gated: direct steady-state fraction -------------------------------
    t0 = time.perf_counter()
    for _ in range(args.ticks):
        controller.tick()
    tick_s = (time.perf_counter() - t0) / args.ticks

    table = st.table
    reps = 20000
    t0 = time.perf_counter()
    for i in range(reps):
        table.row_generation(lids[i % len(lids)])
    gen_check_s = (time.perf_counter() - t0) / reps

    ticks_per_s = 1000.0 / max(args.interval_ms, 1.0)
    fraction = tick_s * ticks_per_s + gen_check_s * args.grants_per_s

    # -- unGATED: paired end-to-end ratio ----------------------------------
    timed_pass(st, lids[0], key_ids)  # warm compile
    base, ctl = [], []
    for r in range(args.rounds):
        order = (("base", "ctl") if r % 2 == 0 else ("ctl", "base"))
        for mode in order:
            if mode == "ctl":
                controller.start()
                ctl.append(timed_pass(st, lids[0], key_ids))
                controller.stop()
            else:
                base.append(timed_pass(st, lids[0], key_ids))

    report = {
        "tick_us": round(tick_s * 1e6, 1),
        "gen_check_us": round(gen_check_s * 1e6, 3),
        "ticks_per_s": ticks_per_s,
        "grants_per_s": args.grants_per_s,
        "steady_state_fraction": round(fraction, 6),
        "tenants": args.tenants,
        "adjustments": controller.adjustments_total,
        "paired_base_s": [round(x, 4) for x in base],
        "paired_ctl_s": [round(x, 4) for x in ctl],
        "paired_ratio": round(
            (sum(ctl) / len(ctl)) / (sum(base) / len(base)), 4),
    }
    print(json.dumps(report, indent=2))
    controller.close()
    st.close()

    if args.assert_budget is not None \
            and fraction > args.assert_budget:
        print(f"ASSERTION FAILED: controller steady-state fraction "
              f"{fraction:.4f} > budget {args.assert_budget}",
              file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
