"""Sidecar loopback benchmark (VERDICT #6 — production ingress).

The decision sidecar (service/sidecar.py) is the framework's
many-clients/one-authority ingress: non-Python services stream binary
decision requests over TCP and every connection funnels into the shared
micro-batcher.  Until r7 it had correctness tests only — no recorded
number for what the ingress machinery sustains.  This bench runs the
production topology in miniature on loopback TCP:

    N pipelining clients -> sidecar server -> shared micro-batcher
                         -> device engine (CPU in-process here)

Each client pipelines frames in batches (the protocol's intended use —
one syscall per direction per batch, like Redis pipelining), so the
measurement covers frame parse, per-request submit, batcher coalescing
across ALL clients, device dispatch, and response framing.  Emits
decisions/s plus per-batch round-trip percentiles (p50/p99) into ONE
JSON line; bench.py records it in BENCH_DETAIL as ``sidecar_loopback``.

Run with cwd=repo root:  python bench/sidecar_loopback.py
Env: BENCH_SCALE=small shrinks the request count (CI).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_CLIENTS = 8
PIPELINE = 64          # frames per pipelined batch (one syscall each way)


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    small = os.environ.get("BENCH_SCALE", "small") == "small"
    reps = 40 if small else 200

    storage = TpuBatchedStorage(num_slots=1 << 14, max_delay_ms=0.3,
                                max_inflight=4)
    server = SidecarServer(storage, host="127.0.0.1").start()
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1000, window_ms=60_000, refill_rate=500.0))
        storage.warm_micro_shapes()

        lat_lock = threading.Lock()
        batch_lat_us: list = []
        allowed_total = [0]
        barrier = threading.Barrier(N_CLIENTS + 1)

        def client_loop(t: int) -> None:
            cli = SidecarClient("127.0.0.1", server.port)
            try:
                keys0 = [f"c{t}-w{i}" for i in range(PIPELINE)]
                cli.acquire_batch(lid, keys0)  # warm the path
                # Synchronized warm rounds: concurrent clients coalesce
                # into batch shapes a lone client never produces, and
                # their XLA compiles must fire before the timed region.
                barrier.wait()
                for _ in range(3):
                    cli.acquire_batch(lid, keys0)
                barrier.wait()
                local_lat, local_allowed = [], 0
                for r in range(reps):
                    keys = [f"c{t}-k{(r * PIPELINE + i) % 512}"
                            for i in range(PIPELINE)]
                    t0 = time.perf_counter()
                    res = cli.acquire_batch(lid, keys)
                    local_lat.append((time.perf_counter() - t0) * 1e6)
                    local_allowed += sum(1 for _, a, _ in res if a)
                with lat_lock:
                    batch_lat_us.extend(local_lat)
                    allowed_total[0] += local_allowed
            finally:
                cli.close()

        threads = [threading.Thread(target=client_loop, args=(t,),
                                    daemon=True)
                   for t in range(N_CLIENTS)]
        for th in threads:
            th.start()
        barrier.wait()   # start of the synchronized warm rounds
        barrier.wait()   # warm done: timed region begins
        t_start = time.perf_counter()
        for th in threads:
            th.join()
        wall = time.perf_counter() - t_start

        n = N_CLIENTS * reps * PIPELINE
        lat = np.asarray(batch_lat_us)
        out = {
            "bench": "sidecar_loopback",
            "clients": N_CLIENTS,
            "pipeline_depth": PIPELINE,
            "decisions": n,
            "wall_s": round(wall, 4),
            "decisions_per_sec": round(n / wall, 1),
            "allowed": allowed_total[0],
            "batch_latency": {
                "p50_us": round(float(np.percentile(lat, 50)), 1),
                "p99_us": round(float(np.percentile(lat, 99)), 1),
                "max_us": round(float(lat.max()), 1),
                "n_samples": int(len(lat)),
            },
            # Amortized per-request figure: a pipelined batch of
            # PIPELINE frames shares one round trip.
            "per_request_p99_us": round(
                float(np.percentile(lat, 99)) / PIPELINE, 2),
            "note": ("loopback TCP, CPU device in-process: measures the "
                     "ingress machinery (framing + batcher coalescing "
                     "across clients), not a TPU"),
        }
        print(json.dumps(out))
    finally:
        server.stop()
        storage.close()


if __name__ == "__main__":
    main()
