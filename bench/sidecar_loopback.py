"""Sidecar loopback benchmark (VERDICT #6 — production ingress).

The decision sidecar (service/sidecar.py) is the framework's
many-clients/one-authority ingress: non-Python services stream binary
decision requests over TCP and every connection funnels into the shared
micro-batcher.  This bench runs the production topology in miniature on
loopback TCP:

    N pipelining clients -> sidecar server -> shared micro-batcher
                         -> device engine (CPU in-process here)

Each client pipelines frames in batches (the protocol's intended use —
one syscall per direction per batch, like Redis pipelining), so the
measurement covers frame parse + validation, per-request submit, batcher
coalescing across ALL clients, device dispatch, and response framing.
Emits decisions/s plus per-batch round-trip percentiles (p50/p99) into
ONE JSON line; bench.py records it in BENCH_DETAIL as
``sidecar_loopback``.

Modes:

- default: the hardened v2 server (frame validation, pipeline cap,
  deadlines, v2 handshake) — the production configuration.
- ``--assert-ratio``: ALSO measures an unhardened pass (bounds off, v1
  clients, no handshake) over the same storage and asserts the hardened
  number stays >= 0.9x of it — the ingress-hardening perf gate run by
  verify.sh.  Each configuration is measured twice and the best pass
  counts (CI noise must not read as a hardening regression).
- ``--faults``: runs the hardened pass while chaos clients hammer the
  server through a ``FaultInjectingProxy`` cycling kill / garbage /
  truncate faults — reports healthy-client throughput under fire and
  asserts the server survives.

Run with cwd=repo root:  python bench/sidecar_loopback.py
Env: BENCH_SCALE=small shrinks the request count (CI).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

N_CLIENTS = 8
PIPELINE = 64          # frames per pipelined batch (one syscall each way)


def run_pass(storage, reps: int, *, hardened: bool, tag: str,
             chaos: bool = False, block: bool = False,
             protocol: int | None = None,
             server_kwargs: dict | None = None,
             block_rows: int = 16) -> dict:
    """One measured loopback pass over an EXISTING storage (a fresh
    server per pass; the batcher/device state is shared, which is the
    production shape — many ingress generations, one authority)."""
    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer
    from ratelimiter_tpu.storage.chaos import FaultInjectingProxy

    if hardened:
        server = SidecarServer(storage, host="127.0.0.1",
                               **(server_kwargs or {})).start()
    else:
        # Every bound off: the pre-hardening ingress shape.
        server = SidecarServer(
            storage, host="127.0.0.1", max_frame_bytes=0, max_key_bytes=0,
            max_pipeline=0, max_connections=0, idle_timeout_ms=0,
            read_timeout_ms=0, resolve_timeout_ms=0).start()
    proxy = FaultInjectingProxy(server.port, seed=7).start() if chaos \
        else None
    stop_chaos = threading.Event()
    if protocol is None:
        protocol = 2 if hardened else 1
    try:
        lid = server.register("tb", RateLimitConfig(
            max_permits=1_000_000, window_ms=60_000, refill_rate=1e6))
        storage.warm_micro_shapes()

        lat_lock = threading.Lock()
        batch_lat_us: list = []
        allowed_total = [0]
        barrier = threading.Barrier(N_CLIENTS + 1)

        def client_loop(t: int) -> None:
            cli = SidecarClient("127.0.0.1", server.port,
                                protocol=protocol)

            def submit(keys):
                # block=True: v5 columnar frames (one frame + one bitmask
                # per block_rows chunk) instead of per-request frames.
                if block:
                    return cli.acquire_block(lid, keys,
                                             max_rows=block_rows)
                return [a for _, a, _ in cli.acquire_batch(lid, keys)]

            try:
                keys0 = [f"{tag}-c{t}-w{i}" for i in range(PIPELINE)]
                submit(keys0)  # warm the path
                # Synchronized warm rounds: concurrent clients coalesce
                # into batch shapes a lone client never produces, and
                # their XLA compiles must fire before the timed region.
                barrier.wait()
                for _ in range(3):
                    submit(keys0)
                barrier.wait()
                local_lat, local_allowed = [], 0
                for r in range(reps):
                    keys = [f"{tag}-c{t}-k{(r * PIPELINE + i) % 512}"
                            for i in range(PIPELINE)]
                    t0 = time.perf_counter()
                    res = submit(keys)
                    local_lat.append((time.perf_counter() - t0) * 1e6)
                    local_allowed += sum(1 for a in res if a)
                with lat_lock:
                    batch_lat_us.extend(local_lat)
                    allowed_total[0] += local_allowed
            finally:
                cli.close()

        def chaos_loop() -> None:
            import socket as socket_mod

            lid_atk = server.register("tb", RateLimitConfig(
                max_permits=1000, window_ms=60_000, refill_rate=100.0))
            k = 0
            while not stop_chaos.is_set():
                mode = ("kill", "garbage", "truncate")[k % 3]
                if mode == "kill":
                    proxy.set_fault("kill", after=90 + 30 * (k % 5))
                elif mode == "garbage":
                    proxy.set_fault("garbage", after=11 + 9 * (k % 7),
                                    n=32)
                else:
                    proxy.set_fault("truncate", after=7 + 5 * (k % 6))
                k += 1
                try:
                    atk = SidecarClient("127.0.0.1", proxy.port,
                                        timeout=1.0, protocol=1)
                    atk.acquire_batch(lid_atk,
                                      [f"a{j}" for j in range(16)])
                    atk.close()
                except (OSError, RuntimeError, socket_mod.timeout):
                    pass
                time.sleep(0.01)

        threads = [threading.Thread(target=client_loop, args=(t,),
                                    daemon=True)
                   for t in range(N_CLIENTS)]
        if chaos:
            threads.append(threading.Thread(target=chaos_loop,
                                            daemon=True))
        for th in threads:
            th.start()
        barrier.wait()   # start of the synchronized warm rounds
        barrier.wait()   # warm done: timed region begins
        t_start = time.perf_counter()
        for th in threads[:N_CLIENTS]:
            th.join()
        wall = time.perf_counter() - t_start
        stop_chaos.set()

        n = N_CLIENTS * reps * PIPELINE
        lat = np.asarray(batch_lat_us)
        out = {
            "clients": N_CLIENTS,
            "pipeline_depth": PIPELINE,
            "decisions": n,
            "wall_s": round(wall, 4),
            "decisions_per_sec": round(n / wall, 1),
            "allowed": allowed_total[0],
            "hardened": hardened,
            "columnar": block,
            "batch_latency": {
                "p50_us": round(float(np.percentile(lat, 50)), 1),
                "p99_us": round(float(np.percentile(lat, 99)), 1),
                "max_us": round(float(lat.max()), 1),
                "n_samples": int(len(lat)),
            },
            # Amortized per-request figure: a pipelined batch of
            # PIPELINE frames shares one round trip.
            "per_request_p99_us": round(
                float(np.percentile(lat, 99)) / PIPELINE, 2),
        }
        if chaos:
            out["chaos"] = {
                "proxy_connections": proxy.connections,
                "faults_injected": proxy.faults_injected,
                "sidecar_malformed": server.malformed_total,
                "sidecar_idle_closed": server.idle_closed_total,
            }
            assert storage.is_available(), "storage died under faults"
        return out
    finally:
        stop_chaos.set()
        if proxy is not None:
            proxy.stop()
        server.stop()


def main() -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    parser = argparse.ArgumentParser()
    parser.add_argument("--assert-ratio", action="store_true",
                        help="measure unhardened vs hardened and assert "
                             "hardened >= 0.9x")
    parser.add_argument("--faults", action="store_true",
                        help="run the hardened pass under proxy fault "
                             "injection")
    args = parser.parse_args()

    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    small = os.environ.get("BENCH_SCALE", "small") == "small"
    reps = 40 if small else 200

    storage = TpuBatchedStorage(num_slots=1 << 14, max_delay_ms=0.3,
                                max_inflight=4)
    try:
        out = {"bench": "sidecar_loopback",
               "note": ("loopback TCP, CPU device in-process: measures "
                        "the ingress machinery (framing + validation + "
                        "batcher coalescing across clients), not a TPU")}
        if args.assert_ratio:
            # Best-of-2 per configuration: scheduler noise on a loaded
            # box must not read as a hardening regression.
            raw = max((run_pass(storage, reps, hardened=False,
                                tag=f"raw{i}")
                       for i in range(2)),
                      key=lambda r: r["decisions_per_sec"])
            hard = max((run_pass(storage, reps, hardened=True,
                                 tag=f"hard{i}")
                        for i in range(2)),
                       key=lambda r: r["decisions_per_sec"])
            ratio = (hard["decisions_per_sec"]
                     / max(raw["decisions_per_sec"], 1.0))
            out.update(hard)
            out["unhardened_decisions_per_sec"] = raw["decisions_per_sec"]
            out["hardening_ratio"] = round(ratio, 3)
            assert ratio >= 0.9, (
                f"hardened ingress throughput fell to {ratio:.2f}x of the "
                f"unhardened path (hardened "
                f"{hard['decisions_per_sec']:.0f}/s vs raw "
                f"{raw['decisions_per_sec']:.0f}/s) — the 0.9x gate "
                "failed")
            # v5 columnar vs v4 per-request frames, apples to apples:
            # both arms on a hardened server whose pipeline cap admits
            # the whole burst (no differential shedding — shed frames
            # do zero device work and would flatter the v4 arm), so
            # every burst is ONE micro-batch flush of PIPELINE real
            # decisions in both shapes.  v5 ships 1 frame + 1 bitmask
            # where v4 ships PIPELINE frames + PIPELINE responses.
            deep = {"max_pipeline": PIPELINE}
            v4 = max((run_pass(storage, reps, hardened=True,
                               tag=f"v4f{i}", protocol=4,
                               server_kwargs=deep)
                      for i in range(2)),
                     key=lambda r: r["decisions_per_sec"])
            v5 = max((run_pass(storage, reps, hardened=True,
                               tag=f"v5b{i}", block=True,
                               server_kwargs=deep, block_rows=PIPELINE)
                      for i in range(2)),
                     key=lambda r: r["decisions_per_sec"])
            ratio5 = (v5["decisions_per_sec"]
                      / max(v4["decisions_per_sec"], 1.0))
            out["v4_decisions_per_sec"] = v4["decisions_per_sec"]
            out["v5_block_decisions_per_sec"] = v5["decisions_per_sec"]
            out["columnar_ratio"] = round(ratio5, 3)
            # Deterministic wire accounting: frames per burst each way.
            out["v5_frames_per_burst"] = -(-PIPELINE // PIPELINE)
            out["v4_frames_per_burst"] = PIPELINE
            assert ratio5 >= 0.9, (
                f"v5 columnar ingress fell to {ratio5:.2f}x of the v4 "
                f"per-request path ({v5['decisions_per_sec']:.0f}/s vs "
                f"{v4['decisions_per_sec']:.0f}/s) — the 0.9x floor "
                "failed")
        else:
            out.update(run_pass(storage, reps, hardened=True, tag="main",
                                chaos=args.faults))
        print(json.dumps(out))
    finally:
        storage.close()


if __name__ == "__main__":
    main()
