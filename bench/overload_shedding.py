"""Goodput and shed rate under overload: 1x / 2x / 4x offered load.

Drives the admission-controlled micro-batcher (bounded pending queue +
queue-deadline budgets, engine/batcher.py) over a fixed-rate synthetic
device via ``storage/chaos.py:overload_drill`` and reports, per offered
load: goodput fraction, shed fraction (queue-full + deadline-expired),
queue-depth high-water mark, and p99 latency of the ADMITTED requests.

The claim being measured ("Designing Scalable Rate Limiting Systems",
PAPERS.md): shedding the excess keeps the admitted requests' tail flat —
without the bound, 2x offered load queues without limit and every
request's latency grows with the backlog.

    JAX_PLATFORMS=cpu python bench/overload_shedding.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--multipliers", type=float, nargs="+",
                        default=[1.0, 2.0, 4.0],
                        help="offered load as multiples of device capacity")
    parser.add_argument("--max-pending", type=int, default=256)
    parser.add_argument("--deadline-ms", type=float, default=1000.0)
    parser.add_argument("--dispatch-ms", type=float, default=5.0,
                        help="synthetic device step latency")
    parser.add_argument("--max-batch", type=int, default=32)
    parser.add_argument("--bursts", type=int, default=80)
    parser.add_argument("--json", action="store_true",
                        help="emit the raw report as JSON only")
    args = parser.parse_args()

    from ratelimiter_tpu.storage.chaos import overload_drill

    report = overload_drill(
        load_multipliers=tuple(args.multipliers),
        max_pending=args.max_pending,
        deadline_ms=args.deadline_ms,
        dispatch_ms=args.dispatch_ms,
        max_batch=args.max_batch,
        bursts=args.bursts,
        p99_slack_ms=10_000.0,  # bench reports the tail; it doesn't gate
    )
    if args.json:
        print(json.dumps(report, indent=2))
        return

    print(f"device capacity: {report['capacity_rps']:.0f} req/s "
          f"(batch {args.max_batch} / {args.dispatch_ms} ms step); "
          f"max_pending={args.max_pending} deadline={args.deadline_ms} ms")
    print(f"{'load':>6} {'offered':>8} {'admitted':>9} {'shed':>6} "
          f"{'expired':>8} {'goodput':>8} {'shed%':>7} {'depth':>6} "
          f"{'p99 ms':>8}")
    for run in report["runs"]:
        print(f"{run['multiplier']:>5.1f}x {run['offered']:>8} "
              f"{run['admitted']:>9} {run['shed']:>6} "
              f"{run['deadline_expired']:>8} "
              f"{run['goodput_frac']:>8.1%} {run['shed_frac']:>7.1%} "
              f"{run['max_depth_seen']:>6} {run['p99_ms']:>8.1f}")
    bound_ok = all(r["max_depth_seen"] <= args.max_pending
                   for r in report["runs"])
    print(f"queue bound held at every load: {bound_ok}")


if __name__ == "__main__":
    main()
