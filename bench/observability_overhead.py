"""Hot-path cost of observability: decision rate with the full layer on
vs explicitly off.

"Observability that costs the hot path is a regression, not a feature"
(ISSUE 7): the stage timers, per-dispatch latency histogram, enriched
decision trace, SLO anomaly compare, and flight recorder are all ON by
default in production, so their cost must be provably inside budget on
the headline TB-Zipf stream.

Measurement method (same shape as ``bench/replication_overhead.py``):

- the two modes run INTERLEAVED, order rotated per round, so drift and
  cache warmth cancel instead of biasing whichever ran last;
- the GATED number is the **direct observability fraction**: the on-mode
  storage's ``_stage`` / ``_record_dispatch`` surfaces are wrapped with
  a wall-clock accumulator, and the gate bounds
  ``obs_seconds / pass_wall``.  On a small shared host the end-to-end
  paired diff's noise floor exceeds the 2% budget itself; the direct
  measurement is deterministic (the accumulator's own locking inflates
  the measured cost, which errs conservative);
- the paired per-round end-to-end ratio is also reported (unGATED).

    JAX_PLATFORMS=cpu python bench/observability_overhead.py \
        --n 2097152 --assert-budget 0.02
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class ObsMeter:
    """Wraps the on-mode storage's observability choke points with a
    wall-clock accumulator — the exact seconds the pass spent inside
    the observability layer."""

    def __init__(self, storage):
        self.seconds = 0.0
        self._lock = threading.Lock()
        storage._stage = self._timed(storage._stage)
        storage._record_dispatch = self._timed(storage._record_dispatch)

    def _timed(self, fn):
        def run(*args, **kwargs):
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self.seconds += dt
        return run


def leased_arm(storage, reps: int) -> dict:
    """Client-side telemetry cost on the LEASED decision path: local
    burns with the burn accumulator + latency histogram on vs off, over
    the same in-process lease manager.  The server-side plane (usage
    ring + fleet counters) is measured by the main arm's direct
    fraction; this arm bounds what the CLIENT pays per local decision."""
    import time as _time

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.leases import (
        DirectTransport,
        LeaseClient,
        LeaseManager,
    )

    cfg = RateLimitConfig(max_permits=1 << 20, window_ms=60_000,
                          refill_rate=1e6)
    lid = storage.register_limiter("tb", cfg)
    mgr = LeaseManager(storage, default_budget=4096, max_budget=4096,
                       ttl_ms=60_000.0)
    # "on" is the shipping config (sampled stamping: one perf_counter
    # pair per flush interval); "every" re-arms the stamp each burn —
    # the pre-sampling behavior — to show what the sampling buys.
    # Per-mode key namespaces: the manager grants ONE burner per key,
    # so concurrent clients must not contend for the same leases.
    modes = ("off", "on", "every")
    clients, mode_keys = {}, {}
    for mode in modes:
        keys = [f"{mode}:tenant{i}:burner" for i in range(8)]
        cli = LeaseClient(DirectTransport(mgr), lid, budget=4096,
                          telemetry=(mode != "off"),
                          telemetry_flush_ms=50.0)
        for k in keys:
            assert cli.try_acquire(k)   # warm: grants charged
        clients[mode] = cli
        mode_keys[mode] = keys
    # Interleaved best-of rounds (the replication_overhead idiom): a
    # shared host's scheduler noise swamps a single pass; the best
    # round per mode is the least-perturbed measurement.
    rates = {m: 0.0 for m in modes}
    for r in range(3):
        for mode in modes[r % 3:] + modes[:r % 3]:
            cli, keys = clients[mode], mode_keys[mode]
            telem = cli._telem
            t0 = _time.perf_counter()
            if mode == "every":
                for i in range(reps):
                    cli.try_acquire(keys[i & 7])
                    telem.stamp_pending = True  # force the per-burn pair
            else:
                for i in range(reps):
                    cli.try_acquire(keys[i & 7])
            wall = _time.perf_counter() - t0
            rates[mode] = max(rates[mode], reps / wall)
    for cli in clients.values():
        cli.release_all()
    return {
        "reps": reps,
        "local_rps_telemetry_off": round(rates["off"]),
        "local_rps_telemetry_on": round(rates["on"]),
        "local_rps_stamp_every_burn": round(rates["every"]),
        "leased_throughput_ratio": round(rates["on"] / rates["off"], 3),
        "stamp_every_burn_ratio": round(rates["every"] / rates["off"], 3),
    }


def timed_pass(storage, lid, key_ids) -> float:
    """One timed stream pass (GC parked, as in replication_overhead)."""
    import gc

    gc.collect()
    gc.disable()
    try:
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids)
        return time.perf_counter() - t0
    finally:
        gc.enable()


def main() -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--n", type=int, default=1 << 21,
                        help="requests per stream pass")
    parser.add_argument("--keys", type=int, default=1 << 14,
                        help="distinct tenant keys (Zipf-ish reuse)")
    parser.add_argument("--rounds", type=int, default=5,
                        help="interleaved off/on rounds")
    parser.add_argument("--num-slots", type=int, default=1 << 16)
    parser.add_argument("--trace-sample", type=int, default=64)
    parser.add_argument("--assert-budget", type=float, default=None,
                        metavar="FRAC",
                        help="fail if the direct observability fraction "
                             "of the on-mode pass exceeds this (e.g. "
                             "0.02)")
    parser.add_argument("--assert-leased-ratio", type=float, default=None,
                        metavar="RATIO",
                        help="fail if the leased arm's telemetry-on/off "
                             "throughput ratio drops below this (the "
                             "sampled perf_counter stamping keeps local "
                             "burns near free)")
    args = parser.parse_args()

    import numpy as np

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.metrics import MeterRegistry
    from ratelimiter_tpu.observability import FlightRecorder
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = np.random.default_rng(42)
    key_ids = rng.integers(0, args.keys, size=args.n)
    cfg = RateLimitConfig(max_permits=1000, window_ms=1000,
                          refill_rate=500.0)

    storages = {}
    lids = {}
    registry = MeterRegistry()
    recorder = FlightRecorder(slo_ms=10_000.0)  # armed, rarely firing
    for mode in ("off", "on"):
        if mode == "on":
            s = TpuBatchedStorage(
                num_slots=args.num_slots, meter_registry=registry,
                trace_sample=args.trace_sample, recorder=recorder)
        else:
            s = TpuBatchedStorage(num_slots=args.num_slots,
                                  observability=False)
        storages[mode] = s
        lids[mode] = s.register_limiter("tb", cfg)
        # Warm: compile shapes, settle the index, elect chunk plans.
        for _ in range(2):
            s.acquire_stream_ids("tb", lids[mode], key_ids)

    meter = ObsMeter(storages["on"])

    walls = {"off": [], "on": []}
    obs_s = []
    modes = ["off", "on"]
    for r in range(args.rounds):
        for mode in modes[r % 2:] + modes[:r % 2]:
            if mode == "on":
                pre = meter.seconds
                wall = timed_pass(storages[mode], lids[mode], key_ids)
                obs_s.append(meter.seconds - pre)
            else:
                wall = timed_pass(storages[mode], lids[mode], key_ids)
            walls[mode].append(wall)

    # Sanity: the on-mode pass actually exercised the layer.
    scrape = registry.scrape()
    fetch = scrape.get("ratelimiter.stream.fetch", {})
    assert fetch.get("count", 0) > 0, "stage timers never recorded"
    assert scrape.get("ratelimiter.storage.latency", {}).get(
        "count", 0) > 0, "dispatch latency histogram never recorded"
    assert len(storages["on"].trace.snapshot(last=5)["recent"]) > 0, (
        "decision trace never recorded")

    # Leased-workload arm: the client-side telemetry accumulator's cost
    # per LOCAL decision (the decision surface PR 12 moved off the
    # server — the fleet plane must stay affordable there too).
    leased = leased_arm(storages["on"], reps=1 << 16)

    # Sanity: the usage ring actually aggregated the stream passes
    # (per-tenant accounting is part of the measured layer).
    plane = storages["on"].telemetry
    assert plane is not None and plane.allowed_total > 0, (
        "fleet telemetry plane never folded a decision")
    assert plane.usage.tenants(), "usage ring tracked no tenant"

    best = {m: min(v) for m, v in walls.items()}
    ratios = sorted(walls["on"][r] / walls["off"][r]
                    for r in range(args.rounds))
    paired_pct = round(100.0 * (ratios[len(ratios) // 2] - 1.0), 2)
    # Direct fraction: best (least-noisy) round — the accumulator's own
    # lock is inside the measured window, so this still overcounts.
    direct_frac = min(o / w for o, w in zip(obs_s, walls["on"]))
    report = {
        "n_per_pass": args.n,
        "distinct_keys": args.keys,
        "rounds": args.rounds,
        "off_rps": round(args.n / best["off"]),
        "on_rps": round(args.n / best["on"]),
        "paired_overhead_pct": paired_pct,
        "obs_direct_pct": round(100.0 * direct_frac, 3),
        "obs_seconds_best_pass": round(min(obs_s), 4),
        "trace_sample": args.trace_sample,
        "leased": leased,
    }
    for s in storages.values():
        s.close()
    print(json.dumps(report, indent=2))
    if args.assert_budget is not None:
        budget_pct = 100.0 * args.assert_budget
        got = report["obs_direct_pct"]
        if got > budget_pct:
            raise SystemExit(
                f"observability decision-path cost {got}% exceeds the "
                f"{budget_pct}% budget")
        print(f"observability decision-path cost {got}% within the "
              f"{budget_pct}% budget")
    if args.assert_leased_ratio is not None:
        got = leased["leased_throughput_ratio"]
        every = leased["stamp_every_burn_ratio"]
        if got < args.assert_leased_ratio:
            raise SystemExit(
                f"leased telemetry-on throughput is {got}x the off "
                f"baseline — below the {args.assert_leased_ratio}x "
                f"floor (per-burn perf_counter stamping regressed?)")
        if got <= every:
            raise SystemExit(
                f"sampled stamping ({got}x) is no faster than stamping "
                f"every burn ({every}x) — the sampling is not engaging")
        print(f"leased telemetry on/off ratio {got}x >= "
              f"{args.assert_leased_ratio}x floor "
              f"(stamp-every-burn: {every}x)")


if __name__ == "__main__":
    main()
