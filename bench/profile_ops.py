"""Ground-truth op timings on the axon tunnel.

block_until_ready does not block under the axon backend, so every timing
here forces completion by fetching a scalar reduction (8 bytes D2H) and
subtracts the no-op baseline.  Uploads are timed by (upload + tiny-reduce
fetch) minus the same baseline on resident data.

Run: python bench/profile_ops.py [B]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


def t_med(f, n=5):
    ts = []
    for _ in range(n):
        t0 = time.perf_counter()
        f()
        ts.append(time.perf_counter() - t0)
    ts.sort()
    return ts[len(ts) // 2]


def main():
    B = int(sys.argv[1]) if len(sys.argv) > 1 else 1 << 21
    S = 1 << 20
    rng = np.random.default_rng(0)
    slots_np = (rng.zipf(1.1, size=B).astype(np.int64) % S).astype(np.int32)
    slots = jnp.asarray(slots_np)
    iota = jnp.arange(B, dtype=jnp.int32)
    state = jnp.zeros((S, 2), dtype=jnp.int32)
    rows = jnp.ones((B, 2), dtype=jnp.int32)
    mask = jnp.asarray(rng.random(B) < 0.5)
    print(f"B={B} S={S}", flush=True)

    csum = jax.jit(lambda x: x.sum()).lower(slots).compile()
    base = t_med(lambda: np.asarray(csum(slots)))
    print(f"  baseline (sum+8B fetch): {base*1000:.1f} ms", flush=True)

    # D2H fetch of B i32
    t = t_med(lambda: np.asarray(slots))
    print(f"  fetch {4*B>>20}MB: {t*1000:.1f} ms -> "
          f"{4*B/t/1e6:.0f} MB/s", flush=True)

    # H2D upload of B i32 (upload + sum fetch - baseline)
    t = t_med(lambda: np.asarray(csum(jnp.asarray(slots_np)))) - base
    print(f"  upload {4*B>>20}MB: {t*1000:.1f} ms -> "
          f"{4*B/max(t,1e-9)/1e6:.0f} MB/s", flush=True)

    def timed_op(name, fn, *args):
        t0 = time.perf_counter()
        c = jax.jit(fn).lower(*args).compile()
        tc = time.perf_counter() - t0
        np.asarray(c(*args))
        t = t_med(lambda: np.asarray(c(*args))) - base
        print(f"  {name}: compile {tc:5.1f}s  run {t*1000:7.1f} ms", flush=True)

    timed_op("sort2", lambda s, i: jax.lax.sort(
        (s, i), num_keys=1, is_stable=True)[1].sum(), slots, iota)
    timed_op("gather_rows", lambda st, s: st[s].sum(), state, slots)
    timed_op("xla_scatter", lambda st, s, m, r: st.at[
        jnp.where(m, s, S)].set(r, mode="drop").sum(),
        state, slots, mask, rows)
    timed_op("elemwise10", lambda s: ((((s * 3 + 1) ^ 5) % 7 + s // 3)
                                      * 2 - 1).sum(), slots)
    timed_op("packbits", lambda m: jnp.packbits(m).sum(), mask)


if __name__ == "__main__":
    main()
