"""Fast sharded-scaling perf smoke (CPU, small shapes) — CI guard.

ISSUE r6: the virtual-mesh scaling curve silently anti-scaled for two
rounds (19.5M/s at 1 shard -> 4.3M/s at 8 in BENCH_r05) because nothing
failed when the sharding machinery regressed.  This smoke runs the TB
Zipf stream at 1 and 2 virtual shards and asserts the 2-shard
throughput is at least 0.9x of 1 shard — a scaling INVERSION fails CI
loudly instead of waiting for the next full bench round.

Each point runs in its OWN subprocess (matching bench.py's discipline:
backend state, donated-buffer history, and virtual-device count must
not leak between points), with one full warmup pass and best-of-3
timed passes; the 0.9 margin absorbs CI timer noise — the threshold is
meant to catch structural regressions (a serialized per-shard walk, a
lost pipeline overlap), not 5% jitter.  The stream is the headline
shape scaled down (4M Zipf decisions over 1M keys: multi-chunk, so the
pipelined prepare actually overlaps).

Prints one JSON line; exit code 1 on inversion.  Run from the repo
root (verify.sh invokes it):  python bench/perf_smoke.py
With --point N it runs a single N-shard point and prints its
decisions/s (the subprocess mode).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
MARGIN = 0.9


def run_point(n_shards: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=2").strip()
    os.environ.setdefault("RATELIMITER_RATE_PROBE", "0")

    import time

    import jax
    import numpy as np

    sys.path.insert(0, _REPO)
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    clock = lambda: 100_000  # noqa: E731 — frozen: identical decisions
    rng = np.random.default_rng(11)
    key_ids = (rng.zipf(1.1, size=1 << 22).astype(np.int64) % 1_000_000)
    num_slots = 1 << 21
    if n_shards == 1:
        storage = TpuBatchedStorage(num_slots=num_slots, clock_ms=clock)
    else:
        from ratelimiter_tpu.parallel import ShardedDeviceEngine
        from ratelimiter_tpu.parallel.mesh import make_mesh

        engine = ShardedDeviceEngine(
            slots_per_shard=num_slots // n_shards,
            table=LimiterTable(),
            mesh=make_mesh(jax.devices()[:n_shards]))
        storage = TpuBatchedStorage(engine=engine, clock_ms=clock)
    lid = storage.register_limiter("tb", cfg)
    storage.acquire_stream_ids("tb", lid, key_ids, None)  # warm shapes
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids, None)
        best = min(best, time.perf_counter() - t0)
    storage.close()
    print(json.dumps({"n_shards": n_shards,
                      "decisions_per_sec": len(key_ids) / best}))


def main() -> int:
    if "--point" in sys.argv:
        run_point(int(sys.argv[sys.argv.index("--point") + 1]))
        return 0
    dps = {}
    for s in (1, 2):
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--point", str(s)],
            capture_output=True, timeout=540, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            print(f"PERF SMOKE FAILED: point {s} rc={proc.returncode} "
                  f"stderr={proc.stderr[-400:]!r}", file=sys.stderr)
            return 1
        dps[s] = json.loads(proc.stdout.strip().splitlines()[-1])[
            "decisions_per_sec"]
    ratio = dps[2] / dps[1]
    ok = ratio >= MARGIN
    print(json.dumps({
        "smoke": "sharded_scaling_2shard",
        "dps_1shard": round(dps[1], 1),
        "dps_2shard": round(dps[2], 1),
        "ratio": round(ratio, 3),
        "margin": MARGIN,
        "ok": ok,
    }))
    if not ok:
        print(f"PERF SMOKE FAILED: 2-shard throughput {ratio:.2f}x of "
              f"1 shard (< {MARGIN}x) — sharded dispatch regressed",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
