"""Fast perf smokes (CPU, small shapes) — CI guards.

ISSUE r6: the virtual-mesh scaling curve silently anti-scaled for two
rounds (19.5M/s at 1 shard -> 4.3M/s at 8 in BENCH_r05) because nothing
failed when the sharding machinery regressed.  This smoke runs the TB
Zipf stream at EVERY shard count of the virtual mesh (1/2/4/8) and
asserts MONOTONICITY (ISSUE r8): each point must reach at least
``MARGIN`` x the next-smaller point, and 8 shards at least
``MARGIN_END`` x of 1 shard — a scaling inversion anywhere on the
curve fails CI loudly instead of waiting for the next full bench
round.  (The pre-r8 smoke only checked 2 shards, which is exactly why
the 4- and 8-shard inversions lived for two rounds.)

Each point runs in its OWN subprocess (matching bench.py's discipline:
backend state, donated-buffer history, and virtual-device count must
not leak between points), with one full warmup pass and best-of-3
timed passes; the 0.9 margin absorbs CI timer noise — the threshold is
meant to catch structural regressions (a serialized per-shard walk, a
lost pipeline overlap, a reintroduced cross-shard barrier), not 5%
jitter.  The stream is the headline shape scaled down (4M Zipf
decisions over 1M keys: multi-chunk, so the per-shard pipelines
actually overlap).

ISSUE r7 adds a RELAY-ELECTION smoke (interpret-safe, also its own
subprocess): on a CPU backend no Pallas relay path may be elected (the
fused kernel is TPU-or-interpret only), the engine's elected sorted
digest dispatch must not run measurably slower than the raw XLA step
it wraps, and every disk-cached per-path election artifact
(pallas_elect_*.json) must be self-consistent — the recorded verdict
must equal what its own recorded A/B times imply, so an election can
never silently pin a measured-slower backend.

Prints one JSON line; exit code 1 on any violation.  Run from the repo
root (verify.sh invokes it):  python bench/perf_smoke.py
With --point N it runs a single N-shard point; with --relay-election
it runs the election smoke (the subprocess modes).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
#: Each shard count must reach MARGIN x the next-smaller count.
MARGIN = 0.9
#: ...and the full curve must not sag: 8 shards vs 1 shard.
MARGIN_END = 0.95
POINTS = (1, 2, 4, 8)


def run_point(n_shards: int) -> None:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    os.environ.setdefault("RATELIMITER_RATE_PROBE", "0")

    import time

    import jax
    import numpy as np

    sys.path.insert(0, _REPO)
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.storage import TpuBatchedStorage
    from ratelimiter_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000,
                          refill_rate=50.0)
    clock = lambda: 100_000  # noqa: E731 — frozen: identical decisions
    rng = np.random.default_rng(11)
    key_ids = (rng.zipf(1.1, size=1 << 22).astype(np.int64) % 1_000_000)
    num_slots = 1 << 21
    if n_shards == 1:
        storage = TpuBatchedStorage(num_slots=num_slots, clock_ms=clock)
    else:
        from ratelimiter_tpu.parallel import ShardedDeviceEngine
        from ratelimiter_tpu.parallel.mesh import make_mesh

        engine = ShardedDeviceEngine(
            slots_per_shard=num_slots // n_shards,
            table=LimiterTable(),
            mesh=make_mesh(jax.devices()[:n_shards]))
        storage = TpuBatchedStorage(engine=engine, clock_ms=clock)
    lid = storage.register_limiter("tb", cfg)
    storage.acquire_stream_ids("tb", lid, key_ids, None)  # warm shapes
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        storage.acquire_stream_ids("tb", lid, key_ids, None)
        best = min(best, time.perf_counter() - t0)
    storage.close()
    print(json.dumps({"n_shards": n_shards,
                      "decisions_per_sec": len(key_ids) / best}))


def run_relay_election() -> None:
    """Relay-election smoke: elected path never slower than XLA on this
    (CPU) backend, and cached election artifacts self-consistent."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("RATELIMITER_RATE_PROBE", "0")

    import functools
    import glob
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np

    sys.path.insert(0, _REPO)
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.engine import DeviceEngine
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.ops import relay
    from ratelimiter_tpu.ops.pallas import election, relay_step
    from ratelimiter_tpu.utils.compile_cache import (
        default_cache_dir,
        enable_compile_cache,
    )

    enable_compile_cache(os.path.join(_REPO, ".jax_cache"))
    out = {"smoke": "relay_election"}

    # 1. The fused Pallas path must not be live on a plain CPU backend.
    table = LimiterTable()
    lid = table.register(RateLimitConfig(
        max_permits=20, window_ms=60_000, refill_rate=5.0))
    eng = DeviceEngine(num_slots=1 << 15, table=table)
    fused_live = eng._relay_fused_ok("tb", 1 << 14)
    interpret = relay_step.interpret_mode()
    out["fused_live_on_cpu"] = bool(fused_live)
    out["interpret_override"] = bool(interpret)
    ok_live = interpret or not fused_live

    # 2. The elected dispatch (whatever the engine chose) must not be
    # slower than the raw XLA digest step on identical traffic.  Same
    # computation either way on CPU, so the generous 1.5x margin only
    # catches a structural mistake (e.g. interpret-mode Pallas leaking
    # into a non-test process).
    rb = eng.rank_bits
    u = 1 << 14
    slots = np.arange(u, dtype=np.uint32) * ((1 << 15) // u)
    uw = (slots << np.uint32(rb + 1)) | np.uint32(2)
    raw = jax.jit(functools.partial(
        relay.tb_relay_counts, rank_bits=rb, out_dtype=jnp.uint8))
    state = jnp.array(eng.tb_packed)
    tarr = table.device_arrays

    def best_of(fn, reps=5):
        fn()  # warm/compile
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    t_elected = best_of(lambda: np.asarray(eng.tb_relay_counts_dispatch(
        uw, np.int32(lid), 1_000_000, np.uint8, slots_sorted=True)))
    t_xla = best_of(lambda: np.asarray(raw(
        state, tarr, jnp.asarray(uw), jnp.int32(lid),
        jnp.int64(1_000_000))[1]))
    out["elected_s"] = round(t_elected, 6)
    out["xla_s"] = round(t_xla, 6)
    ok_speed = t_elected <= 1.5 * t_xla

    # 3. Cached election artifacts: verdict == what the recorded A/B
    # implies.  (env-off/interpret records carry no timings — skipped.)
    bad_records = []
    base = jax.config.jax_compilation_cache_dir or default_cache_dir()
    for path in sorted(glob.glob(os.path.join(
            base, "pallas_elect_*.json"))):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                rec = json.load(fh)
        except Exception:  # noqa: BLE001 — corrupt artifact: re-measured
            continue
        if "pallas_s" not in rec or "xla_s" not in rec:
            continue
        margin = float(rec.get("margin", election.DEFAULT_MARGIN))
        implied = rec["pallas_s"] <= margin * rec["xla_s"]
        if bool(rec.get("elected", rec.get("micro_win"))) != implied:
            bad_records.append(os.path.basename(path))
    out["election_artifacts_checked"] = len(
        glob.glob(os.path.join(base, "pallas_elect_*.json")))
    out["inconsistent_artifacts"] = bad_records
    out["ok"] = bool(ok_live and ok_speed and not bad_records)
    print(json.dumps(out))
    if not out["ok"]:
        print(f"RELAY ELECTION SMOKE FAILED: live_ok={ok_live} "
              f"speed_ok={ok_speed} bad={bad_records}", file=sys.stderr)
        sys.exit(1)


def main() -> int:
    if "--point" in sys.argv:
        run_point(int(sys.argv[sys.argv.index("--point") + 1]))
        return 0
    if "--relay-election" in sys.argv:
        run_relay_election()
        return 0
    dps = {}
    for s in POINTS:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--point", str(s)],
            capture_output=True, timeout=540, text=True, cwd=_REPO)
        if proc.returncode != 0 or not proc.stdout.strip():
            print(f"PERF SMOKE FAILED: point {s} rc={proc.returncode} "
                  f"stderr={proc.stderr[-400:]!r}", file=sys.stderr)
            return 1
        dps[s] = json.loads(proc.stdout.strip().splitlines()[-1])[
            "decisions_per_sec"]
    ratios = {f"{b}v{a}": dps[b] / dps[a]
              for a, b in zip(POINTS, POINTS[1:])}
    end_ratio = dps[POINTS[-1]] / dps[POINTS[0]]
    ok = (all(r >= MARGIN for r in ratios.values())
          and end_ratio >= MARGIN_END)
    # Relay-election smoke (its own subprocess: the engine + election
    # caches must resolve fresh, exactly as a service boot would).
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--relay-election"],
        capture_output=True, timeout=540, text=True, cwd=_REPO)
    relay_ok = proc.returncode == 0 and bool(proc.stdout.strip())
    try:
        relay_out = json.loads(proc.stdout.strip().splitlines()[-1])
    except Exception:  # noqa: BLE001 — crash before the JSON line
        relay_out = {"error": proc.stderr[-400:]}
    print(json.dumps({
        "smoke": "sharded_scaling_monotonic",
        "dps": {str(s): round(dps[s], 1) for s in POINTS},
        "ratios": {k: round(r, 3) for k, r in ratios.items()},
        "end_ratio_8v1": round(end_ratio, 3),
        "margin": MARGIN,
        "margin_end": MARGIN_END,
        "ok": ok,
        "relay_election": relay_out,
    }))
    if not ok:
        print(f"PERF SMOKE FAILED: sharded scaling not monotone — "
              f"ratios={ {k: round(r, 2) for k, r in ratios.items()} } "
              f"(each must be >= {MARGIN}), 8v1={end_ratio:.2f} "
              f"(must be >= {MARGIN_END}) — sharded dispatch regressed",
              file=sys.stderr)
        return 1
    if not relay_ok:
        print(f"PERF SMOKE FAILED: relay election smoke "
              f"rc={proc.returncode} stderr={proc.stderr[-400:]!r}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
