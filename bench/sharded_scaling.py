"""Sharded-engine scaling measurement on the virtual CPU mesh.

Runs the TB Zipf stream over 1 / 2 / 4 / 8 shards of a fixed-size global
slot table and reports decisions/s per shard count (VERDICT r1 #7: the
multi-chip story needs a measured slope, not just a compile proof).

On the virtual mesh every "device" is a slice of ONE host CPU, so the
slope here measures the sharding machinery's overhead (routing,
dispatch bookkeeping, per-shard padding), not parallel speedup — the
speedup model for a real v5e slice is in ARCHITECTURE.md (each shard
executes its slice of every dispatch concurrently; per-chip cost follows
the single-chip cost model at B/n_shards batch rows).  Two r3 fixes
moved this bench from "correct and 2x slower" to the real curve: a full
warmup pass (one-super-batch warmup left XLA compiles inside the timed
region — they were most of the recorded r2 "overhead") and O(n) C
routing (rl_shard_route: hash + stable counting sort in one pass,
replacing a numpy hash + argsort that was 60% of the warm chunk cost).
r8 removed the remaining inversion (BENCH_r05: 19.5M -> 4.3M/s from
1 -> 8 shards): the per-chunk mesh-wide shard_map dispatch — every
shard barriered on the slowest sibling's layout, the multi-device
launch rendezvoused all devices, lanes padded to the busiest shard —
was replaced by fully independent per-shard pipelines (storage/tpu.py
``_stream_relay_sharded`` + ``_ShardLane``; per-shard single-device
dispatches via ``ShardedDeviceEngine.relay_shard_dispatch``), with
routing electable onto the mesh (``build_route_count``).  The gate for
this curve staying monotone is bench/perf_smoke.py in verify.sh.

Invoked by bench.py in a subprocess (it must force the CPU backend before
any device is touched); standalone:  python bench/sharded_scaling.py
"""

from __future__ import annotations

import json
import os
import sys
import time

# Force 8 virtual CPU devices BEFORE jax initializes: XLA_FLAGS works on
# every jax this repo meets; newer jax also exposes jax_num_cpu_devices
# (tried below for belt and braces — on jax 0.4.x the option does not
# exist and the env flag alone provides the mesh).
os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass

import numpy as np  # noqa: E402

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ratelimiter_tpu.core.config import RateLimitConfig  # noqa: E402
from ratelimiter_tpu.engine.state import LimiterTable  # noqa: E402
from ratelimiter_tpu.storage import TpuBatchedStorage  # noqa: E402


def run(n_shards: int, num_slots: int, key_ids, batch, subbatches,
        str_keys=None) -> dict:
    cfg = RateLimitConfig(max_permits=100, window_ms=60_000, refill_rate=50.0)
    clock = lambda: 100_000  # noqa: E731 — frozen: identical decisions per point
    if n_shards == 1:
        storage = TpuBatchedStorage(num_slots=num_slots, clock_ms=clock)
    else:
        from ratelimiter_tpu.parallel import ShardedDeviceEngine
        from ratelimiter_tpu.parallel.mesh import make_mesh

        mesh = make_mesh(jax.devices()[:n_shards])
        engine = ShardedDeviceEngine(
            slots_per_shard=num_slots // n_shards,
            table=LimiterTable(), mesh=mesh)
        storage = TpuBatchedStorage(engine=engine, clock_ms=clock)
    lid = storage.register_limiter("tb", cfg)
    # FULL untimed warmup pass: the chunk-growth schedule is deterministic
    # in the key stream, so this visits every compile shape the timed
    # passes will hit (a one-super-batch warmup left shape compiles inside
    # the timed region and dominated the r2 "sharded overhead").
    storage.acquire_stream_ids("tb", lid, key_ids, None,
                               batch=batch, subbatches=subbatches)
    # >=6 reps per point with median + spread recorded (VERDICT r4 #6:
    # the r4 single-best points were noisy and non-monotonic, and the
    # artifact gave a reader no way to tell machine noise from a real
    # regression; r8 bumped 4 -> 6 reps — per-rep noise on a shared
    # 1-core container is ~±8%, and the monotonicity claim reads off
    # the medians).
    runs = []
    for _ in range(6):
        storage.stream_stats = stats = []
        t0 = time.perf_counter()
        allowed = storage.acquire_stream_ids("tb", lid, key_ids, None,
                                             batch=batch,
                                             subbatches=subbatches)
        wall = time.perf_counter() - t0
        storage.stream_stats = None
        runs.append((wall, stats))
    str_point = None
    if str_keys is not None:
        # END-TO-END string keys through the same engine (r6: the
        # sharded path hashes each chunk once and routes by fingerprint;
        # 1-shard runs the single-device string fast path) — tracked per
        # round so the str-vs-int gap and its scaling are in the
        # artifact, not just the single-device numbers.
        storage.acquire_stream_strs("tb", lid, str_keys)  # warm shapes
        str_walls = []
        for _ in range(2):
            t0 = time.perf_counter()
            storage.acquire_stream_strs("tb", lid, str_keys)
            str_walls.append(time.perf_counter() - t0)
        best = min(str_walls)
        str_point = {
            "decisions": len(str_keys),
            "walls_s": [round(w, 4) for w in str_walls],
            "decisions_per_sec": round(len(str_keys) / best, 1),
        }
    storage.close()
    runs.sort(key=lambda r: r[0])
    walls = [round(w, 4) for w, _ in runs]
    med_wall, med_stats = runs[(len(runs) - 1) // 2]
    phase = None
    if med_stats:
        phase = {
            "chunks": len(med_stats),
            "assign_s": round(sum(r.get("assign_s", 0)
                                  for r in med_stats), 4),
            "route_s": round(sum(r.get("route_s", 0)
                                 for r in med_stats), 4),
            "host_s": round(sum(r.get("host_s", 0) for r in med_stats), 4),
            "fetch_s": round(sum(r.get("fetch_s", 0)
                                 for r in med_stats), 4),
            "wire_bytes": int(sum(r.get("wire_bytes", 0)
                                  for r in med_stats)),
        }
        walks = [r["shard_walk_s"] for r in med_stats
                 if "shard_walk_s" in r]
        if walks:
            # Per-shard walk seconds summed over the pass, alongside the
            # per-shard REQUEST counts: walk spread with balanced
            # requests is core contention (this host has ONE core — the
            # pool's C walks serialize in arbitrary order), walk spread
            # tracking the request counts is routing skew.
            per_shard = [round(sum(w[s] for w in walks), 4)
                         for s in range(len(walks[0]))]
            phase["shard_walk_s"] = per_shard
        shard_ns = [r["shard_n"] for r in med_stats if "shard_n" in r]
        if shard_ns:
            phase["shard_n"] = [int(sum(c[s] for c in shard_ns))
                                for s in range(len(shard_ns[0]))]
    return {
        "n_shards": n_shards,
        "decisions": len(key_ids),
        "wall_s": med_wall,
        "walls_s": walls,
        "spread": round(walls[-1] / walls[0], 3) if walls[0] else None,
        "decisions_per_sec": len(key_ids) / med_wall,
        "best_decisions_per_sec": round(len(key_ids) / walls[0], 1),
        "allowed": int(allowed.sum()),
        "phase": phase,
        "str_end_to_end": str_point,
    }


def main() -> None:
    # >=4M decisions/point over 1M keys (VERDICT r3 #9): large enough to
    # expose per-shard serialization that the old 262K-decision points
    # amortized away.
    rng = np.random.default_rng(7)
    num_keys, n = 1_000_000, 1 << 22
    key_ids = (rng.zipf(1.1, size=n).astype(np.int64) % num_keys)
    # String end-to-end rides the same sweep on a half-size stream over a
    # disjoint key population sized so ints + strs fit the slot table
    # without eviction thrash (ints <= 1M uniques, strs <= 512K).
    str_keys = [f"k{i}" for i in
                (key_ids[:n // 2] % 500_000)]
    out = {"mesh": "virtual-cpu-8", "num_keys": num_keys,
           "points": [run(s, 1 << 21, key_ids, 1 << 14, 4,
                          str_keys=str_keys)
                      for s in (1, 2, 4, 8)]}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
