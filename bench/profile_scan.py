"""Compare cumulative-op formulations on the real device: compile time and
fetched-run time (np.asarray round trip; the tunnel adds a fixed floor, so
compare deltas, not absolutes).

Run: python bench/profile_scan.py [B ...]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")


def hier_scan(op, x, identity, chunk=4096):
    """Two-level associative scan: inner scans of length `chunk`, one outer
    scan over the B/chunk row totals.  Equivalent to associative_scan(op, x)
    for associative ops; compiles orders of magnitude faster at mega-batch
    sizes because every scan axis stays small."""
    n = x.shape[0]
    rows = n // chunk
    x2 = x.reshape(rows, chunk)
    inner = jax.lax.associative_scan(op, x2, axis=1)
    tots = inner[:, -1]
    outer = jax.lax.associative_scan(op, tots)
    base = jnp.concatenate([jnp.full((1,), identity, x.dtype), outer[:-1]])
    return op(inner, base[:, None]).reshape(n)


def timed(name, fn, *args):
    t0 = time.perf_counter()
    c = jax.jit(fn).lower(*args).compile()
    tc = time.perf_counter() - t0
    np.asarray(c(*args))
    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        np.asarray(c(*args))
        times.append(time.perf_counter() - t0)
    print(f"  {name}: compile {tc:6.1f}s  fetch-run {min(times)*1000:7.1f} ms",
          flush=True)
    return c


def main():
    sizes = [int(x) for x in sys.argv[1:]] or [1 << 21]
    rng = np.random.default_rng(0)
    for B in sizes:
        print(f"B={B}", flush=True)
        xi = jnp.asarray(rng.integers(0, 1 << 20, B, dtype=np.int32))
        xl = xi.astype(jnp.int64)

        timed("lax.cummax_i32", jax.lax.cummax, xi)
        timed("lax.cumsum_i64", jax.lax.cumsum, xl)
        timed("hier_cummax_i32",
              lambda v: hier_scan(jnp.maximum, v, np.int32(-2**31)), xi)
        timed("hier_cumsum_i64", lambda v: hier_scan(jnp.add, v, 0), xl)
        # correctness spot check
        a = np.asarray(jax.jit(
            lambda v: hier_scan(jnp.maximum, v, np.int32(-2**31)))(xi))
        b = np.maximum.accumulate(np.asarray(xi))
        c = np.asarray(jax.jit(lambda v: hier_scan(jnp.add, v, 0))(xl))
        d = np.cumsum(np.asarray(xl))
        print(f"  hier correct: cummax={bool((a==b).all())} "
              f"cumsum={bool((c==d).all())}", flush=True)


if __name__ == "__main__":
    main()
