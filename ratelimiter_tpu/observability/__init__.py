"""Observability subsystem (ARCHITECTURE §13).

Four layers over the metrics registry the service already carries:

- request-lifecycle tracing (``trace.LatencyTracer``): monotonic stage
  timestamps stamped at enqueue -> batch-assembly -> device-step ->
  resolve, aggregated into the ``ratelimiter.latency.*`` histograms,
  with optional 1-in-N full-trace sampling into the enriched
  ``DecisionTrace`` ring;
- log2-bucket histograms (``metrics/registry.Timer``) — O(1) record,
  no sort on scrape;
- Prometheus text exposition (``prometheus.render``) at
  ``GET /actuator/prometheus``;
- the flight recorder (``flightrecorder.FlightRecorder``): a bounded
  structured-event ring that subsystems append to at state transitions,
  plus an anomaly hook that snapshots the stage breakdown of any
  dispatch over the SLO threshold; ``GET /actuator/flightrecorder``
  (``?kind=`` / ``?since_ms=`` filter ring-side);
- the fleet telemetry plane (``telemetry.TelemetryPlane``): client
  lease-burn reports folded into fleet-true ``ratelimiter.decisions.*``
  counters, per-tenant usage accounting (``usage.UsageRing``,
  ``GET /actuator/tenants``, ``UsageSignals`` for the adaptive
  controller), and 64-bit trace-id lineage across client -> sidecar ->
  batcher -> shard -> resolve (``telemetry.TraceLineage``).

The whole layer is CI-gated at <= 2% of the headline decision stream
(``bench/observability_overhead.py --assert-budget 0.02`` in verify.sh).
"""

from ratelimiter_tpu.observability.flightrecorder import (  # noqa: F401
    FlightRecorder,
    flight_recorder,
)
from ratelimiter_tpu.observability.prometheus import (  # noqa: F401
    render as render_prometheus,
)
from ratelimiter_tpu.observability.telemetry import (  # noqa: F401
    ClientTelemetry,
    TelemetryPlane,
    TraceLineage,
    decode_report,
    mint_trace_id,
    trace_hex,
)
from ratelimiter_tpu.observability.trace import LatencyTracer  # noqa: F401
from ratelimiter_tpu.observability.usage import (  # noqa: F401
    UsageRing,
    UsageSignals,
)
