"""Fleet telemetry plane: client burn reports + end-to-end trace ids.

PR 12's token leases moved the bulk of decisions OFF the server: a
leased client burns permits locally and the server only sees coarse
``used`` counts at renewal.  The PR 7 observability layer therefore
stopped seeing most of the fleet.  This module restores fleet-true
visibility with three pieces:

1. **Client burn telemetry** (:class:`ClientTelemetry` + the wire
   codec).  ``LeaseClient`` accumulates per-(lid, key-class)
   allow/deny/permit counts and a local-decision latency histogram
   (same log2-bucket scheme as ``metrics/registry.Timer``), and flushes
   them as one compact binary report — piggybacked on RENEW wire ops
   and on a bounded cadence, with **drop-don't-block** semantics:
   telemetry must never add a wire round trip (the TELEMETRY sidecar op
   is response-less) nor stall a decision (a send that cannot complete
   promptly is dropped and counted, never retried inline).

2. **The server-side plane** (:class:`TelemetryPlane`).  Folds decoded
   reports — plus server-side dispatch results, degraded-path decisions
   and admission-control sheds — into the registry
   (``ratelimiter.decisions.*`` is again the true fleet-wide decision
   count) and into the per-tenant :class:`~ratelimiter_tpu.
   observability.usage.UsageRing`.  A per-client staleness gauge
   (``ratelimiter.telemetry.staleness_ms``) bounds how far behind the
   fleet counters can be: one client flush interval.

3. **Trace context** (:func:`mint_trace_id` + :class:`TraceLineage`).
   A 64-bit trace id is minted at ingress (or carried in on a v4
   sidecar frame), threaded through the micro-batcher, the dispatch
   paths and the lease protocol; sampled ids accumulate ordered hops
   (client -> sidecar -> batcher -> shard -> resolve) in a bounded
   lineage ring so one slow or surprising decision can be followed
   across the whole distributed decision surface.  Explicitly
   client-supplied ids are always sampled (the caller asked); minted
   ids head-sample 1-in-N so the ring costs O(sampled), not O(requests).
"""

from __future__ import annotations

import collections
import itertools
import os
import struct
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

_M64 = (1 << 64) - 1
_GOLDEN = 0x9E3779B97F4A7C15


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


def _mix64(x: int) -> int:
    """splitmix64 finalizer — the same mixer the shard router family
    uses; decorrelates sequential mint counters so head-sampling by
    ``tid % n`` is unbiased."""
    x = (x + _GOLDEN) & _M64
    x = ((x ^ (x >> 30)) * 0xBF58476D1CE4E5B9) & _M64
    x = ((x ^ (x >> 27)) * 0x94D049BB133111EB) & _M64
    return x ^ (x >> 31)


_MINT = itertools.count(int.from_bytes(os.urandom(8), "little")).__next__


def mint_trace_id() -> int:
    """A fresh nonzero 64-bit trace id (0 means "no trace")."""
    return _mix64(_MINT() & _M64) or 1


def trace_hex(tid: int) -> str:
    return f"{int(tid) & _M64:016x}"


#: Number of latency buckets mirrored from ``metrics/registry.Timer``.
N_LATENCY_BUCKETS = 64


def latency_bucket(micros: float) -> int:
    """The Timer log2 bucket index for one latency sample — value v
    lands in the bucket whose range (2^(i-1), 2^i] us contains it."""
    if micros > 1.0:
        idx = (-int(-micros) - 1).bit_length()
        return idx if idx < N_LATENCY_BUCKETS else N_LATENCY_BUCKETS - 1
    return 0


def default_key_class(key: str) -> str:
    """Bound the telemetry label space: the segment before the first
    ``:`` (the common ``tenant:user`` shape), or ``*`` for unstructured
    keys — raw keys are unbounded-cardinality and must never become
    label values wholesale."""
    i = key.find(":")
    return key[:i] if i > 0 else "*"


# ---------------------------------------------------------------------------
# Trace lineage
# ---------------------------------------------------------------------------

class TraceLineage:
    """Bounded per-trace-id hop ring.

    ``record`` is a no-op unless the id is sampled, so arming this on
    the hot path costs one dict probe + one modulo per candidate.
    Explicit ids (a client sent one over the wire) are ``force``d —
    always sampled; minted ids head-sample 1-in-``sample_n``.
    """

    def __init__(self, capacity: int = 256, sample_n: int = 0,
                 max_hops: int = 64):
        self._capacity = max(int(capacity), 1)
        self._sample_n = max(int(sample_n), 0)
        self._max_hops = max(int(max_hops), 1)
        self._traces: "collections.OrderedDict[int, List[dict]]" = \
            collections.OrderedDict()
        self._forced: "collections.OrderedDict[int, None]" = \
            collections.OrderedDict()
        self._lock = threading.Lock()
        self.recorded_hops = 0
        self.dropped_hops = 0   # hops refused by the per-trace bound

    @property
    def sample_n(self) -> int:
        return self._sample_n

    def force(self, tid: int) -> None:
        """Mark an explicitly-propagated id as always-sampled."""
        if not tid:
            return
        with self._lock:
            self._forced[int(tid)] = None
            self._forced.move_to_end(int(tid))
            while len(self._forced) > self._capacity:
                self._forced.popitem(last=False)

    def sampled(self, tid: int) -> bool:
        if not tid:
            return False
        if int(tid) in self._forced:
            return True
        return (self._sample_n > 0
                and (_mix64(int(tid)) % self._sample_n) == 0)

    def record(self, tid: int, hop: str, **fields) -> bool:
        """Append one hop under a sampled trace id; returns whether it
        was recorded."""
        if not self.sampled(tid):
            return False
        entry = {"hop": hop, "t_ms": _wall_ms()}
        if fields:
            entry.update(fields)
        with self._lock:
            hops = self._traces.get(int(tid))
            if hops is None:
                hops = []
                self._traces[int(tid)] = hops
                while len(self._traces) > self._capacity:
                    self._traces.popitem(last=False)
            if len(hops) >= self._max_hops:
                self.dropped_hops += 1
                return False
            hops.append(entry)
            self._traces.move_to_end(int(tid))
            self.recorded_hops += 1
        return True

    def lineage(self, tid: int) -> List[dict]:
        with self._lock:
            return list(self._traces.get(int(tid), ()))

    def hops(self, tid: int) -> List[str]:
        return [h["hop"] for h in self.lineage(tid)]

    def snapshot(self, last: int = 16) -> Dict:
        with self._lock:
            items = list(self._traces.items())[-last:]
            return {
                "traces": {trace_hex(t): list(h) for t, h in items},
                "recorded_hops": self.recorded_hops,
                "sample_n": self._sample_n,
            }


# ---------------------------------------------------------------------------
# Client-side accumulator + wire codec
# ---------------------------------------------------------------------------

class TelemetryReport(NamedTuple):
    """One decoded client report."""

    client_id: int
    allowed: int            # local decisions allowed (all classes)
    denied: int             # local decisions denied
    hist: Tuple[Tuple[int, int], ...]   # (bucket idx, count), sparse
    hist_total_us: int
    # (lid, key_class, allowed, denied, permits)
    records: Tuple[Tuple[int, str, int, int, int], ...]


_HDR = struct.Struct("<BQQQQB")       # ver, client_id, allowed, denied,
#                                        hist_total_us, n_buckets
_BUCKET = struct.Struct("<BQ")        # idx, count
_REC_HDR = struct.Struct("<IIIQB")    # lid, allowed, denied, permits,
#                                        class_len
_WIRE_VERSION = 1

#: Overflow class: records past ``max_classes`` fold here so one
#: misbehaving key namespace cannot balloon the report (or the label
#: space it becomes).
OVERFLOW_CLASS = "~other"


class ClientTelemetry:
    """Per-client burn/deny accumulator with a local-latency histogram.

    NOT thread-safe on its own — it lives inside a ``LeaseClient``,
    which is single-caller by contract (one burner per key).
    """

    def __init__(self, client_id: Optional[int] = None,
                 key_class: Optional[Callable[[str], str]] = None,
                 max_classes: int = 64, max_key_cache: int = 4096):
        self.client_id = int(client_id) if client_id else mint_trace_id()
        self._key_class = key_class or default_key_class
        self.max_classes = max(int(max_classes), 1)
        self.max_key_cache = max(int(max_key_cache), 1)
        # (lid, class) -> [allowed, denied, permits]
        self._counts: Dict[Tuple[int, str], List[int]] = {}
        # (lid, key) -> row: skips the class split + tuple build on the
        # hot burn path (a leased client hits the same keys over and
        # over — that is what a lease IS).
        self._row_cache: Dict[Tuple[int, str], List[int]] = {}
        self._hist = [0] * N_LATENCY_BUCKETS
        self._hist_total_us = 0
        self.allowed = 0
        self.denied = 0
        # Sampled latency stamping (one stamp per flush interval): the
        # perf_counter pair costs ~1 µs per local burn — material on a
        # path whose whole budget is a few µs (PR 13's bench note).
        # The caller checks ``stamp_pending`` and only pays the pair
        # while a sample is wanted; the first latency-carrying record
        # clears it, and the next flush re-arms it.  The histogram
        # becomes one sample per client per flush interval — the shape
        # survives, the per-burn cost does not.
        self.stamp_pending = True

    def _row(self, lid: int, key: str) -> List[int]:
        row = self._row_cache.get((lid, key))
        if row is not None:
            return row
        cls = self._key_class(key)
        k = (int(lid), cls)
        row = self._counts.get(k)
        if row is None:
            if len(self._counts) >= self.max_classes:
                k = (int(lid), OVERFLOW_CLASS)
                row = self._counts.setdefault(k, [0, 0, 0])
            else:
                row = self._counts[k] = [0, 0, 0]
        if len(self._row_cache) < self.max_key_cache:
            self._row_cache[(lid, key)] = row
        return row

    def record_burn(self, lid: int, key: str, permits: int,
                    latency_us: Optional[float] = None) -> None:
        row = self._row(lid, key)
        row[0] += 1
        row[2] += int(permits)
        self.allowed += 1
        if latency_us is not None:
            self._hist[latency_bucket(latency_us)] += 1
            self._hist_total_us += int(latency_us)
            self.stamp_pending = False

    def record_deny(self, lid: int, key: str,
                    latency_us: Optional[float] = None) -> None:
        row = self._row(lid, key)
        row[1] += 1
        self.denied += 1
        if latency_us is not None:
            self._hist[latency_bucket(latency_us)] += 1
            self._hist_total_us += int(latency_us)
            self.stamp_pending = False

    def pending(self) -> bool:
        return bool(self.allowed or self.denied)

    def encode_and_reset(self) -> bytes:
        """Snapshot the accumulated report as one wire blob and clear.
        The caller owns delivery; on a dropped flush it may simply keep
        accumulating (counts since the snapshot are a fresh report)."""
        buckets = [(i, c) for i, c in enumerate(self._hist) if c]
        parts = [_HDR.pack(_WIRE_VERSION, self.client_id,
                           self.allowed, self.denied,
                           self._hist_total_us, len(buckets))]
        parts.extend(_BUCKET.pack(i, c) for i, c in buckets)
        records = list(self._counts.items())
        parts.append(struct.pack("<H", len(records)))
        for (lid, cls), (alw, den, permits) in records:
            raw = cls.encode()[:255]
            parts.append(_REC_HDR.pack(lid, alw, den, permits, len(raw)))
            parts.append(raw)
        self._counts.clear()
        self._row_cache.clear()   # rows were just detached from _counts
        self._hist = [0] * N_LATENCY_BUCKETS
        self._hist_total_us = 0
        self.allowed = 0
        self.denied = 0
        self.stamp_pending = True   # re-arm: one sample per interval
        return b"".join(parts)


def decode_report(blob: bytes) -> TelemetryReport:
    """Decode one wire report; raises ``ValueError`` on malformed input
    (the server counts those, never crashes on them)."""
    try:
        ver, client_id, allowed, denied, hist_total, n_buckets = \
            _HDR.unpack_from(blob)
        if ver != _WIRE_VERSION:
            raise ValueError(f"telemetry wire version {ver}")
        off = _HDR.size
        hist = []
        for _ in range(n_buckets):
            idx, count = _BUCKET.unpack_from(blob, off)
            off += _BUCKET.size
            if idx >= N_LATENCY_BUCKETS:
                raise ValueError(f"latency bucket {idx} out of range")
            hist.append((idx, count))
        (n_records,) = struct.unpack_from("<H", blob, off)
        off += 2
        records = []
        for _ in range(n_records):
            lid, alw, den, permits, class_len = \
                _REC_HDR.unpack_from(blob, off)
            off += _REC_HDR.size
            cls = blob[off:off + class_len]
            if len(cls) != class_len:
                raise ValueError("truncated key-class")
            off += class_len
            records.append((lid, cls.decode(), alw, den, permits))
        if off != len(blob):
            raise ValueError(f"{len(blob) - off} trailing bytes")
    except (struct.error, UnicodeDecodeError) as exc:
        raise ValueError(str(exc)) from exc
    return TelemetryReport(client_id, allowed, denied, tuple(hist),
                           hist_total, tuple(records))


# ---------------------------------------------------------------------------
# Server-side plane
# ---------------------------------------------------------------------------

class TelemetryPlane:
    """Folds every decision source into fleet-true registry counters and
    the per-tenant usage ring.

    ``ratelimiter.decisions.allowed/denied`` count EVERY decision in the
    fleet — server dispatches, degraded-path host decisions, and
    client-local lease burns (from telemetry reports) — so they
    reconcile with ground truth to within one client flush interval
    (the documented staleness bound, surfaced as the
    ``ratelimiter.telemetry.staleness_ms`` gauge).
    """

    def __init__(self, registry=None, clock_ms=None, usage=None,
                 max_clients: int = 1024, max_classes: int = 512):
        from ratelimiter_tpu.observability.usage import UsageRing

        self._clock_ms = clock_ms or _wall_ms
        self.usage = usage if usage is not None else UsageRing(
            clock_ms=self._clock_ms)
        self.max_clients = max(int(max_clients), 1)
        self.max_classes = max(int(max_classes), 1)
        # client_id -> wall-clock ms of the last folded report.
        self._clients: "collections.OrderedDict[int, int]" = \
            collections.OrderedDict()
        # (lid, key_class) -> [allowed, denied, permits] — the labeled
        # Prometheus series behind prometheus_samples().
        self._classes: Dict[Tuple[int, str], List[int]] = {}
        self._lock = threading.Lock()
        # Plain totals (drills/benches read these without a registry).
        self.allowed_total = 0
        self.denied_total = 0
        self.shed_total = 0
        self.lease_local_total = 0
        self.reports_total = 0
        self.reports_rejected = 0
        if registry is not None:
            mk = registry.counter
            self._m_allowed = mk(
                "ratelimiter.decisions.allowed",
                "Fleet-wide allowed decisions: server dispatches + "
                "degraded-path decisions + client-reported lease burns")
            self._m_denied = mk(
                "ratelimiter.decisions.denied",
                "Fleet-wide denied decisions (all decision surfaces)")
            self._m_shed = mk(
                "ratelimiter.decisions.shed",
                "Decisions refused by admission control before reaching "
                "a decision surface (batcher queue/deadline, sidecar "
                "pipeline cap)")
            self._m_lease_local = mk(
                "ratelimiter.decisions.lease_local",
                "Subset of fleet decisions decided client-side against "
                "token leases, folded from telemetry reports")
            self._m_reports = mk(
                "ratelimiter.telemetry.reports",
                "Client telemetry reports folded into the fleet counters")
            self._m_rejected = mk(
                "ratelimiter.telemetry.rejected",
                "Client telemetry reports the server failed to decode")
            self._m_clients = registry.gauge(
                "ratelimiter.telemetry.clients",
                "Distinct clients that have reported telemetry (bounded "
                "LRU window)")
            self._m_staleness = registry.gauge(
                "ratelimiter.telemetry.staleness_ms",
                "Age of the OLDEST client's last telemetry report — the "
                "bound on how far the fleet decision counters trail "
                "ground truth (~ one client flush interval when healthy)")
            self._m_latency = registry.timer(
                "ratelimiter.telemetry.local_latency",
                "Client-local lease decision latency, folded from "
                "telemetry reports (us)")
        else:
            self._m_allowed = self._m_denied = self._m_shed = None
            self._m_lease_local = self._m_reports = self._m_rejected = None
            self._m_clients = self._m_staleness = self._m_latency = None

    # -- server-side decision sources -----------------------------------------
    def note_server(self, lid: int, n: int, allowed: int,
                    now_ms: Optional[int] = None) -> None:
        """One server-side dispatch's outcome for one tenant: ``n``
        decisions, ``allowed`` of them admitted.  O(1) — called per
        micro batch / per stream chunk, never per decision."""
        allowed = int(allowed)
        denied = max(int(n) - allowed, 0)
        self.allowed_total += allowed
        self.denied_total += denied
        if self._m_allowed is not None:
            if allowed:
                self._m_allowed.add(allowed)
            if denied:
                self._m_denied.add(denied)
        self.usage.record(lid, admitted=allowed, denied=denied,
                          now_ms=now_ms)

    def note_batch(self, lids, allowed_mask,
                   now_ms: Optional[int] = None) -> None:
        """A mixed-tenant micro batch: fold per-tenant outcomes in one
        bincount pass."""
        import numpy as np

        lids = np.asarray(lids)
        mask = np.asarray(allowed_mask, dtype=bool)
        if lids.size == 0:
            return
        uniq, inv = np.unique(lids, return_inverse=True)
        n_per = np.bincount(inv, minlength=len(uniq))
        a_per = np.bincount(inv, weights=mask, minlength=len(uniq))
        for lid, n, a in zip(uniq.tolist(), n_per.tolist(),
                             a_per.tolist()):
            self.note_server(int(lid), int(n), int(a), now_ms=now_ms)

    def note_shed(self, lid: int, n: int = 1,
                  now_ms: Optional[int] = None) -> None:
        self.shed_total += int(n)
        if self._m_shed is not None:
            self._m_shed.add(int(n))
        self.usage.record(lid, shed=int(n), now_ms=now_ms)

    def note_degraded(self, lid: int, allowed: bool,
                      now_ms: Optional[int] = None) -> None:
        self.note_server(lid, 1, 1 if allowed else 0, now_ms=now_ms)

    # -- client telemetry ------------------------------------------------------
    def fold(self, blob_or_report, now_ms: Optional[int] = None) -> int:
        """Fold one client report (wire blob or decoded); returns the
        record count, or -1 when the blob was malformed (counted in
        ``ratelimiter.telemetry.rejected``, never raised — telemetry is
        advisory input from the network)."""
        if isinstance(blob_or_report, (bytes, bytearray, memoryview)):
            try:
                report = decode_report(bytes(blob_or_report))
            except ValueError:
                self.reports_rejected += 1
                if self._m_rejected is not None:
                    self._m_rejected.increment()
                return -1
        else:
            report = blob_or_report
        now = int(self._clock_ms() if now_ms is None else now_ms)
        self.allowed_total += report.allowed
        self.denied_total += report.denied
        self.lease_local_total += report.allowed + report.denied
        self.reports_total += 1
        if self._m_allowed is not None:
            if report.allowed:
                self._m_allowed.add(report.allowed)
            if report.denied:
                self._m_denied.add(report.denied)
            if report.allowed or report.denied:
                self._m_lease_local.add(report.allowed + report.denied)
            self._m_reports.increment()
        if self._m_latency is not None and report.hist:
            self._m_latency.merge(report.hist, report.hist_total_us)
        for lid, cls, allowed, denied, permits in report.records:
            self.usage.record(lid, admitted=allowed, denied=denied,
                              lease_local=allowed, now_ms=now)
            with self._lock:
                row = self._classes.get((lid, cls))
                if row is None:
                    if len(self._classes) >= self.max_classes:
                        row = self._classes.setdefault(
                            (lid, OVERFLOW_CLASS), [0, 0, 0])
                    else:
                        row = self._classes[(lid, cls)] = [0, 0, 0]
                row[0] += allowed
                row[1] += denied
                row[2] += permits
        with self._lock:
            self._clients[report.client_id] = now
            self._clients.move_to_end(report.client_id)
            while len(self._clients) > self.max_clients:
                self._clients.popitem(last=False)
        self._refresh_gauges(now)
        return len(report.records)

    # -- staleness -------------------------------------------------------------
    def staleness_ms(self, now_ms: Optional[int] = None) -> float:
        """Age of the OLDEST client's last report (0 with no clients):
        the bound on how far the fleet counters trail ground truth."""
        now = int(self._clock_ms() if now_ms is None else now_ms)
        with self._lock:
            if not self._clients:
                return 0.0
            oldest = min(self._clients.values())
        return float(max(now - oldest, 0))

    def _refresh_gauges(self, now: int) -> None:
        if self._m_clients is not None:
            with self._lock:
                n = len(self._clients)
            self._m_clients.set(float(n))
            self._m_staleness.set(self.staleness_ms(now))

    # -- export surfaces -------------------------------------------------------
    def signals(self, tenant: int, window_ms: int = 10_000):
        """ARCHITECTURE §13e: the adaptive controller's observation."""
        return self.usage.signals(tenant, window_ms)

    def all_signals(self, window_ms: int = 10_000):
        return self.usage.all_signals(window_ms)

    def tenants_payload(self) -> Dict:
        """``GET /actuator/tenants``."""
        now = int(self._clock_ms())
        self._refresh_gauges(now)
        with self._lock:
            n_clients = len(self._clients)
        payload = self.usage.snapshot(now)
        payload["telemetry"] = {
            "reports": self.reports_total,
            "rejected": self.reports_rejected,
            "clients": n_clients,
            "staleness_ms": self.staleness_ms(now),
            "lease_local_decisions": self.lease_local_total,
        }
        return payload

    def prometheus_samples(self):
        """Labeled series for the Prometheus exposition (the registry
        carries only unlabeled meters): per-tenant usage totals and
        per-(lid, key-class) client burn counts.  Label VALUES are
        escaped by the renderer — key classes come off the wire."""
        samples = []
        tenant_rows = {f: [] for f in ("admitted", "denied", "shed",
                                       "lease_local")}
        for t in self.usage.tenants():
            totals = self.usage.totals(t)
            for f, rows in tenant_rows.items():
                rows.append(({"tenant": str(t)}, totals[f]))
        for f, rows in tenant_rows.items():
            if rows:
                samples.append((
                    f"ratelimiter.tenant.{f}", "counter",
                    f"Per-tenant {f} decisions (usage ring totals)",
                    rows))
        with self._lock:
            classes = sorted(self._classes.items())
        for idx, name in ((0, "allowed"), (1, "denied"), (2, "permits")):
            rows = [({"lid": str(lid), "key_class": cls}, row[idx])
                    for (lid, cls), row in classes if row[idx]]
            if rows:
                samples.append((
                    f"ratelimiter.telemetry.class_{name}", "counter",
                    f"Client-reported lease-local {name} per "
                    "(limiter, key class)", rows))
        return samples
