"""Flight recorder: a bounded structured-event ring for state transitions.

Metrics answer "how much"; the flight recorder answers "what happened,
in what order".  Subsystems append one event per *state transition* —
breaker open/half-open/close, health state changes, shed bursts, Pallas
election verdicts and fused-relay fallback, replication promotion /
``reordered`` / ``coalesced``, shard failover — so after an incident the
ring reads as a timeline (open -> degraded -> resync; kill -> promote ->
bit-identical) without log archaeology.  The chaos drills
(``storage/chaos.py``) assert exactly those sequences.

Events are rare by construction (transitions, not requests), so the ring
takes a plain lock; per-kind coalescing (``coalesce_ms``) keeps bursty
kinds — shed storms, replicator coalescing — from flooding the ring:
a repeat of the same kind within the window increments the previous
event's ``n`` instead of appending.

The **anomaly hook** is the one per-dispatch touch point: any dispatch
whose wall time exceeds the configured SLO threshold gets its stage
breakdown snapshotted together with the last ``context_events`` ring
events — the "where did this request's 3.2 ms go" artifact, captured at
the moment it happened.  The threshold check itself is one float compare
on the recording path (``storage/tpu.py:_record_dispatch``).

A process-global default instance (``flight_recorder()``) exists so that
deeply-nested subsystems (the breaker inside the wrapper chain, the
Pallas election, the standby receiver) need no plumbing; components
accept an explicit ``recorder=`` for isolation in tests.
Exposed at ``GET /actuator/flightrecorder``.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class FlightRecorder:
    """Fixed-capacity ring of structured transition events + anomalies."""

    def __init__(self, capacity: int = 1024, anomaly_capacity: int = 64,
                 slo_ms: float = 0.0, context_events: int = 16):
        self._capacity = max(int(capacity), 1)
        self._anomaly_capacity = max(int(anomaly_capacity), 1)
        self._context_events = max(int(context_events), 1)
        self._slo_us = float(slo_ms) * 1000.0
        self._events: List[Optional[dict]] = [None] * self._capacity
        self._next = 0
        self._seq = 0          # total events ever recorded (wrap counter)
        self._anomalies: List[dict] = []
        self._anomaly_total = 0
        self._last_by_kind: Dict[str, dict] = {}
        self._lock = threading.Lock()

    # -- configuration --------------------------------------------------------
    def set_slo_ms(self, slo_ms: float) -> None:
        """Arm (or disarm, 0) the slow-dispatch anomaly hook."""
        self._slo_us = float(slo_ms) * 1000.0

    def resize(self, capacity: int) -> None:
        """Re-bound the ring (boot-time config; keeps the newest events
        that fit)."""
        capacity = max(int(capacity), 1)
        with self._lock:
            kept = self._ordered_locked()[-capacity:]
            self._capacity = capacity
            self._events = kept + [None] * (capacity - len(kept))
            self._next = len(kept) % capacity

    @property
    def slo_us(self) -> float:
        return self._slo_us

    # -- recording ------------------------------------------------------------
    def record(self, kind: str, coalesce_ms: float = 0.0, **fields) -> None:
        """Append one transition event.

        ``coalesce_ms`` > 0: a repeat of ``kind`` within the window
        bumps the previous event's ``n`` count instead of appending —
        a burst reads as one event with a tally, not a flood.
        """
        now_ms = time.time_ns() // 1_000_000
        with self._lock:
            if coalesce_ms > 0:
                last = self._last_by_kind.get(kind)
                if last is not None and now_ms - last["t_ms"] <= coalesce_ms:
                    last["n"] = last.get("n", 1) + 1
                    last["t_last_ms"] = now_ms
                    return
            event = {"seq": self._seq, "t_ms": now_ms, "kind": kind}
            if fields:
                event.update(fields)
            self._events[self._next] = event
            self._next = (self._next + 1) % self._capacity
            self._seq += 1
            self._last_by_kind[kind] = event

    def record_transition(self, kind: str, state: str, **fields) -> bool:
        """Record only when ``state`` differs from the last recorded
        state of this ``kind`` — the health poll calls this on every
        scrape and only transitions land in the ring.  Returns whether
        an event was recorded."""
        with self._lock:
            last = self._last_by_kind.get(kind)
            if last is not None and last.get("state") == state:
                return False
        self.record(kind, state=state, **fields)
        return True

    def anomaly(self, kind: str, total_us: float,
                stages: Optional[dict] = None, **fields) -> None:
        """Snapshot a slow dispatch: its stage breakdown plus the last
        ``context_events`` ring events (what the system was doing when
        the tail happened)."""
        with self._lock:
            entry = {
                "seq": self._seq,
                "t_ms": time.time_ns() // 1_000_000,
                "kind": kind,
                "total_us": round(float(total_us), 1),
                "slo_us": self._slo_us,
                "context": self._ordered_locked()[-self._context_events:],
            }
            if stages:
                entry["stages_us"] = {
                    k: round(float(v), 1) for k, v in stages.items()}
            if fields:
                entry.update(fields)
            self._anomalies.append(entry)
            self._anomaly_total += 1
            if len(self._anomalies) > self._anomaly_capacity:
                del self._anomalies[0]

    def note_dispatch(self, total_us: float, stages: Optional[dict] = None,
                      **fields) -> None:
        """The per-dispatch anomaly hook: one float compare when the SLO
        threshold is unarmed or met; a full snapshot when exceeded."""
        if self._slo_us > 0.0 and total_us > self._slo_us:
            self.anomaly("slow_dispatch", total_us, stages, **fields)

    # -- reading --------------------------------------------------------------
    def _ordered_locked(self) -> List[dict]:
        return [e for e in (self._events[self._next:]
                            + self._events[:self._next]) if e is not None]

    def mark(self) -> int:
        """Current sequence number — drills snapshot it, then assert on
        ``events(since=mark)``."""
        with self._lock:
            return self._seq

    def events(self, kind: Optional[str] = None,
               since: int = -1,
               since_ms: Optional[int] = None) -> List[dict]:
        """Ring events in order, optionally filtered by kind prefix, by
        ``seq >= since``, and by wall-clock ``t_ms >= since_ms``."""
        with self._lock:
            out = self._ordered_locked()
        if since >= 0:
            out = [e for e in out if e["seq"] >= since]
        if since_ms is not None:
            out = [e for e in out if e["t_ms"] >= since_ms]
        if kind is not None:
            out = [e for e in out if e["kind"] == kind
                   or e["kind"].startswith(kind + ".")]
        return out

    def snapshot(self, last: int = 256, kind: Optional[str] = None,
                 since_ms: Optional[int] = None) -> dict:
        """Full payload for ``GET /actuator/flightrecorder``; ``kind``
        (exact or dotted prefix) and ``since_ms`` filter ring-side so an
        incident query returns only the relevant slice, not the whole
        ring for the client to sift."""
        filtered = kind is not None or since_ms is not None
        with self._lock:
            events = self._ordered_locked()
            anomalies = list(self._anomalies)
            total = self._seq
        if filtered:
            if since_ms is not None:
                events = [e for e in events if e["t_ms"] >= since_ms]
                anomalies = [a for a in anomalies
                             if a["t_ms"] >= since_ms]
            if kind is not None:
                events = [e for e in events if e["kind"] == kind
                          or e["kind"].startswith(kind + ".")]
                anomalies = [a for a in anomalies if a["kind"] == kind
                             or a["kind"].startswith(kind + ".")]
        out = {
            "total_events": total,
            "capacity": self._capacity,
            "slo_ms": self._slo_us / 1000.0,
            "events": events[-last:],
            "anomaly_total": self._anomaly_total,
            "anomalies": anomalies,
        }
        if filtered:
            out["filtered"] = {"kind": kind, "since_ms": since_ms,
                               "matched": len(events)}
        return out

    def reset(self) -> None:
        """Drop everything (test isolation for the global instance)."""
        with self._lock:
            self._events = [None] * self._capacity
            self._next = 0
            self._seq = 0
            self._anomalies = []
            self._anomaly_total = 0
            self._last_by_kind.clear()


_GLOBAL = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    """The process-global recorder (see module docstring)."""
    return _GLOBAL
