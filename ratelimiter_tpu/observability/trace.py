"""Request-lifecycle tracing: enqueue -> assembly -> device -> resolve.

The micro-batcher stamps each submitted request with a monotonic
timestamp; the dispatch/drain pipeline adds three more (batch taken,
dispatch enqueued, device results fetched, futures resolved).  This
module aggregates those stamps into the per-stage histograms the
latency-SLO work (ROADMAP item 3) needs:

- ``ratelimiter.latency.queue_wait`` — submit until the flusher took the
  batch (per request; the adaptive-flush controller's feedback signal),
- ``ratelimiter.latency.assembly``   — take until the device dispatch
  call returned (host-side batch build, per batch),
- ``ratelimiter.latency.device``     — dispatch until the blocking fetch
  returned (per batch),
- ``ratelimiter.latency.resolve``    — fetch until every waiter's future
  was resolved (per batch),
- ``ratelimiter.latency.total``      — submit to resolve (per request).

The four stages telescope: queue_wait + assembly + device + resolve ==
total for the oldest request of a batch, by construction — the
trace-propagation test asserts it.

**Sampling.**  With ``sample_n > 0`` (config
``ratelimiter.obs.trace_sample``), one request per ~N is recorded as a
full per-request trace into the enriched ``DecisionTrace`` ring
(``utils/tracing.py``): stage breakdown, dispatch path, micro-batch
size — scraped at ``/actuator/trace``.

**Anomaly hook.**  A batch whose oldest request exceeded the flight
recorder's SLO threshold snapshots its stage breakdown plus recent ring
events (``FlightRecorder.note_dispatch``).

**Stream dispatch routes.**  The streaming loops bypass the batcher, so
their lifecycle lives in the ``ratelimiter.stream.*`` stage timers
(route/pack/index/layout/enqueue/fetch — per shard on the sharded path)
instead of the histograms above; every stream dispatch still records
its route into the same ``DecisionTrace`` ring (``relay|digest``,
``flat``, ``sharded|digest`` / ``sharded|words`` with its shard id, …)
and feeds the same slow-dispatch anomaly hook, so one
``/actuator/trace`` read shows which path — micro, flat, or a specific
shard's lane — a slow decision took (ARCHITECTURE §6c, §13).
"""

from __future__ import annotations

from typing import Optional, Sequence

STAGES = ("queue_wait", "assembly", "device", "resolve", "total")

#: Assembly sub-stages (r11), mirroring the stream path's
#: pack/index/layout split: where inside the assembly stage a
#: micro-batch's microseconds go.  ``pack`` = host staging-buffer
#: finalize + eviction clears at take, ``index`` = per-request key->slot
#: assignment (recorded at submit, the only per-request piece),
#: ``layout`` = device placement + step enqueue.
ASSEMBLY_SUBSTAGES = ("pack", "index", "layout")


class LatencyTracer:
    """Aggregates batcher lifecycle timestamps into stage histograms."""

    def __init__(self, registry, trace=None, sample_n: int = 0,
                 recorder=None, lineage=None):
        self._h = {
            stage: registry.timer(
                f"ratelimiter.latency.{stage}",
                f"Request lifecycle: {stage} stage (us)")
            for stage in STAGES
        }
        self._sub = {
            stage: registry.timer(
                f"ratelimiter.latency.assembly.{stage}",
                f"Micro-batch assembly sub-stage: {stage} (us)")
            for stage in ASSEMBLY_SUBSTAGES
        }
        self._trace = trace
        self._sample_n = max(int(sample_n), 0)
        self._tick = 0          # requests since the last sampled trace
        self._recorder = recorder
        # Trace-id lineage ring (observability/telemetry.TraceLineage):
        # sampled ids get per-hop records (batcher/shard/resolve) so a
        # trace minted at ingress reads as an ordered path.
        self._lineage = lineage

    def record_sub(self, stage: str, us: float) -> None:
        """One assembly sub-stage sample (storage dispatch path)."""
        self._sub[stage].record_us(us)

    def observe_batch(self, algo: str, out: Optional[dict],
                      t_subs: Sequence[float], t_take: float,
                      t_disp: float, t_dev: float, t_res: float,
                      trace_ids: Optional[Sequence[int]] = None) -> None:
        """One dispatched-and-resolved batch's stamps.  Runs on the
        drain thread AFTER the waiters' futures resolved — nothing here
        is on a caller's critical path.  ``trace_ids`` (aligned with
        ``t_subs``; 0 = untraced) feed the lineage ring and enrich the
        sampled DecisionTrace with the trace the batch carried."""
        n = len(t_subs)
        if n == 0:
            return
        h = self._h
        h["assembly"].record_us((t_disp - t_take) * 1e6)
        h["device"].record_us((t_dev - t_disp) * 1e6)
        h["resolve"].record_us((t_res - t_dev) * 1e6)
        qh, th = h["queue_wait"], h["total"]
        for t0 in t_subs:
            qh.record_us((t_take - t0) * 1e6)
            th.record_us((t_res - t0) * 1e6)

        # Oldest request = the batch's worst case; it feeds both the
        # sampler and the SLO anomaly hook.
        t_oldest = min(t_subs)
        stages_us = {
            "queue_wait": (t_take - t_oldest) * 1e6,
            "assembly": (t_disp - t_take) * 1e6,
            "device": (t_dev - t_disp) * 1e6,
            "resolve": (t_res - t_dev) * 1e6,
        }
        total_us = (t_res - t_oldest) * 1e6

        sampled_tids = []
        lin = self._lineage
        if lin is not None and trace_ids:
            sampled_tids = [t for t in trace_ids if t and lin.sampled(t)]
            for i, tid in enumerate(trace_ids):
                if not tid or tid not in sampled_tids:
                    continue
                lin.record(tid, "batcher", algo=algo, batch=n,
                           queue_wait_us=round(
                               (t_take - t_subs[i]) * 1e6, 1),
                           assembly_us=round(
                               (t_disp - t_take) * 1e6, 1))
                lin.record(tid, "shard", path="micro", shard=0,
                           device_us=round((t_dev - t_disp) * 1e6, 1))
                lin.record(tid, "resolve",
                           total_us=round((t_res - t_subs[i]) * 1e6, 1))

        if self._sample_n and self._trace is not None:
            self._tick += n
            if self._tick >= self._sample_n:
                self._tick = 0
                allowed = -1
                if out is not None and "allowed" in out:
                    allowed = int(sum(1 for a in out["allowed"] if a))
                extra = {}
                if sampled_tids:
                    from ratelimiter_tpu.observability.telemetry import (
                        trace_hex,
                    )

                    extra["trace"] = trace_hex(sampled_tids[0])
                self._trace.record(
                    algo, n, allowed, total_us, path="micro",
                    stages_us={k: round(v, 1)
                               for k, v in stages_us.items()},
                    **extra)

        if self._recorder is not None:
            self._recorder.note_dispatch(total_us, stages_us,
                                         algo=algo, batch=n, path="micro")
