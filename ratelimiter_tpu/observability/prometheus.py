"""Prometheus text exposition (format 0.0.4) over the MeterRegistry.

Mapping (Micrometer-convention names like ``ratelimiter.storage.latency``
sanitize to ``ratelimiter_storage_latency``):

- ``Counter`` -> ``# TYPE <name>_total counter`` + one sample,
- ``Gauge``   -> ``# TYPE <name> gauge`` + one sample,
- ``Timer``   -> ``# TYPE <name>_seconds histogram``: cumulative
  ``_bucket{le="..."}`` lines from the log2 buckets (converted us ->
  seconds, the Prometheus base unit), ``_sum`` and ``_count``.  Bucket
  lines stop at the highest non-empty bucket; the mandatory
  ``le="+Inf"`` line always carries the full count.

``# HELP`` comes from the meter's registered description when one was
given, else from the :data:`METRIC_HELP` description table — so a meter
registered at a call site that omitted the description still documents
itself on the scrape.  HELP text escapes ``\\`` and newlines per the
exposition format.

**Labeled series.**  The registry's meters are unlabeled; per-tenant /
per-key-class series come from *collectors* — objects exposing
``prometheus_samples() -> [(name, kind, help, [(labels, value)])]``
(e.g. ``observability/telemetry.TelemetryPlane``).  Label VALUES are
escaped (``\\`` -> ``\\\\``, ``\"`` -> ``\\\"``, newline -> ``\\n``):
key-class labels arrive off the wire and must not be able to break the
exposition syntax.

The golden test (tests/test_observability.py) pins the exact output
shape; bucket monotonicity and ``_sum``/``_count`` consistency are
asserted over a live registry scrape.
"""

from __future__ import annotations

import re
from typing import List

from ratelimiter_tpu.metrics.registry import Counter, Gauge, Timer

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")

#: Fallback HELP text by metric name, used when the meter was registered
#: without a description.  Keep entries for names that are (or were)
#: registered description-less somewhere — a missing entry just means
#: the name echoes as its own HELP.
METRIC_HELP = {
    "ratelimiter.requests.allowed": "Sliding-window decisions allowed",
    "ratelimiter.requests.rejected": "Sliding-window decisions rejected",
    "ratelimiter.tokenbucket.allowed": "Token-bucket decisions allowed",
    "ratelimiter.tokenbucket.rejected": "Token-bucket decisions rejected",
    "ratelimiter.cache.hits": "Local TTL-cache hits",
    "ratelimiter.storage.latency":
        "Device dispatch latency (per micro-batch)",
    "ratelimiter.decisions.allowed":
        "Fleet-wide allowed decisions (server + degraded + lease-local)",
    "ratelimiter.decisions.denied": "Fleet-wide denied decisions",
    "ratelimiter.decisions.shed":
        "Decisions refused by admission control",
    "ratelimiter.decisions.lease_local":
        "Fleet decisions decided client-side against token leases",
    "ratelimiter.telemetry.staleness_ms":
        "Age of the oldest client's last telemetry report",
}


def _metric_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label_value(value: str) -> str:
    """Exposition-format label-value escaping — label values (key
    classes!) come off the wire and may contain anything."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_metric_name(str(k))}="{_escape_label_value(v)}"'
        for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def _help_for(name: str, description: str) -> str:
    return _escape_help(description or METRIC_HELP.get(name, name))


def _fmt(value: float) -> str:
    # Integral values print without a trailing .0 — bucket counts are
    # counts; +Inf/NaN spellings follow the exposition format.
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _le(bound_us: float) -> str:
    if bound_us == float("inf"):
        return "+Inf"
    return _fmt(bound_us / 1e6)


def render(registry, collectors=()) -> str:
    """The full exposition document for ``GET /actuator/prometheus``.

    ``collectors`` append labeled sample families after the registry's
    meters (see module docstring)."""
    lines: List[str] = []
    meters = registry.meters()
    for name in sorted(meters):
        meter = meters[name]
        base = _metric_name(name)
        help_text = _help_for(name, meter.description)
        if isinstance(meter, Counter):
            lines.append(f"# HELP {base}_total {help_text}")
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {_fmt(meter.count())}")
        elif isinstance(meter, Gauge):
            lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {_fmt(meter.value())}")
        elif isinstance(meter, Timer):
            lines.extend(_render_timer(base, help_text, meter))
    for collector in collectors:
        for name, kind, help_text, samples in collector.prometheus_samples():
            base = _metric_name(name)
            if kind == "counter":
                base += "_total"
            lines.append(f"# HELP {base} {_escape_help(help_text or name)}")
            lines.append(f"# TYPE {base} {kind}")
            for labels, value in samples:
                lines.append(f"{base}{_labels(labels)} {_fmt(value)}")
    return "\n".join(lines) + "\n" if lines else ""


def _render_timer(base: str, help_text: str, timer: Timer) -> List[str]:
    name = f"{base}_seconds"
    counts = timer.bucket_counts()
    bounds = timer.bucket_bounds_us()
    total = sum(counts)
    # Highest non-empty bucket bounds the emitted ladder (64 lines of
    # zeros per timer would dominate the document); +Inf always closes.
    top = max((i for i, c in enumerate(counts) if c), default=-1)
    lines = [f"# HELP {name} {help_text}",
             f"# TYPE {name} histogram"]
    cum = 0
    for i in range(min(top + 1, len(bounds) - 1)):
        cum += counts[i]
        lines.append(
            f'{name}_bucket{{le="{_le(bounds[i])}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
    lines.append(f"{name}_sum {_fmt(timer.total_us() / 1e6)}")
    lines.append(f"{name}_count {total}")
    return lines
