"""Prometheus text exposition (format 0.0.4) over the MeterRegistry.

Mapping (Micrometer-convention names like ``ratelimiter.storage.latency``
sanitize to ``ratelimiter_storage_latency``):

- ``Counter`` -> ``# TYPE <name>_total counter`` + one sample,
- ``Gauge``   -> ``# TYPE <name> gauge`` + one sample,
- ``Timer``   -> ``# TYPE <name>_seconds histogram``: cumulative
  ``_bucket{le="..."}`` lines from the log2 buckets (converted us ->
  seconds, the Prometheus base unit), ``_sum`` and ``_count``.  Bucket
  lines stop at the highest non-empty bucket; the mandatory
  ``le="+Inf"`` line always carries the full count.

HELP text escapes ``\\`` and newlines per the exposition format.  The
golden test (tests/test_observability.py) pins the exact output shape;
bucket monotonicity and ``_sum``/``_count`` consistency are asserted
over a live registry scrape.
"""

from __future__ import annotations

import re
from typing import List

from ratelimiter_tpu.metrics.registry import Counter, Gauge, Timer

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_help(text: str) -> str:
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(value: float) -> str:
    # Integral values print without a trailing .0 — bucket counts are
    # counts; +Inf/NaN spellings follow the exposition format.
    if value != value:
        return "NaN"
    if value == float("inf"):
        return "+Inf"
    if value == float("-inf"):
        return "-Inf"
    if float(value).is_integer() and abs(value) < 1e15:
        return str(int(value))
    return repr(float(value))


def _le(bound_us: float) -> str:
    if bound_us == float("inf"):
        return "+Inf"
    return _fmt(bound_us / 1e6)


def render(registry) -> str:
    """The full exposition document for ``GET /actuator/prometheus``."""
    lines: List[str] = []
    meters = registry.meters()
    for name in sorted(meters):
        meter = meters[name]
        base = _metric_name(name)
        help_text = _escape_help(meter.description or name)
        if isinstance(meter, Counter):
            lines.append(f"# HELP {base}_total {help_text}")
            lines.append(f"# TYPE {base}_total counter")
            lines.append(f"{base}_total {_fmt(meter.count())}")
        elif isinstance(meter, Gauge):
            lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} gauge")
            lines.append(f"{base} {_fmt(meter.value())}")
        elif isinstance(meter, Timer):
            lines.extend(_render_timer(base, help_text, meter))
    return "\n".join(lines) + "\n" if lines else ""


def _render_timer(base: str, help_text: str, timer: Timer) -> List[str]:
    name = f"{base}_seconds"
    counts = timer.bucket_counts()
    bounds = timer.bucket_bounds_us()
    total = sum(counts)
    # Highest non-empty bucket bounds the emitted ladder (64 lines of
    # zeros per timer would dominate the document); +Inf always closes.
    top = max((i for i, c in enumerate(counts) if c), default=-1)
    lines = [f"# HELP {name} {help_text}",
             f"# TYPE {name} histogram"]
    cum = 0
    for i in range(min(top + 1, len(bounds) - 1)):
        cum += counts[i]
        lines.append(
            f'{name}_bucket{{le="{_le(bounds[i])}"}} {cum}')
    lines.append(f'{name}_bucket{{le="+Inf"}} {total}')
    lines.append(f"{name}_sum {_fmt(timer.total_us() / 1e6)}")
    lines.append(f"{name}_count {total}")
    return lines
