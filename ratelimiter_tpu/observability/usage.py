"""Per-tenant usage accounting: a sliding multi-resolution time ring.

ROADMAP item 3's adaptive per-tenant controller (AIMD/PID first, RL
later) needs fresh per-tenant observed-load / shed / goodput signals —
and PR 12's token leases moved most decisions OFF the server, so those
signals can no longer be derived from server dispatches alone.  This
module is the aggregation point: every decision source feeds one ring —

- server-side dispatches (micro drains + stream chunks,
  ``storage/tpu.py:_record_dispatch`` / the staged drainer),
- degraded-path decisions (``storage/degraded.py``),
- admission-control sheds (batcher queue_full/deadline, sidecar
  pipeline cap),
- client-reported lease burns (telemetry reports,
  ``observability/telemetry.py``),

so per-tenant rates are fleet-true again regardless of where the
decision ran.

**Shape.**  Per tenant (= limiter id, the device policy-table row), one
fixed bucket ring per resolution — 1 s x 64, 10 s x 64, 60 s x 64 by
default — each bucket a 4-vector (admitted, denied, shed, lease_local)
stamped with its epoch (``now // bucket_ms``).  ``record`` is O(1):
one epoch compare + one vector add per resolution (a stale bucket is
zeroed in place when its epoch rotates — no sweeper thread, no
allocation after the first touch).  Memory is fixed:
``max_tenants * sum(slots) * 4`` int64s; tenants over the cap are
counted in ``dropped_tenants`` and not tracked (the controller can
only actuate rows it observes — a silent cap would read as zero load).

**Exactness.**  A bucket only counts toward a window when its stamped
epoch is inside the window's epoch range, so overwritten-but-stale
slots can never leak old counts into a fresh window —
``tests/test_telemetry.py`` asserts window sums equal a brute-force
recount of the raw event log across rotations and long clock jumps.

Exported at ``GET /actuator/tenants``, as labeled Prometheus series
(via ``TelemetryPlane.prometheus_samples``), and programmatically as
:class:`UsageSignals` — the observation contract the item-3 controller
consumes (ARCHITECTURE §13e).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import numpy as np

#: Bucketed fields, in ring order.
FIELDS = ("admitted", "denied", "shed", "lease_local")
_NF = len(FIELDS)

#: Default resolutions: (bucket_ms, n_buckets) — 64 s of 1 s buckets,
#: ~10 min of 10 s buckets, ~1 h of 60 s buckets.
RESOLUTIONS: Tuple[Tuple[int, int], ...] = (
    (1_000, 64), (10_000, 64), (60_000, 64))


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class UsageSignals(NamedTuple):
    """One tenant's observation vector — the contract the adaptive
    per-tenant controller (ROADMAP item 3) consumes.  Counts cover the
    last ``window_s`` seconds (bucket-aligned); rates are counts /
    window_s.  ``observed_load`` is everything the tenant ASKED for
    (admitted + denied + shed, /s); ``goodput`` is what it got
    (admitted, /s).  ``lease_local`` is the subset of ``admitted``
    decided client-side against leases — included in ``admitted``, so
    the totals stay fleet-true under leases."""

    tenant: int
    window_s: float
    admitted: int
    denied: int
    shed: int
    lease_local: int
    admitted_rate: float
    denied_rate: float
    shed_rate: float
    lease_local_rate: float
    observed_load: float
    goodput: float


class _TenantRing:
    """One tenant's buckets: per resolution, counts[slots, 4] + epoch
    stamps; plus lifetime totals."""

    __slots__ = ("counts", "epochs", "totals")

    def __init__(self, resolutions):
        self.counts = [np.zeros((n, _NF), dtype=np.int64)
                       for _, n in resolutions]
        self.epochs = [np.full(n, -1, dtype=np.int64)
                       for _, n in resolutions]
        self.totals = np.zeros(_NF, dtype=np.int64)


class UsageRing:
    """Sliding multi-resolution per-tenant usage accounting."""

    def __init__(self, clock_ms=None, max_tenants: int = 256,
                 resolutions: Sequence[Tuple[int, int]] = RESOLUTIONS):
        self._clock_ms = clock_ms or _wall_ms
        self._res = tuple((int(b), int(n)) for b, n in resolutions)
        if not self._res:
            raise ValueError("usage ring needs at least one resolution")
        self.max_tenants = max(int(max_tenants), 1)
        self._tenants: Dict[int, _TenantRing] = {}
        self._lock = threading.Lock()
        self.dropped_tenants = 0   # records refused over max_tenants
        self.recorded_total = 0

    # -- recording -------------------------------------------------------------
    def record(self, tenant: int, admitted: int = 0, denied: int = 0,
               shed: int = 0, lease_local: int = 0,
               now_ms: Optional[int] = None) -> bool:
        """Fold one batch of decisions into the tenant's buckets.
        O(1): one epoch check + vector add per resolution.  Returns
        False when the tenant cap refused a NEW tenant."""
        if not (admitted or denied or shed or lease_local):
            return True
        now = int(self._clock_ms() if now_ms is None else now_ms)
        vec = (int(admitted), int(denied), int(shed), int(lease_local))
        with self._lock:
            ring = self._tenants.get(int(tenant))
            if ring is None:
                if len(self._tenants) >= self.max_tenants:
                    self.dropped_tenants += 1
                    return False
                ring = _TenantRing(self._res)
                self._tenants[int(tenant)] = ring
            for r, (bucket_ms, slots) in enumerate(self._res):
                epoch = now // bucket_ms
                i = epoch % slots
                if ring.epochs[r][i] != epoch:
                    ring.counts[r][i] = 0
                    ring.epochs[r][i] = epoch
                ring.counts[r][i] += vec
            ring.totals += vec
            self.recorded_total += 1
        return True

    # -- reading ---------------------------------------------------------------
    def _pick_res(self, window_ms: int) -> int:
        """Finest resolution whose ring spans the window (else the
        coarsest)."""
        for r, (bucket_ms, slots) in enumerate(self._res):
            if bucket_ms * slots >= window_ms:
                return r
        return len(self._res) - 1

    def window_counts(self, tenant: int, window_ms: int,
                      now_ms: Optional[int] = None):
        """Counts over the trailing window: every bucket whose epoch
        falls in the last ``ceil(window/bucket)`` epochs INCLUDING the
        current (partial) one.  Returns ``(counts_dict, covered_ms)``
        — ``covered_ms`` is the bucket-aligned span actually summed,
        the denominator for exact rates."""
        now = int(self._clock_ms() if now_ms is None else now_ms)
        r = self._pick_res(int(window_ms))
        bucket_ms, slots = self._res[r]
        k = min(max(-(-int(window_ms) // bucket_ms), 1), slots)
        e_now = now // bucket_ms
        with self._lock:
            ring = self._tenants.get(int(tenant))
            if ring is None:
                vec = np.zeros(_NF, dtype=np.int64)
            else:
                live = ring.epochs[r] > (e_now - k)
                # epochs are stamped at record time and never run ahead
                # of the recorder's clock; with a monotonic clock the
                # upper bound is implied, but guard it anyway so an
                # injected-clock test stepping backwards can't read
                # future buckets.
                live &= ring.epochs[r] <= e_now
                vec = ring.counts[r][live].sum(axis=0)
        counts = {f: int(vec[i]) for i, f in enumerate(FIELDS)}
        return counts, k * bucket_ms

    def signals(self, tenant: int, window_ms: int = 10_000,
                now_ms: Optional[int] = None) -> UsageSignals:
        counts, covered_ms = self.window_counts(tenant, window_ms, now_ms)
        w = covered_ms / 1000.0
        adm, den = counts["admitted"], counts["denied"]
        shed, local = counts["shed"], counts["lease_local"]
        return UsageSignals(
            tenant=int(tenant), window_s=w,
            admitted=adm, denied=den, shed=shed, lease_local=local,
            admitted_rate=adm / w, denied_rate=den / w,
            shed_rate=shed / w, lease_local_rate=local / w,
            observed_load=(adm + den + shed) / w,
            goodput=adm / w,
        )

    def all_signals(self, window_ms: int = 10_000,
                    now_ms: Optional[int] = None) -> Dict[int, UsageSignals]:
        """The controller's observation sweep: one UsageSignals per
        tracked tenant."""
        with self._lock:
            tenants = list(self._tenants)
        return {t: self.signals(t, window_ms, now_ms) for t in tenants}

    def tenants(self):
        with self._lock:
            return sorted(self._tenants)

    def totals(self, tenant: int) -> Dict[str, int]:
        with self._lock:
            ring = self._tenants.get(int(tenant))
            vec = (np.zeros(_NF, dtype=np.int64) if ring is None
                   else ring.totals.copy())
        return {f: int(vec[i]) for i, f in enumerate(FIELDS)}

    def snapshot(self, now_ms: Optional[int] = None) -> Dict:
        """The ``GET /actuator/tenants`` payload body: per tenant,
        lifetime totals plus rates at each configured resolution's
        natural window (one full bucket span of the finest, 10 buckets
        of each coarser one — enough to see a storm and its decay)."""
        now = int(self._clock_ms() if now_ms is None else now_ms)
        out: Dict[str, Dict] = {}
        for t in self.tenants():
            entry: Dict = {"totals": self.totals(t)}
            for bucket_ms, _slots in self._res:
                window = bucket_ms * 10
                counts, covered = self.window_counts(t, window, now)
                entry[f"last_{window // 1000}s"] = {
                    **counts,
                    "rate_per_s": {f: round(c / (covered / 1000.0), 3)
                                   for f, c in counts.items()},
                }
            out[str(t)] = entry
        return {"tenants": out, "dropped_tenants": self.dropped_tenants,
                "resolutions_ms": [b for b, _ in self._res]}
