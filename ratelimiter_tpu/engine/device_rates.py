"""Measured device step rates for the stream loops' cost models.

The chunk-plan election and the words-vs-digest mode election charge
the device step explicitly (storage/tpu.py).  Through r4 those charges
were constants measured once on a v5e dev chip and frozen into source —
wrong on any other TPU generation, and badly wrong on the CPU devices
the test suite and the local-latency bench run on (VERDICT r4 #5).

This module measures them at runtime: a short chained-step probe (the
same chain-K-steps-in-one-jit, fetch-one-checksum, subtract-RTT method
as bench/device_only.py, shrunk to ~0.1-0.3 s of device time) run once
per (platform, device kind) and cached

- in-process (module dict), and
- on disk next to the compile cache (device_rates_<platform>_<kind>.json)
  so later processes skip the probe entirely.

``RATELIMITER_RATE_PROBE=0`` disables probing (the v5e fallback
constants below are used); probing also falls back on any error.
Rates are returned as a dict
``{"s_per_lane", "s_per_unique_sorted", "s_per_unique_unsorted"}``.
The probed artifact additionally carries ``probed_at_ms`` and the
device kind so BENCH_DETAIL can record exactly what the elections ran
on (VERDICT r4 #5 "Done" criterion).
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional

# v5e dev-chip measurements (ROUND_NOTES r4, bench/device_only.py):
# relay words step 58 ns/lane; digest counts step 24.6 ns/unique through
# the dense presorted sweep, 52.2 ns through XLA's per-index scatter.
FALLBACK_RATES: Dict[str, float] = {
    "s_per_lane": 60e-9,
    "s_per_unique_sorted": 25e-9,
    "s_per_unique_unsorted": 52e-9,
}

_mem_cache: Dict[str, Dict] = {}


def _cache_path(platform: str, kind: str) -> Optional[str]:
    try:
        import jax

        base = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001
        base = None
    if not base:
        from ratelimiter_tpu.utils.compile_cache import default_cache_dir

        base = default_cache_dir()
    safe_kind = "".join(ch if ch.isalnum() else "_" for ch in kind)[:40]
    return os.path.join(base, f"device_rates_{platform}_{safe_kind}.json")


def _probe() -> Dict[str, float]:
    """Measure the three step rates on the default device (~0.1-0.3 s
    of device time + one compile per step shape, disk-cached)."""
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ratelimiter_tpu import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.ops import relay
    from ratelimiter_tpu.ops.token_bucket import make_tb_packed

    num_slots = 1 << 19
    lanes = 1 << 17
    k_steps = 16
    table = LimiterTable()
    lid = table.register(RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0))
    tarr = table.device_arrays
    lid_dev = jnp.int32(lid)
    rb = 8

    tiny = jax.jit(lambda v: v.sum())
    np.asarray(tiny(jnp.zeros(8, jnp.int32)))
    t0 = time.perf_counter()
    for _ in range(2):
        np.asarray(tiny(jnp.zeros(8, jnp.int32)))
    rtt_s = (time.perf_counter() - t0) / 2

    base = np.arange(lanes, dtype=np.uint32) * (num_slots // lanes)
    shuf = np.random.default_rng(9).permutation(base).astype(np.uint32)

    def chain(step_fn):
        @functools.partial(jax.jit, donate_argnums=0)
        def run(packed, now0):
            def body(i, carry):
                packed, acc = carry
                packed, out = step_fn(packed, now0 + i)
                return packed, acc + jnp.sum(out.astype(jnp.int64))

            packed, acc = jax.lax.fori_loop(0, k_steps, body,
                                            (packed, jnp.int64(0)))
            return packed, acc

        return run

    words = jnp.asarray((base << np.uint32(rb + 1)) | np.uint32(1))
    uw_sorted = jnp.asarray((base << np.uint32(rb + 1))
                            | np.uint32(1 << 1))
    uw_shuf = jnp.asarray((shuf << np.uint32(rb + 1)) | np.uint32(1 << 1))

    def relay_step(packed, now):
        return relay.tb_relay_bits(packed, tarr, words, lid_dev, now,
                                   rank_bits=rb)

    def digest_step(uw, sorted_flag):
        def step(packed, now):
            return relay.tb_relay_counts(
                packed, tarr, uw, lid_dev, now, rank_bits=rb,
                out_dtype=jnp.uint8, slots_sorted=sorted_flag)

        return step

    def measure(step_fn) -> float:
        fn = chain(step_fn)
        packed, acc = fn(make_tb_packed(num_slots), jnp.int64(1_000_000))
        int(np.asarray(acc))  # compile + settle
        t0 = time.perf_counter()
        packed, acc = fn(packed, jnp.int64(2_000_000))
        int(np.asarray(acc))
        dt = time.perf_counter() - t0
        return max(dt - rtt_s, 1e-6) / (k_steps * lanes)

    from ratelimiter_tpu.ops.pallas import block_scatter
    from ratelimiter_tpu.ops.pallas import relay_step as fused_relay

    rates = {
        "s_per_lane": measure(relay_step),
        "s_per_unique_unsorted": measure(digest_step(uw_shuf, False)),
    }
    if block_scatter.enabled((num_slots, 2), lanes):
        rates["s_per_unique_sorted"] = measure(digest_step(uw_sorted, True))
    else:  # sorted sweep can't engage on this backend: same cost
        rates["s_per_unique_sorted"] = rates["s_per_unique_unsorted"]
    # Fused Pallas relay step (per-path election; ops/pallas/relay_step):
    # when it is elected on this device the engine's sorted digest
    # dispatch actually RUNS it, so the sorted rate the stream elections
    # charge must be the better of the two — both raw rates stay
    # recorded so BENCH_DETAIL shows what the election saw.
    if fused_relay.enabled((num_slots, 4), lanes, rb):
        def fused_step(packed, now):
            return fused_relay.tb_relay_counts_fused(
                packed, tarr, uw_sorted, lid_dev, now, rank_bits=rb,
                interpret=fused_relay.interpret_mode())

        rates["s_per_unique_fused"] = measure(fused_step)
        rates["s_per_unique_sorted"] = min(rates["s_per_unique_sorted"],
                                           rates["s_per_unique_fused"])
    return rates


def get_device_rates() -> Dict:
    """Rates for the default jax backend, probing + caching as
    documented in the module docstring.  Never raises."""
    try:
        import jax

        dev = jax.devices()[0]
        platform = dev.platform
        kind = getattr(dev, "device_kind", platform)
    except Exception:  # noqa: BLE001 — no backend at all
        return dict(FALLBACK_RATES, source="fallback")
    key = f"{platform}/{kind}"
    hit = _mem_cache.get(key)
    if hit is not None:
        return hit
    # The opt-out must beat the disk cache: tests (and any run pinning
    # deterministic election inputs) set RATELIMITER_RATE_PROBE=0 and
    # must get the fallback constants even when an earlier bench run
    # left a probe artifact on this host.
    if os.environ.get("RATELIMITER_RATE_PROBE", "1") == "0":
        rates = dict(FALLBACK_RATES, source="fallback", device=key)
        _mem_cache[key] = rates
        return rates
    path = _cache_path(platform, kind)
    if path and os.path.exists(path):
        try:
            with open(path, "r", encoding="utf-8") as fh:
                rates = json.load(fh)
            if all(k in rates for k in FALLBACK_RATES):
                _mem_cache[key] = rates
                return rates
        except Exception:  # noqa: BLE001 — corrupt cache: re-probe
            pass
    try:
        rates = dict(_probe(), source="probe", device=key,
                     probed_at_ms=int(time.time() * 1000))
    except Exception:  # noqa: BLE001 — probe failed: fall back
        rates = dict(FALLBACK_RATES, source="fallback", device=key)
        _mem_cache[key] = rates
        return rates
    _mem_cache[key] = rates
    if path:
        try:
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(rates, fh)
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — disk cache is best-effort
            pass
    return rates
