"""Shared engine error types.

``SlotCapacityError`` is raised when a batch slot assignment cannot place
every key (all slots pinned).  The C walk is not transactional: lanes
processed before the failing one WERE assigned — their evicted slots are
already remapped to new keys in the index, so their device state must be
zeroed before any reuse or a later acquire of a newly mapped key would
read the evicted key's stale counters.  ``pending_clears`` carries those
evictions (slot ids local to the raising index) up to the storage layer,
which routes them through ``_clear_slots`` exactly as the success path
does (reference analog: the Redis backend's retry wrapper surfaces every
failure as StorageException AFTER the partial pipeline effects are
already durable — storage/RedisRateLimitStorage.java:155-178).
"""

from __future__ import annotations

import numpy as np


class OverloadedError(RuntimeError):
    """A request was shed by admission control instead of queued.

    Raised by ``MicroBatcher.submit`` when the bounded pending queue is
    full (``reason="queue_full"``), by the dispatch/watchdog path when a
    queued request's deadline budget expires before it can be dispatched
    (``reason="deadline"``), and when the flusher thread has died and
    nothing will ever dispatch the queue (``reason="flusher_dead"``).

    Deliberately NOT a ``StorageException``: shedding is a local
    admission decision, not a backend fault — it must not be retried
    (retrying amplifies the overload), must not trip the circuit
    breaker, and must not be converted into a fail-open allow.  The
    service tier maps it to 429 with a Retry-After header.
    """

    def __init__(self, msg: str, reason: str = "overloaded",
                 retry_after_ms: float = 0.0):
        super().__init__(msg)
        self.reason = reason
        self.retry_after_ms = float(retry_after_ms)


class ShutdownError(RuntimeError):
    """The batcher (or a component above it) is closed: the request was
    refused at submit, or a still-pending future was failed by
    ``MicroBatcher.close()`` instead of being left blocked forever on
    ``Future.result()``."""


class SlotCapacityError(RuntimeError):
    """Batch assignment ran out of evictable slots.

    ``pending_clears``: int32 slot ids (local to the index that raised)
    whose device state must be cleared — evictions applied by the lanes
    that succeeded before the failure.  Consumers that clear them should
    set the attribute to ``None`` so a re-raise through nested handlers
    cannot double-clear.
    """

    def __init__(self, msg: str, pending_clears=None):
        super().__init__(msg)
        self.pending_clears = (
            np.asarray(pending_clears, dtype=np.int64)
            if pending_clears is not None and len(pending_clears)
            else None)


def consume_pending_clears(exc, base: int = 0) -> list:
    """Extract an exception's ``pending_clears`` as a list of GLOBAL slot
    ids (each local id offset by ``base``) and null the attribute, so the
    same raise passing through nested handlers cannot double-clear.  The
    caller takes over responsibility for actually clearing what it got —
    use this where the clears from several sub-indexes are pooled and
    cleared in one call; a handler that clears inline should instead
    clear FIRST and null the attribute only after the clear landed (a
    clear-time failure then still propagates with the information
    intact)."""
    pc = getattr(exc, "pending_clears", None)
    if pc is None or not len(pc):
        return []
    try:
        exc.pending_clears = None
    except AttributeError:  # exotic __slots__ exception: best effort
        pass
    return [base + int(s) for s in pc]
