"""Shared engine error types.

``SlotCapacityError`` is raised when a batch slot assignment cannot place
every key (all slots pinned).  The C walk is not transactional: lanes
processed before the failing one WERE assigned — their evicted slots are
already remapped to new keys in the index, so their device state must be
zeroed before any reuse or a later acquire of a newly mapped key would
read the evicted key's stale counters.  ``pending_clears`` carries those
evictions (slot ids local to the raising index) up to the storage layer,
which routes them through ``_clear_slots`` exactly as the success path
does (reference analog: the Redis backend's retry wrapper surfaces every
failure as StorageException AFTER the partial pipeline effects are
already durable — storage/RedisRateLimitStorage.java:155-178).
"""

from __future__ import annotations

import numpy as np


class SlotCapacityError(RuntimeError):
    """Batch assignment ran out of evictable slots.

    ``pending_clears``: int32 slot ids (local to the index that raised)
    whose device state must be cleared — evictions applied by the lanes
    that succeeded before the failure.  Consumers that clear them should
    set the attribute to ``None`` so a re-raise through nested handlers
    cannot double-clear.
    """

    def __init__(self, msg: str, pending_clears=None):
        super().__init__(msg)
        self.pending_clears = (
            np.asarray(pending_clears, dtype=np.int64)
            if pending_clears is not None and len(pending_clears)
            else None)


def consume_pending_clears(exc, base: int = 0) -> list:
    """Extract an exception's ``pending_clears`` as a list of GLOBAL slot
    ids (each local id offset by ``base``) and null the attribute, so the
    same raise passing through nested handlers cannot double-clear.  The
    caller takes over responsibility for actually clearing what it got —
    use this where the clears from several sub-indexes are pooled and
    cleared in one call; a handler that clears inline should instead
    clear FIRST and null the attribute only after the clear landed (a
    clear-time failure then still propagates with the information
    intact)."""
    pc = getattr(exc, "pending_clears", None)
    if pc is None or not len(pc):
        return []
    try:
        exc.pending_clears = None
    except AttributeError:  # exotic __slots__ exception: best effort
        pass
    return [base + int(s) for s in pc]
