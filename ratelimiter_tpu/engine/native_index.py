"""ctypes binding for the native slot index (native/slot_index.cpp).

Same interface as the pure-Python ``SlotIndex`` (engine/slots.py) plus
vectorized batch assignment, which is what makes the host keep up with the
device: one C call maps a whole micro-batch of keys to slots.

The shared library is built on demand with the repo Makefile (g++ is in the
image; pybind11 is not, hence the C ABI + ctypes).  If compilation is
impossible the caller falls back to the Python index — behavior is
identical, only slower (tested equivalent in tests/test_native_index.py).
"""

from __future__ import annotations

import contextlib
import ctypes
import os
import subprocess
import threading
from typing import Hashable, Optional, Set, Tuple

import numpy as np

from ratelimiter_tpu.engine.errors import SlotCapacityError

_NATIVE_DIR = os.path.join(os.path.dirname(__file__), "..", "..", "native")
_LIB_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libslotindex.so"))
_build_lock = threading.Lock()
_lib = None
_lib_failed = False


def _load_library():
    global _lib, _lib_failed
    if _lib is not None or _lib_failed:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_failed:
            return _lib
        try:
            # Rebuild when the source is newer than the .so (a stale
            # library would silently miss newer entry points).  A failed
            # build — e.g. a deployment with a prebuilt .so but no
            # toolchain — falls through to loading the existing library.
            src = os.path.join(os.path.abspath(_NATIVE_DIR), "slot_index.cpp")
            stale = (not os.path.exists(_LIB_PATH)
                     or (os.path.exists(src) and os.path.getmtime(src)
                         > os.path.getmtime(_LIB_PATH)))
            if stale:
                try:
                    subprocess.run(
                        ["make", "-C", os.path.abspath(_NATIVE_DIR)],
                        check=True, capture_output=True, timeout=120)
                except Exception as exc:  # noqa: BLE001
                    if not os.path.exists(_LIB_PATH):
                        raise
                    # A symbol-complete but semantically outdated library
                    # would load silently otherwise; give operators a signal
                    # that the binary predates the source.
                    import warnings

                    warnings.warn(
                        f"native slot index rebuild failed ({exc!r}); "
                        f"loading possibly STALE {_LIB_PATH} — rebuild "
                        "with `make -C native` to match the source",
                        RuntimeWarning, stacklevel=2)
            lib = ctypes.CDLL(_LIB_PATH)
            _bind(lib)  # missing symbol (stale prebuilt .so) => fallback
        except Exception:  # noqa: BLE001 — any failure => Python fallback
            _lib_failed = True
            return None
        _lib = lib
        return _lib


def _bind(lib) -> None:
    """Declare the C ABI; raises AttributeError on a library that predates
    any entry point (caller maps that to the Python-index fallback)."""
    lib.rl_index_new.restype = ctypes.c_void_p
    lib.rl_index_new.argtypes = [ctypes.c_int64]
    lib.rl_index_free.argtypes = [ctypes.c_void_p]
    lib.rl_index_len.restype = ctypes.c_int64
    lib.rl_index_len.argtypes = [ctypes.c_void_p]
    lib.rl_index_assign_ints.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_assign_ints_multi.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_assign_bytes.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_assign_ints_uniques.restype = ctypes.c_int64
    lib.rl_index_assign_ints_uniques.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_uint64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_assign_ints_multi_uniques.restype = ctypes.c_int64
    lib.rl_index_assign_ints_multi_uniques.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_assign_bytes_uniques.restype = ctypes.c_int64
    lib.rl_index_assign_bytes_uniques.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_uint64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_get_bytes.restype = ctypes.c_int32
    lib.rl_index_get_bytes.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64]
    lib.rl_index_get_int.restype = ctypes.c_int32
    lib.rl_index_get_int.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
    lib.rl_index_remove_bytes.restype = ctypes.c_int32
    lib.rl_index_remove_bytes.argtypes = [
        ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_uint64]
    lib.rl_index_remove_int.restype = ctypes.c_int32
    lib.rl_index_remove_int.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_uint64]
    lib.rl_index_pin.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.rl_index_unpin.argtypes = [ctypes.c_void_p, ctypes.c_int32]
    lib.rl_index_pin_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.rl_index_unpin_batch.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
    lib.rl_index_dump.restype = ctypes.c_int64
    lib.rl_index_dump.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_index_restore.restype = ctypes.c_int32
    lib.rl_index_restore.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64]
    lib.rl_index_lookup_fps.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p]
    lib.rl_index_assign_fps.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_relay_decide.argtypes = [
        ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_int64, ctypes.c_void_p]
    lib.rl_shard_route.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_sort_uniques.restype = ctypes.c_int32
    lib.rl_sort_uniques.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_int64]
    lib.rl_rebuild_words.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_int32, ctypes.c_void_p]
    lib.rl_weighted_layout.restype = ctypes.c_int32
    lib.rl_weighted_layout.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    lib.rl_weighted_decide.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p]
    # Optional (r5): a stale prebuilt .so without the symbol must not
    # kill the library load — split_layout falls back to numpy.
    try:
        lib.rl_split_layout.restype = ctypes.c_int64
        lib.rl_split_layout.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
    except AttributeError:
        pass
    # Optional (r6): the fingerprint string fast path + hash routing.
    # Stale prebuilt .so => callers fall back to the packed-bytes path.
    try:
        lib.rl_index_assign_fps_uniques.restype = ctypes.c_int64
        lib.rl_index_assign_fps_uniques.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.rl_hash_bytes_batch.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p]
        lib.rl_route_hashes.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.rl_shard_route2.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p]
        lib.rl_route_hashes2.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.rl_relay_decide_pos.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p]
    except AttributeError:
        pass


def native_available() -> bool:
    return _load_library() is not None


_STRPACK_PATH = os.path.abspath(os.path.join(_NATIVE_DIR, "libstrpack.so"))
_strpack = None
_strpack_failed = False


def _load_strpack():
    """Optional CPython-API string packer (native/str_pack.cpp): one C
    pass over the key list instead of join + encode + separator scan.
    Needs Python headers + shared libpython to build; any failure means
    the numpy packer below is used — behavior identical."""
    global _strpack, _strpack_failed
    if _strpack is not None or _strpack_failed:
        return _strpack
    with _build_lock:
        if _strpack is not None or _strpack_failed:
            return _strpack
        try:
            src = os.path.join(os.path.abspath(_NATIVE_DIR), "str_pack.cpp")
            stale = (not os.path.exists(_STRPACK_PATH)
                     or (os.path.exists(src) and os.path.getmtime(src)
                         > os.path.getmtime(_STRPACK_PATH)))
            if stale:
                subprocess.run(
                    ["make", "-C", os.path.abspath(_NATIVE_DIR),
                     "libstrpack.so"],
                    check=True, capture_output=True, timeout=120)
            # PyDLL, not CDLL: these functions touch Python objects, so
            # the GIL must stay held across the call.
            lib = ctypes.PyDLL(_STRPACK_PATH)
            lib.rl_strlist_total.restype = ctypes.c_int64
            lib.rl_strlist_total.argtypes = [ctypes.py_object]
            # _pack2: arity changed with the bounds re-checks; binding by
            # a new name makes a stale prebuilt .so raise AttributeError
            # here (=> numpy fallback) instead of silently dropping them.
            lib.rl_strlist_pack2.restype = ctypes.c_int32
            lib.rl_strlist_pack2.argtypes = [
                ctypes.py_object, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64]
            # Optional (r6): windowed fingerprint hashing — a stale
            # prebuilt libstrpack without it must not lose pack2.
            try:
                lib.rl_strlist_hash_fp.restype = ctypes.c_int32
                lib.rl_strlist_hash_fp.argtypes = [
                    ctypes.py_object, ctypes.c_int64, ctypes.c_int64,
                    ctypes.c_uint64, ctypes.c_void_p, ctypes.c_void_p]
            except AttributeError:
                pass
        except Exception:  # noqa: BLE001 — optional fast path only
            _strpack_failed = True
            return None
        _strpack = lib
        return _strpack


def _pack_str_keys(keys):
    """(packed bytes u8[:], offsets i64[n+1]) for a batch of string keys.

    Fast path: one ``"\\x00".join().encode()`` pass (C speed) plus a
    vectorized separator scan and one masked compaction — no per-key
    Python encode loop.  Falls back to the per-key path when a key embeds
    NUL or isn't a str.  Byte-identical packing either way (the hashes
    must match every other entry path's)."""
    n = len(keys)
    if n == 0:
        return np.empty(0, dtype=np.uint8), np.zeros(1, dtype=np.int64)
    sp = _load_strpack() if isinstance(keys, list) else None
    if sp is not None:
        total = sp.rl_strlist_total(keys)
        if total >= 0:
            buf = np.empty(total, dtype=np.uint8)
            offs = np.empty(n + 1, dtype=np.int64)
            # n/total re-checked inside: the list could have been mutated
            # between the sizing pass and here (bounds, not a data race
            # guarantee — concurrent mutation still yields garbage keys,
            # just never a heap overflow).
            if sp.rl_strlist_pack2(keys, buf.ctypes.data,
                                   offs.ctypes.data, n, total) == 0:
                return buf, offs
    try:
        joined = "\x00".join(keys).encode()
    except TypeError:
        joined = None
    if joined is not None:
        buf = np.frombuffer(joined, dtype=np.uint8)
        seps = np.flatnonzero(buf == 0)
        if len(seps) == n - 1:  # no embedded NULs
            bounds = np.empty(n + 1, dtype=np.int64)
            bounds[0] = -1
            bounds[1:n] = seps
            bounds[n] = len(buf)
            lens = np.diff(bounds) - 1
            offs = np.empty(n + 1, dtype=np.int64)
            offs[0] = 0
            np.cumsum(lens, out=offs[1:])
            if n == 1:
                return buf, offs
            mask = np.ones(len(buf), dtype=bool)
            mask[seps] = False
            return buf[mask], offs
    encoded = [k.encode() if isinstance(k, str) else bytes(k) for k in keys]
    packed = np.frombuffer(b"".join(encoded), dtype=np.uint8)
    lens = np.fromiter((len(b) for b in encoded), dtype=np.int64,
                       count=n)
    offs = np.empty(n + 1, dtype=np.int64)
    offs[0] = 0
    np.cumsum(lens, out=offs[1:])
    return packed, offs


_FNV_OFF1 = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_U64 = (1 << 64) - 1


def fnv_fingerprint_h1(data: bytes, seed: int) -> int:
    """Python mirror of the h1 stream of native/slot_index.cpp:
    hash_bytes — the fingerprint the string shard router keys on.  Used
    by scalar paths (parallel/sharded.py:shard_of_key) so scalar and
    batched string traffic always agree on a key's shard; parity with
    the C implementation is pinned by tests/test_native_index.py."""
    h = (_FNV_OFF1 ^ (seed & _U64)) & _U64
    for b in data:
        h = ((h ^ b) * _FNV_PRIME) & _U64
    return h


# Per-thread fingerprint scratch: the hash arrays are consumed within
# the same call that fills them (assign / route), so one grow-only pair
# per thread removes the 16 B/key allocation from every stream chunk.
_fp_tls = threading.local()


def _fp_scratch(n: int):
    h1 = getattr(_fp_tls, "h1", None)
    if h1 is None or len(h1) < n:
        _fp_tls.h1 = h1 = np.empty(max(n, 1024), dtype=np.uint64)
        _fp_tls.h2 = np.empty(max(n, 1024), dtype=np.uint64)
    return h1, _fp_tls.h2


def str_hash_available() -> bool:
    """Whether hash_str_keys has a native producer (either the CPython
    hasher or packed-bytes hashing through the index library)."""
    lib = _load_library()
    if lib is None or not hasattr(lib, "rl_hash_bytes_batch"):
        return False
    return True


def hash_str_keys(keys, seed: int, start: int = 0,
                  count: int | None = None):
    """128-bit fingerprints for a window of a string-key batch, with no
    per-key Python objects: (h1 u64[n], h2 u64[n]) views into per-thread
    scratch (consume before the next call on the same thread), or None
    when no native producer exists.

    Fast path: one CPython-API pass over the list window
    (str_pack.cpp:rl_strlist_hash_fp) — hashes straight off each str's
    interned UTF-8 buffer, no join/copy/offsets.  Fallback: the numpy
    packer + rl_hash_bytes_batch (handles bytes keys and non-list
    sequences).  Both produce fingerprints bit-identical to every other
    index entry path."""
    n = (len(keys) - start) if count is None else count
    if n < 0:
        return None
    h1, h2 = _fp_scratch(n)
    sp = _load_strpack() if isinstance(keys, list) else None
    if sp is not None and hasattr(sp, "rl_strlist_hash_fp"):
        if sp.rl_strlist_hash_fp(keys, start, n, seed & _U64,
                                 h1.ctypes.data, h2.ctypes.data) == 0:
            return h1[:n], h2[:n]
    lib = _load_library()
    if lib is None or not hasattr(lib, "rl_hash_bytes_batch"):
        return None
    sub = keys[start:start + n]
    packed, offs = _pack_str_keys(
        sub if isinstance(sub, list) else list(sub))
    lib.rl_hash_bytes_batch(packed.ctypes.data if len(packed) else 0,
                            offs.ctypes.data, n, seed & _U64,
                            h1.ctypes.data, h2.ctypes.data)
    return h1[:n], h2[:n]


def shard_route_gather(key_ids: np.ndarray, n_shards: int):
    """Fused shard routing + key gather: (shard i32[n], order i64[n],
    counts i64[n_shards], keys_sorted i64[n]) in one C pass — the
    separate numpy fancy-gather of the sorted keys was a whole extra
    memory pass per chunk on 1-core hosts.  None off-native (callers
    fall back to shard_route/_route_chunk + numpy gather).

    Since r8 this is the HOST side of a measured routing election: the
    on-mesh route-and-count pass (parallel/sharded.py:build_route_count,
    bit-identical binning) is the other side, and the storage serves
    whichever measured faster (``RATELIMITER_DEVICE_ROUTE``,
    ARCHITECTURE §6c) — on CPU containers this C pass wins; on a real
    slice the binning moves to the mesh."""
    lib = _load_library()
    if lib is None or not hasattr(lib, "rl_shard_route2"):
        return None
    key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
    n = len(key_ids)
    shard = np.empty(n, dtype=np.int32)
    order = np.empty(n, dtype=np.int64)
    counts = np.empty(n_shards, dtype=np.int64)
    kst = np.empty(n, dtype=np.int64)
    lib.rl_shard_route2(key_ids.ctypes.data, n, int(n_shards),
                        shard.ctypes.data, order.ctypes.data,
                        counts.ctypes.data, kst.ctypes.data)
    return shard, order, counts, kst


def route_hashes_gather(h1: np.ndarray, h2: np.ndarray, n_shards: int):
    """Fused fingerprint routing + gather: (shard, order, counts,
    h1_sorted, h2_sorted) in one C pass; numpy fallback bit-identical.
    Host side of the r8 routing election for STRING streams (the
    on-mesh pass bins by the same h1 stream — see shard_route_gather)."""
    n = len(h1)
    lib = _load_library()
    if lib is not None and hasattr(lib, "rl_route_hashes2"):
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        h2 = np.ascontiguousarray(h2, dtype=np.uint64)
        shard = np.empty(n, dtype=np.int32)
        order = np.empty(n, dtype=np.int64)
        counts = np.empty(n_shards, dtype=np.int64)
        h1s = np.empty(n, dtype=np.uint64)
        h2s = np.empty(n, dtype=np.uint64)
        lib.rl_route_hashes2(h1.ctypes.data, h2.ctypes.data, n,
                             int(n_shards), shard.ctypes.data,
                             order.ctypes.data, counts.ctypes.data,
                             h1s.ctypes.data, h2s.ctypes.data)
        return shard, order, counts, h1s, h2s
    shard, order, counts = route_hashes(h1, n_shards)
    return shard, order, counts, h1[order], h2[order]


def relay_decide_pos(counts: np.ndarray, uidx: np.ndarray,
                     rank: np.ndarray, pos: np.ndarray,
                     out: np.ndarray) -> int:
    """Scattered relay decision reconstruction: ``out[pos[i]] = rank[i]
    < counts[uidx[i]]`` in one C pass (``out`` a C-contiguous bool
    view), returning the allowed count — fuses the dense reconstruction
    + numpy fancy-scatter the sharded drain used to pay as two memory
    passes.  Falls back to the two-pass numpy route off-native."""
    lib = _load_library()
    n = len(uidx)
    if (lib is not None and hasattr(lib, "rl_relay_decide_pos")
            and counts.dtype.itemsize <= 2 and out.flags["C_CONTIGUOUS"]
            and out.dtype == np.bool_):
        counts = np.ascontiguousarray(counts)
        uidx = np.ascontiguousarray(uidx, dtype=np.int32)
        rank = np.ascontiguousarray(rank, dtype=np.int32)
        pos = np.ascontiguousarray(pos, dtype=np.int64)
        allowed = np.empty(1, dtype=np.int64)
        lib.rl_relay_decide_pos(
            counts.ctypes.data, counts.dtype.itemsize, uidx.ctypes.data,
            rank.ctypes.data, pos.ctypes.data, n, out.ctypes.data,
            allowed.ctypes.data)
        return int(allowed[0])
    got = relay_decide(counts, uidx, rank)
    out[pos] = got
    return int(got.sum())


def route_hashes(h1: np.ndarray, n_shards: int):
    """(shard i32[n], stable order i64[n], counts i64[n_shards]) from
    precomputed fingerprints: shard = h1 % n_shards + stable counting
    sort, one C pass (numpy fallback bit-identical)."""
    n = len(h1)
    lib = _load_library()
    if lib is not None and hasattr(lib, "rl_route_hashes"):
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        shard = np.empty(n, dtype=np.int32)
        order = np.empty(n, dtype=np.int64)
        counts = np.empty(n_shards, dtype=np.int64)
        lib.rl_route_hashes(h1.ctypes.data, n, int(n_shards),
                            shard.ctypes.data, order.ctypes.data,
                            counts.ctypes.data)
        return shard, order, counts
    shard = (h1 % np.uint64(n_shards)).astype(np.int32)
    order = np.argsort(shard, kind="stable")
    return shard, order, np.bincount(
        shard, minlength=n_shards).astype(np.int64)


def relay_decide(counts: np.ndarray, uidx: np.ndarray,
                 rank: np.ndarray) -> np.ndarray:
    """allowed[i] = rank[i] < counts[uidx[i]] — the digest-mode decision
    reconstruction, fused into one C pass (numpy fallback off-native).
    ``counts`` is the device's u8/u16 per-unique allowed counts."""
    lib = _load_library()
    if lib is None or counts.dtype.itemsize > 2:
        return rank < counts.astype(np.int32)[uidx]
    counts = np.ascontiguousarray(counts)
    uidx = np.ascontiguousarray(uidx, dtype=np.int32)
    rank = np.ascontiguousarray(rank, dtype=np.int32)
    out = np.empty(len(uidx), dtype=np.uint8)
    lib.rl_relay_decide(counts.ctypes.data, counts.dtype.itemsize,
                        uidx.ctypes.data, rank.ctypes.data, len(uidx),
                        out.ctypes.data)
    return out.view(np.bool_)


def sort_uniques(uwords: np.ndarray, rank_bits: int,
                 uidx: np.ndarray) -> bool:
    """Sort ``uwords`` by slot IN PLACE (radix on the slot field) and
    remap ``uidx`` to the new positions — the prerequisite for the
    dense presorted device scatter.  Decision reconstruction is
    order-agnostic (counts[uidx] with the remapped uidx), so callers
    can sort freely before a digest dispatch.  False when the native
    library is unavailable (callers dispatch unsorted)."""
    lib = _load_library()
    if lib is None:
        return False
    # Explicit precondition checks (NOT asserts: under `python -O` an
    # assert vanishes and a non-contiguous or wrong-dtype array would
    # hand the C sort a garbage pointer) — ADVICE r4.
    if not (uwords.flags["C_CONTIGUOUS"] and uwords.dtype == np.uint32
            and uidx.flags["C_CONTIGUOUS"] and uidx.dtype == np.int32):
        return False  # caller dispatches unsorted, decisions unchanged
    lib.rl_sort_uniques(uwords.ctypes.data, len(uwords), int(rank_bits),
                        uidx.ctypes.data, len(uidx))
    return True


def rebuild_words_into(uwords: np.ndarray, uidx: np.ndarray,
                       rank: np.ndarray, rank_bits: int,
                       out: np.ndarray) -> bool:
    """Words-mode per-request reconstruction straight into the caller's
    (padded) dispatch buffer — one C pass instead of numpy's gather +
    shift temporaries + pad copy.  ``out`` must be a C-contiguous uint32
    view with at least len(uidx) lanes.  False when the native library
    is unavailable (callers fall back to ops/relay.rebuild_words)."""
    lib = _load_library()
    if lib is None:
        return False
    # Explicit check, not an assert (see sort_uniques) — ADVICE r4.
    if not (out.flags["C_CONTIGUOUS"] and out.dtype == np.uint32):
        return False  # caller rebuilds via ops/relay.rebuild_words
    lib.rl_rebuild_words(uwords.ctypes.data, uidx.ctypes.data,
                         rank.ctypes.data, len(uidx), int(rank_bits),
                         out.ctypes.data)
    return True


def weighted_layout(uwords: np.ndarray, rank_bits: int, uidx: np.ndarray,
                    rank: np.ndarray, perms: np.ndarray, r_b: int,
                    uw_sorted: np.ndarray, spos: np.ndarray,
                    roff: np.ndarray, perms_rank: np.ndarray) -> bool:
    """Count-descending rank-major layout for the weighted relay, in one
    C pass (native/slot_index.cpp:rl_weighted_layout) — emits the sorted
    words into caller-padded ``uw_sorted``, unique->position ``spos``,
    rank offsets ``roff``, and scatters ``perms`` into the caller-zeroed
    ``perms_rank``.  Returns False when the native library is missing
    (callers fall back to the numpy layout, bit-identical)."""
    lib = _load_library()
    if lib is None:
        return False
    rc = lib.rl_weighted_layout(
        uwords.ctypes.data, len(uwords), int(rank_bits),
        uidx.ctypes.data, rank.ctypes.data, len(uidx),
        perms.ctypes.data, int(r_b), uw_sorted.ctypes.data,
        spos.ctypes.data, roff.ctypes.data, perms_rank.ctypes.data)
    # rc != 0 = the C guard's own r_b ceiling (4096, slot_index.cpp)
    # tripped.  Unreachable while _WREL_MAX_R (64) stays far below it,
    # but if the cap is ever raised past 4096 the right behavior is the
    # bit-identical numpy fallback, not a hard failure of the whole
    # weighted pass — ADVICE r4.
    return rc == 0


def weighted_decide(bits: np.ndarray, roff: np.ndarray, spos: np.ndarray,
                    uidx: np.ndarray, rank: np.ndarray) -> np.ndarray:
    """Per-request decisions from the packed weighted bitmask: bit
    (roff[rank] + spos[uidx]) of ``bits`` (MSB-first), one C pass
    replacing unpackbits + fancy-index gather.  None-safe: callers only
    use this when :func:`weighted_layout` returned True."""
    lib = _load_library()
    out = np.empty(len(uidx), dtype=np.uint8)
    lib.rl_weighted_decide(bits.ctypes.data, roff.ctypes.data,
                           spos.ctypes.data, uidx.ctypes.data,
                           rank.ctypes.data, len(uidx), out.ctypes.data)
    return out.view(np.bool_)


def split_layout(uwords: np.ndarray, rank_bits: int, uidx: np.ndarray,
                 singles: np.ndarray | None = None):
    """Partition a digest chunk's uniques into SINGLETONS and
    multi-count segments for the split dispatch (ops/relay.py:
    _relay_counts_split, r5).

    Returns ``(s3, mwords, uidx2, n_singles)``: the singletons' slots
    as a uint8[S, 3] little-endian 24-bit plane, the multis' uwords
    unchanged, and uidx remapped to singles-then-multis positions
    (reconstruction: position < S reads an allow bit, else a count).
    A count FIELD of 1 is an exact singleton — relay_usable() forces
    rank_bits >= 2, so the clamp sentinel is >= 3 and can't alias 1.
    C fast path (rl_split_layout: two GIL-free passes; ~19 ns/unique
    all-in at 3M uniques, output allocation included); the numpy
    fallback (~4 passes, ~46 ns/unique) is bit-identical.
    ``singles`` lets a caller that already computed the singleton mask
    (the election did, to price the split) pass it in (numpy path
    only — the C pass re-classifies for ~1 ns/unique)."""
    u = len(uwords)
    n = len(uidx)
    lib = _load_library()
    if (lib is not None and hasattr(lib, "rl_split_layout")
            and uwords.flags["C_CONTIGUOUS"] and uwords.dtype == np.uint32
            and uidx.flags["C_CONTIGUOUS"] and uidx.dtype == np.int32):
        s3 = np.empty((u, 3), dtype=np.uint8)
        mwords = np.empty(max(u, 1), dtype=np.uint32)
        uidx2 = np.empty(n, dtype=np.int32)
        scratch = np.empty(max(u, 1), dtype=np.int32)
        n_s = int(lib.rl_split_layout(
            uwords.ctypes.data, u, int(rank_bits), uidx.ctypes.data, n,
            s3.ctypes.data, mwords.ctypes.data, uidx2.ctypes.data,
            scratch.ctypes.data))
        return s3[:n_s], mwords[:u - n_s], uidx2, n_s
    if singles is None:
        rank_mask = np.uint32((1 << rank_bits) - 1)
        singles = ((uwords >> np.uint32(1)) & rank_mask) == 1
    n_s = int(singles.sum())
    newpos = np.empty(u, dtype=np.int32)
    newpos[singles] = np.arange(n_s, dtype=np.int32)
    newpos[~singles] = np.arange(n_s, u, dtype=np.int32)
    uidx2 = newpos[uidx]
    s_slots = (uwords[singles] >> np.uint32(rank_bits + 1)).astype("<u4")
    s3 = s_slots.view(np.uint8).reshape(-1, 4)[:, :3]
    return s3, uwords[~singles], uidx2, n_s


def shard_route(key_ids: np.ndarray, n_shards: int):
    """(shard i32[n], stable order i64[n], counts i64[n_shards]) for an
    int64 key batch — one C pass of splitmix hash + counting sort,
    bit-identical to shard_of_int_keys + stable argsort.  None when the
    native library is unavailable (callers fall back to numpy)."""
    lib = _load_library()
    if lib is None:
        return None
    key_ids = np.ascontiguousarray(key_ids, dtype=np.int64)
    n = len(key_ids)
    shard = np.empty(n, dtype=np.int32)
    order = np.empty(n, dtype=np.int64)
    counts = np.empty(n_shards, dtype=np.int64)
    lib.rl_shard_route(key_ids.ctypes.data, n, int(n_shards),
                       shard.ctypes.data, order.ctypes.data,
                       counts.ctypes.data)
    return shard, order, counts


def _split_key(key: Hashable) -> Tuple[int, bytes | int]:
    """Index keys arrive as (limiter_id, user_key); the lid becomes the hash
    seed so tenants are isolated."""
    if isinstance(key, tuple) and len(key) == 2:
        lid, user = key
        seed = int(lid) if isinstance(lid, int) else abs(hash(lid))
    else:
        seed, user = 0, key
    if isinstance(user, int):
        return seed, user
    if isinstance(user, bytes):
        return seed, user
    return seed, str(user).encode()


class NativeSlotIndex:
    """Drop-in SlotIndex backed by the C++ table (thread-safe via lock —
    matches the Python index; the batch path amortizes it over 1000s of keys)."""

    def __init__(self, num_slots: int):
        lib = _load_library()
        if lib is None:
            raise RuntimeError("native slot index unavailable")
        self._lib = lib
        self.num_slots = int(num_slots)
        self._h = ctypes.c_void_p(lib.rl_index_new(self.num_slots))
        self._lock = threading.Lock()

    def __del__(self):
        h = getattr(self, "_h", None)
        if h:
            self._lib.rl_index_free(h)
            self._h = None

    @contextlib.contextmanager
    def _pinned(self, pinned):
        """Hold pin refcounts on the given slots for the enclosed call.
        Must be entered with self._lock held."""
        pins = list(pinned) if pinned else []
        for s in pins:
            self._lib.rl_index_pin(self._h, s)
        try:
            yield
        finally:
            for s in pins:
                self._lib.rl_index_unpin(self._h, s)

    # -- scalar interface (SlotIndex parity) ----------------------------------
    def get(self, key: Hashable) -> Optional[int]:
        seed, user = _split_key(key)
        with self._lock:
            if isinstance(user, int):
                slot = self._lib.rl_index_get_int(self._h, user, seed)
            else:
                slot = self._lib.rl_index_get_bytes(self._h, user, len(user), seed)
        return None if slot < 0 else slot

    def assign(
        self, key: Hashable, pinned: Optional[Set[int]] = None,
        hold_pin: bool = False
    ) -> Tuple[int, Optional[int]]:
        seed, user = _split_key(key)
        out_slot = np.empty(1, dtype=np.int32)
        out_ev = np.empty(1, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            if isinstance(user, int):
                keys = np.asarray([user], dtype=np.int64)
                self._lib.rl_index_assign_ints(
                    self._h, keys.ctypes.data, 1, seed,
                    out_slot.ctypes.data, out_ev.ctypes.data)
            else:
                data = np.frombuffer(user, dtype=np.uint8) if user else \
                    np.empty(0, dtype=np.uint8)
                offs = np.asarray([0, len(user)], dtype=np.int64)
                self._lib.rl_index_assign_bytes(
                    self._h, data.ctypes.data if len(user) else 0,
                    offs.ctypes.data, 1, seed,
                    out_slot.ctypes.data, out_ev.ctypes.data)
            if hold_pin and out_slot[0] >= 0:
                self._lib.rl_index_pin(self._h, int(out_slot[0]))
        if out_ev[0] == -2:
            raise RuntimeError("all slots pinned; increase num_slots or flush")
        evicted = int(out_ev[0]) if out_ev[0] >= 0 else None
        return int(out_slot[0]), evicted

    def remove(self, key: Hashable) -> Optional[int]:
        seed, user = _split_key(key)
        with self._lock:
            if isinstance(user, int):
                slot = self._lib.rl_index_remove_int(self._h, user, seed)
            else:
                slot = self._lib.rl_index_remove_bytes(self._h, user, len(user), seed)
        return None if slot < 0 else slot

    def __len__(self) -> int:
        with self._lock:
            return int(self._lib.rl_index_len(self._h))

    # -- vectorized interface -------------------------------------------------
    def assign_batch_ints(self, keys: np.ndarray, lid: int,
                          pinned: Optional[Set[int]] = None,
                          hold_pins: bool = False):
        """Assign slots for an int64 key batch in one C call.
        ``pinned`` slots (queued async requests) are never evicted.
        ``hold_pins`` pins the returned slots ATOMICALLY with the
        assignment (same lock hold) — the caller must ``unpin_batch``
        them once its dispatch is enqueued.  Returns (slots i32[n],
        evictions i32[k])."""
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        n = len(keys)
        out_slots = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            self._lib.rl_index_assign_ints(
                self._h, keys.ctypes.data, n, int(lid),
                out_slots.ctypes.data, out_ev.ctypes.data)
            # Pin only on full success: the caller raises on -2 and never
            # dispatches, so pinning the successful lanes would leak.
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:
                self._lib.rl_index_pin_batch(
                    self._h, out_slots.ctypes.data, n)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return out_slots, out_ev[out_ev >= 0]

    def assign_batch_ints_multi(self, keys: np.ndarray, lids: np.ndarray,
                                pinned: Optional[Set[int]] = None,
                                hold_pins: bool = False):
        """Assign slots for an int64 key batch with per-request limiter ids
        in one C call.  Same key namespace as per-lid assign_batch_ints —
        (lid, key) maps to the same slot whichever path touches it first."""
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        seeds = np.ascontiguousarray(lids, dtype=np.uint64)
        n = len(keys)
        out_slots = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            self._lib.rl_index_assign_ints_multi(
                self._h, keys.ctypes.data, seeds.ctypes.data, n,
                out_slots.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                self._lib.rl_index_pin_batch(
                    self._h, out_slots.ctypes.data, n)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return out_slots, out_ev[out_ev >= 0]

    # -- held pins (streams: assign -> dispatch-enqueue window) ---------------
    def pin_batch(self, slots: np.ndarray) -> None:
        """Refcounted pins (duplicates fine) held across a dispatch-prep
        window so concurrent assigns can't evict these slots."""
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        with self._lock:
            self._lib.rl_index_pin_batch(self._h, slots.ctypes.data,
                                         len(slots))

    def unpin_batch(self, slots: np.ndarray) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        with self._lock:
            self._lib.rl_index_unpin_batch(self._h, slots.ctypes.data,
                                           len(slots))

    # -- uniques interface (the relay streaming path; ops/relay.py) -----------
    # One uint32 per UNIQUE slot of the batch — (slot | clamped segment
    # count) — plus per-request (unique-index, rank) scratch the caller
    # keeps host-side (layout in native/slot_index.cpp:
    # assign_batch_uniques).  Evictions are reported exactly like the
    # plain batch assigns.

    def assign_batch_ints_uniques(self, keys: np.ndarray, lid: int,
                                  rank_bits: int,
                                  pinned: Optional[Set[int]] = None,
                                  hold_pins: bool = False):
        """Unique-compaction assign (segment-digest path): returns
        (uwords uint32[u], uidx i32[n], rank i32[n], evictions).  uwords
        carries (slot | clamped-count) per unique in first-appearance
        order; uidx/rank stay host-side for decision reconstruction."""
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        n = len(keys)
        uwords = np.empty(n, dtype=np.uint32)
        uidx = np.empty(n, dtype=np.int32)
        rank = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            u = self._lib.rl_index_assign_ints_uniques(
                self._h, keys.ctypes.data, n, int(lid), int(rank_bits),
                uwords.ctypes.data, uidx.ctypes.data, rank.ctypes.data,
                out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                uslots = (uwords[:u] >> np.uint32(rank_bits + 1)).astype(
                    np.int32)
                self._lib.rl_index_pin_batch(
                    self._h, np.ascontiguousarray(uslots).ctypes.data, u)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return uwords[:u], uidx, rank, out_ev[out_ev >= 0]

    def assign_batch_ints_multi_uniques(self, keys: np.ndarray,
                                        lids: np.ndarray, rank_bits: int,
                                        pinned: Optional[Set[int]] = None,
                                        hold_pins: bool = False):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        seeds = np.ascontiguousarray(lids, dtype=np.uint64)
        n = len(keys)
        uwords = np.empty(n, dtype=np.uint32)
        uidx = np.empty(n, dtype=np.int32)
        rank = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            u = self._lib.rl_index_assign_ints_multi_uniques(
                self._h, keys.ctypes.data, seeds.ctypes.data, n,
                int(rank_bits), uwords.ctypes.data, uidx.ctypes.data,
                rank.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                uslots = (uwords[:u] >> np.uint32(rank_bits + 1)).astype(
                    np.int32)
                self._lib.rl_index_pin_batch(
                    self._h, np.ascontiguousarray(uslots).ctypes.data, u)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return uwords[:u], uidx, rank, out_ev[out_ev >= 0]

    def assign_batch_fps_uniques(self, h1: np.ndarray, h2: np.ndarray,
                                 rank_bits: int,
                                 pinned: Optional[Set[int]] = None,
                                 hold_pins: bool = False):
        """Unique-compaction assign for PRECOMPUTED fingerprints — the
        sharded/partitioned string streams hash once, route by h1, and
        feed each sub-index its slice here.  Identical semantics to the
        bytes-keyed uniques assign on the same fingerprints."""
        if not hasattr(self._lib, "rl_index_assign_fps_uniques"):
            raise RuntimeError("stale native library: rebuild native/ "
                               "(rl_index_assign_fps_uniques missing)")
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        h2 = np.ascontiguousarray(h2, dtype=np.uint64)
        n = len(h1)
        uwords = np.empty(n, dtype=np.uint32)
        uidx = np.empty(n, dtype=np.int32)
        rank = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            u = self._lib.rl_index_assign_fps_uniques(
                self._h, h1.ctypes.data, h2.ctypes.data, n,
                int(rank_bits), uwords.ctypes.data, uidx.ctypes.data,
                rank.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                uslots = (uwords[:u] >> np.uint32(rank_bits + 1)).astype(
                    np.int32)
                self._lib.rl_index_pin_batch(
                    self._h, np.ascontiguousarray(uslots).ctypes.data, u)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return uwords[:u], uidx, rank, out_ev[out_ev >= 0]

    def assign_batch_strs_uniques(self, keys, lid: int, rank_bits: int,
                                  pinned: Optional[Set[int]] = None,
                                  hold_pins: bool = False,
                                  start: int = 0,
                                  count: int | None = None):
        """String-key uniques assign: pack -> hash -> slot walk with zero
        per-key Python objects.  ``start``/``count`` window the key
        sequence so stream chunking never slices a multi-million-entry
        list (the r5 path copied each chunk's slice).  Fast path: one
        CPython hash pass (fingerprints straight off the interned UTF-8
        buffers) feeding the fingerprint walk; fallback: the packed-bytes
        walk, bit-identical."""
        import time as _time

        n = (len(keys) - start) if count is None else count
        t_p0 = _time.perf_counter()
        fp = (hash_str_keys(keys, lid, start, n)
              if hasattr(self._lib, "rl_index_assign_fps_uniques")
              else None)
        if fp is not None:
            # Exposed for the stream loop's per-chunk phase lanes (pack
            # vs hash+walk — VERDICT r4 #7); the caller reads it before
            # it submits the next chunk's prefetch, so it always refers
            # to the chunk just assigned.
            self.str_pack_s = _time.perf_counter() - t_p0
            return self.assign_batch_fps_uniques(
                fp[0], fp[1], rank_bits, pinned=pinned,
                hold_pins=hold_pins)
        sub = keys if (start == 0 and n == len(keys)) else keys[
            start:start + n]
        packed, offs = _pack_str_keys(
            sub if isinstance(sub, list) else list(sub))
        self.str_pack_s = _time.perf_counter() - t_p0
        uwords = np.empty(n, dtype=np.uint32)
        uidx = np.empty(n, dtype=np.int32)
        rank = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            u = self._lib.rl_index_assign_bytes_uniques(
                self._h, packed.ctypes.data if len(packed) else 0,
                offs.ctypes.data, n, int(lid), int(rank_bits),
                uwords.ctypes.data, uidx.ctypes.data, rank.ctypes.data,
                out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                uslots = (uwords[:u] >> np.uint32(rank_bits + 1)).astype(
                    np.int32)
                self._lib.rl_index_pin_batch(
                    self._h, np.ascontiguousarray(uslots).ctypes.data, u)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return uwords[:u], uidx, rank, out_ev[out_ev >= 0]

    # -- fingerprint enumeration (checkpoint/resume at native speed) ----------
    def dump_fp(self):
        """All live entries as (h1 u64[n], h2 u64[n], slots i32[n]), in LRU
        order most-recent first — the native-speed checkpoint payload.
        Fingerprints are one-way: use the Python index when a dump must
        carry the original keys (cross-shard rebalance)."""
        cap = self.num_slots
        h1 = np.empty(cap, dtype=np.uint64)
        h2 = np.empty(cap, dtype=np.uint64)
        slots = np.empty(cap, dtype=np.int32)
        with self._lock:
            n = self._lib.rl_index_dump(
                self._h, h1.ctypes.data, h2.ctypes.data, slots.ctypes.data)
        return h1[:n].copy(), h2[:n].copy(), slots[:n].copy()

    def restore_fp(self, h1: np.ndarray, h2: np.ndarray,
                   slots: np.ndarray) -> None:
        """Rebuild from a dump_fp payload (exact LRU order restored)."""
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        h2 = np.ascontiguousarray(h2, dtype=np.uint64)
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        n = len(h1)
        if len(h2) != n or len(slots) != n:
            raise ValueError("fingerprint dump arrays disagree on length")
        with self._lock:
            rc = self._lib.rl_index_restore(
                self._h, h1.ctypes.data, h2.ctypes.data, slots.ctypes.data, n)
        if rc != 0:
            raise ValueError(
                "invalid fingerprint dump (bad slot, duplicate, or size)")

    def lookup_fps(self, h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
        """Slots of the given fingerprints (-1 if absent); no LRU touch."""
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        h2 = np.ascontiguousarray(h2, dtype=np.uint64)
        out = np.empty(len(h1), dtype=np.int32)
        with self._lock:
            self._lib.rl_index_lookup_fps(
                self._h, h1.ctypes.data, h2.ctypes.data, len(h1),
                out.ctypes.data)
        return out

    def assign_batch_fps(self, h1: np.ndarray, h2: np.ndarray,
                         pinned: Optional[Set[int]] = None,
                         hold_pins: bool = False):
        """Assign slots for raw fingerprints (flat-to-flat rebalance
        import, and the string fast path once the keys are hashed).
        Returns (slots i32[n], evictions i32[k])."""
        h1 = np.ascontiguousarray(h1, dtype=np.uint64)
        h2 = np.ascontiguousarray(h2, dtype=np.uint64)
        n = len(h1)
        out_slots = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            self._lib.rl_index_assign_fps(
                self._h, h1.ctypes.data, h2.ctypes.data, n,
                out_slots.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                self._lib.rl_index_pin_batch(
                    self._h, out_slots.ctypes.data, n)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return out_slots, out_ev[out_ev >= 0]

    def assign_batch_bytes(self, data, offsets, lid: int,
                           pinned: Optional[Set[int]] = None,
                           hold_pins: bool = False):
        """Assign slots straight off a packed UTF-8 key column (the
        sidecar's v5 batch frame: data uint8[klen] + offsets i64[n+1] is
        exactly rl_index_assign_bytes' input), so a whole frame of keys
        assigns with zero per-key Python objects.  Fingerprints are
        seeded by lid like the per-frame string path — the same key
        lands in the same slot through either.  Returns (slots i32[n],
        evictions i32[k])."""
        data = np.ascontiguousarray(data, dtype=np.uint8)
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        n = len(offsets) - 1
        out_slots = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        with self._lock, self._pinned(pinned):
            self._lib.rl_index_assign_bytes(
                self._h, data.ctypes.data if len(data) else 0,
                offsets.ctypes.data, n, int(lid),
                out_slots.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                self._lib.rl_index_pin_batch(
                    self._h, out_slots.ctypes.data, n)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return out_slots, out_ev[out_ev >= 0]

    def assign_batch_strs(self, keys, lid: int,
                          pinned: Optional[Set[int]] = None,
                          hold_pins: bool = False,
                          start: int = 0, count: int | None = None):
        """Assign slots for a string key batch in one C call (fingerprint
        fast path when the CPython hasher is available; windowed like
        assign_batch_strs_uniques)."""
        n = (len(keys) - start) if count is None else count
        fp = hash_str_keys(keys, lid, start, n)
        out_slots = np.empty(n, dtype=np.int32)
        out_ev = np.empty(n, dtype=np.int32)
        if fp is not None:
            h1 = np.ascontiguousarray(fp[0], dtype=np.uint64)
            h2 = np.ascontiguousarray(fp[1], dtype=np.uint64)
            with self._lock, self._pinned(pinned):
                self._lib.rl_index_assign_fps(
                    self._h, h1.ctypes.data, h2.ctypes.data, n,
                    out_slots.ctypes.data, out_ev.ctypes.data)
                failed = bool((out_ev == -2).any())
                if hold_pins and not failed:  # see assign_batch_ints
                    self._lib.rl_index_pin_batch(
                        self._h, out_slots.ctypes.data, n)
            if failed:
                raise SlotCapacityError(
                    "slot capacity exhausted (all pinned)",
                    pending_clears=out_ev[out_ev >= 0])
            return out_slots, out_ev[out_ev >= 0]
        sub = keys if (start == 0 and n == len(keys)) else keys[
            start:start + n]
        packed, offs = _pack_str_keys(
            sub if isinstance(sub, list) else list(sub))
        with self._lock, self._pinned(pinned):
            self._lib.rl_index_assign_bytes(
                self._h, packed.ctypes.data if len(packed) else 0,
                offs.ctypes.data, n, int(lid),
                out_slots.ctypes.data, out_ev.ctypes.data)
            failed = bool((out_ev == -2).any())
            if hold_pins and not failed:  # see assign_batch_ints
                self._lib.rl_index_pin_batch(
                    self._h, out_slots.ctypes.data, n)
        if failed:
            raise SlotCapacityError("slot capacity exhausted (all pinned)",
                                    pending_clears=out_ev[out_ev >= 0])
        return out_slots, out_ev[out_ev >= 0]
