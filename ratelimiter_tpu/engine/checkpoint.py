"""Checkpoint / resume for device-resident limiter state.

The reference delegates durability to Redis AOF persistence
(docker-compose.yml enables --appendonly): counters survive an app restart
because they live in Redis.  In this framework the source of truth is HBM,
which dies with the process — so durability is an explicit subsystem
(SURVEY.md §5.4): snapshot the slot arrays and the key->slot index to disk,
restore them on boot.

Format: a directory with
  - ``state.npz``  — the SW/TB slot arrays (numpy int64)
  - ``index.json`` — limiter registrations + key->slot mappings + metadata

Snapshots are crash-consistent (written to a temp dir, atomically renamed)
and backend-portable: a checkpoint taken on a sharded engine restores onto a
single-device engine and vice versa (state is keyed by global slot id; the
restore re-routes rows if the slot geometry changed... geometry must match —
enforced by metadata check; cross-geometry migration is a rebalance, left to
the operator via export/import of per-key state in a future round).

The native slot index cannot enumerate its keys (it stores fingerprints
only), so checkpointable deployments either use the Python index
(``TpuBatchedStorage(checkpointable=True)``) or supply key enumeration at
snapshot time from the service tier.  The device state itself snapshots
regardless of index type.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, Optional

import numpy as np


# v1: TBState carried a stored deadline array; v2 derives it from
# last_refill + 2*window and drops the lane. Restore iterates the CURRENT
# field set, so v1 checkpoints load in v2 binaries (the extra tb_deadline
# array is ignored); v2 checkpoints refuse to load in v1 binaries via the
# version check rather than failing on a missing array.
FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)

# Identity of the key->shard routing hash used by sharded indexes
# (parallel/sharded.py:shard_of_key): crc32-of-repr for string keys,
# splitmix64 for int keys.  Stored in sharded index dumps so a restore into
# a binary with a different routing function fails loudly instead of
# silently orphaning entries.
SHARD_HASH_VERSION = "crc32-repr/splitmix64-v1"


def snapshot_engine_state(engine, index_dump: Optional[Dict] = None) -> Dict:
    """Materialize the device state to host numpy (one blocking transfer)."""
    engine.block_until_ready()
    sw = engine.sw_state
    tb = engine.tb_state
    return {
        "sw": {f: np.asarray(getattr(sw, f)).reshape(-1) for f in sw._fields},
        "tb": {f: np.asarray(getattr(tb, f)).reshape(-1) for f in tb._fields},
        "meta": {
            "format": FORMAT_VERSION,
            "num_slots": engine.num_slots,
            "taken_at_ms": time.time_ns() // 1_000_000,
            "index": index_dump or {},
        },
    }


def save_checkpoint(path: str, engine, index_dump: Optional[Dict] = None) -> None:
    """Write an atomic on-disk checkpoint (temp dir + rename)."""
    snap = snapshot_engine_state(engine, index_dump)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt-tmp-", dir=parent)
    try:
        arrays = {f"sw_{k}": v for k, v in snap["sw"].items()}
        arrays.update({f"tb_{k}": v for k, v in snap["tb"].items()})
        np.savez(os.path.join(tmp, "state.npz"), **arrays)
        with open(os.path.join(tmp, "index.json"), "w") as fh:
            json.dump(snap["meta"], fh)
        if os.path.exists(path):
            old = path + f".old-{os.getpid()}"
            os.rename(path, old)
            os.rename(tmp, path)
            import shutil

            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, path)
    except Exception:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(path: str) -> Dict:
    with open(os.path.join(path, "index.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported checkpoint format: {meta.get('format')}")
    data = np.load(os.path.join(path, "state.npz"))
    return {"meta": meta, "arrays": dict(data)}


def restore_engine_state(engine, ckpt: Dict) -> None:
    """Load checkpointed slot arrays into an engine of the same geometry."""
    import jax.numpy as jnp

    meta = ckpt["meta"]
    if meta["num_slots"] != engine.num_slots:
        raise ValueError(
            f"checkpoint has {meta['num_slots']} slots, engine has "
            f"{engine.num_slots}; geometry must match")
    arrays = ckpt["arrays"]
    sw = engine.sw_state
    tb = engine.tb_state
    shape = np.asarray(sw.win_start).shape  # matches engine layout (1D or 2D)
    engine.sw_state = type(sw)(*(
        jnp.asarray(arrays[f"sw_{f}"].reshape(shape)) for f in sw._fields))
    engine.tb_state = type(tb)(*(
        jnp.asarray(arrays[f"tb_{f}"].reshape(shape)) for f in tb._fields))


# ---------------------------------------------------------------------------
# Index dump/load (Python SlotIndex only — see module docstring)
# ---------------------------------------------------------------------------

def _dump_flat(index) -> list:
    with index._lock:
        return [[list(k) if isinstance(k, tuple) else k, slot]
                for k, slot in index._map.items()]


def _restore_flat(index, entries) -> None:
    with index._lock:
        index._map.clear()
        used = set()
        for key, slot in entries:
            key = tuple(key) if isinstance(key, list) else key
            index._map[key] = int(slot)
            used.add(int(slot))
        index._free = [s for s in range(index.num_slots - 1, -1, -1)
                       if s not in used]


def dump_slot_indexes(storage) -> Dict:
    """Serialize key->slot maps of a TpuBatchedStorage.

    Works for the Python flat index and the sharded index (global slot =
    shard * slots_per_shard + local).  The native index stores fingerprints
    only — construct the storage with checkpointable=True to use the
    enumerable Python index.
    """
    out: Dict = {"algos": {}}
    for algo, index in storage._index.items():
        if hasattr(index, "_map"):
            out["algos"][algo] = {"kind": "flat", "entries": _dump_flat(index)}
        elif hasattr(index, "_sub"):
            if not all(hasattr(s, "_map") for s in index._sub):
                raise ValueError(
                    "native slot sub-indexes are not enumerable; construct "
                    "the storage with checkpointable=True to use Python subs")
            base = index.slots_per_shard
            entries = []
            for shard, sub in enumerate(index._sub):
                for key, local in _dump_flat(sub):
                    entries.append([key, shard * base + local])
            out["algos"][algo] = {
                "kind": "sharded",
                # Key->shard hash identity: a restore into a binary with a
                # different shard hash would silently orphan every entry
                # (lookups would miss the restored shard), so it is refused.
                "shard_hash": SHARD_HASH_VERSION,
                "entries": entries,
            }
        else:
            raise ValueError(
                "native slot index is not enumerable; construct the storage "
                "with checkpointable=True to use the Python index")
    return out


def restore_slot_indexes(storage, dump: Dict) -> None:
    for algo, payload in dump.get("algos", {}).items():
        index = storage._index[algo]
        entries = payload["entries"]
        if payload.get("kind") == "sharded":
            stored_hash = payload.get("shard_hash", SHARD_HASH_VERSION)
            if stored_hash != SHARD_HASH_VERSION:
                raise ValueError(
                    f"checkpoint used shard hash {stored_hash!r}; this "
                    f"binary routes with {SHARD_HASH_VERSION!r} — restoring "
                    "would orphan every entry (export/import per key instead)")
        if hasattr(index, "_map"):
            _restore_flat(index, entries)
        elif hasattr(index, "_sub"):
            base = index.slots_per_shard
            per_shard = [[] for _ in index._sub]
            for key, gslot in entries:
                per_shard[gslot // base].append([key, gslot % base])
            for sub, sub_entries in zip(index._sub, per_shard):
                _restore_flat(sub, sub_entries)
        else:
            raise ValueError("cannot restore into a native slot index")
