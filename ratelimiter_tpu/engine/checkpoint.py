"""Checkpoint / resume for device-resident limiter state.

The reference delegates durability to Redis AOF persistence
(docker-compose.yml enables --appendonly): counters survive an app restart
because they live in Redis.  In this framework the source of truth is HBM,
which dies with the process — so durability is an explicit subsystem
(SURVEY.md §5.4): snapshot the slot arrays and the key->slot index to disk,
restore them on boot.

Format: a directory with
  - ``state.npz``  — the SW/TB slot arrays (numpy int64)
  - ``index.json`` — limiter registrations + key->slot mappings + metadata

Snapshots are crash-consistent (written to a temp dir, atomically renamed)
but geometry-locked (slot arrays restore 1:1; enforced by metadata check).
Cross-geometry migration — growing the table, changing shard counts,
flat <-> sharded — uses the per-KEY path instead: :func:`export_keys` /
:func:`import_keys` (also on ``TpuBatchedStorage``), which re-assign slots
in the target and carry each key's packed state row across.

The native slot index enumerates as (h1, h2, slot) fingerprint triples
(native/slot_index.cpp:rl_index_dump), so the DEFAULT storage checkpoints
at native speed: snapshots carry the fingerprints (state.npz) and restore
rebuilds the table with its exact LRU order.  Fingerprints are one-way,
so only dumps from the keyed Python index (checkpointable=True) can be
re-sharded or re-keyed; flat-to-flat rebalance works from fingerprints
directly (LRU tables assign slots geometry-independently).
"""

from __future__ import annotations

import json
import os
import tempfile
import time
import zlib
from typing import Dict, Optional

import numpy as np


# v1: TBState carried a stored deadline array; v2 derives it from
# last_refill + 2*window and drops the lane. Restore iterates the CURRENT
# field set, so v1 checkpoints load in v2 binaries (the extra tb_deadline
# array is ignored); v2 checkpoints refuse to load in v1 binaries via the
# version check rather than failing on a missing array.
# v3 adds integrity: per-array CRC32s + a manifest checksum over
# index.json itself (a bit-flipped or torn dump must refuse to restore
# with a typed CheckpointCorruptError, not silently hand stale/garbage
# counters to live traffic).  v1/v2 dumps predate the checksums and
# still restore (nothing to verify).
FORMAT_VERSION = 3
SUPPORTED_VERSIONS = (1, 2, 3)


class CheckpointCorruptError(ValueError):
    """The checkpoint failed integrity verification (bit flip, torn
    write, truncated state.npz): restore refuses rather than loading
    corrupted counters."""


def _array_crc(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _manifest_crc(meta: Dict) -> int:
    """CRC of the canonical JSON of the manifest (everything except the
    stored checksum itself) — json.dumps(sort_keys=True) is stable
    across the dump/load round trip, independent of file formatting."""
    canon = json.dumps({k: v for k, v in meta.items()
                        if k != "manifest_crc"}, sort_keys=True)
    return zlib.crc32(canon.encode()) & 0xFFFFFFFF

# Identity of the key->shard routing hash used by sharded indexes
# (parallel/sharded.py:shard_of_key): FNV-fingerprint h1 for string/bytes
# keys (r6 — lets the batched string stream hash once and both route and
# assign from the result), splitmix64 for int keys, crc32-of-repr for
# exotic key types.  Stored in sharded index dumps so a restore into a
# binary with a different routing function fails loudly instead of
# silently orphaning entries.
SHARD_HASH_VERSION = "fp-fnv/splitmix64-v2"
# Sharded dumps written before the shard_hash field existed were produced by
# binaries that routed int user keys via crc32-of-repr.  A missing field
# therefore marks the LEGACY hash, not the current one — restoring a legacy
# dump with int user keys under the current splitmix64 routing would
# silently orphan every int-key entry.
LEGACY_SHARD_HASH = "crc32-repr-v0"
# Dumps under these hashes restore iff every entry already sits where the
# CURRENT hash routes its key (divergence-proof placement check below):
# v0 legacy, and v1 (whose string keys routed by crc32-of-repr — int keys
# route identically in v1 and v2, so int-only v1 dumps restore clean).
PLACEMENT_CHECK_HASHES = (LEGACY_SHARD_HASH, "crc32-repr/splitmix64-v1")


def snapshot_engine_state(engine, index_dump: Optional[Dict] = None) -> Dict:
    """Materialize the device state to host numpy (one blocking transfer)."""
    engine.block_until_ready()
    sw = engine.sw_state
    tb = engine.tb_state
    return {
        "sw": {f: np.asarray(getattr(sw, f)).reshape(-1) for f in sw._fields},
        "tb": {f: np.asarray(getattr(tb, f)).reshape(-1) for f in tb._fields},
        "meta": {
            "format": FORMAT_VERSION,
            "num_slots": engine.num_slots,
            "taken_at_ms": time.time_ns() // 1_000_000,
            "index": index_dump or {},
        },
    }


def _detach_index_arrays(index_dump: Dict, arrays: Dict) -> Dict:
    """Move fingerprint numpy arrays out of the index dump into the npz
    payload (JSON holds a marker; arrays go to state.npz as idx_*)."""
    out = {"algos": {}}
    for algo, payload in index_dump.get("algos", {}).items():
        p = dict(payload)
        if p.get("kind") == "native_fp":
            for f in ("h1", "h2", "slots"):
                arrays[f"idx_{algo}_{f}"] = p.pop(f)
            p["array_ref"] = f"idx_{algo}"
        elif p.get("kind") == "sharded_native_fp":
            for j, shard_p in enumerate(p.pop("per_shard")):
                for f in ("h1", "h2", "slots"):
                    arrays[f"idx_{algo}_s{j}_{f}"] = shard_p[f]
            p["array_ref"] = f"idx_{algo}"
        elif p.get("kind") == "partitioned_native_fp":
            for j, part_p in enumerate(p.pop("per_part")):
                for f in ("h1", "h2", "slots"):
                    arrays[f"idx_{algo}_p{j}_{f}"] = part_p[f]
            p["array_ref"] = f"idx_{algo}"
        out["algos"][algo] = p
    return out


def _attach_index_arrays(meta_index: Dict, arrays: Dict) -> Dict:
    """Inverse of :func:`_detach_index_arrays` at load time."""
    out = {"algos": {}}
    for algo, payload in meta_index.get("algos", {}).items():
        p = dict(payload)
        ref = p.pop("array_ref", None)
        if p.get("kind") == "native_fp":
            for f in ("h1", "h2", "slots"):
                p[f] = arrays[f"{ref}_{f}"]
        elif p.get("kind") == "sharded_native_fp":
            p["per_shard"] = [
                {f: arrays[f"{ref}_s{j}_{f}"] for f in ("h1", "h2", "slots")}
                for j in range(p["n_shards"])]
        elif p.get("kind") == "partitioned_native_fp":
            p["per_part"] = [
                {f: arrays[f"{ref}_p{j}_{f}"] for f in ("h1", "h2", "slots")}
                for j in range(p["n_parts"])]
        out["algos"][algo] = p
    return out


def save_checkpoint(path: str, engine, index_dump: Optional[Dict] = None) -> None:
    """Write an atomic on-disk checkpoint (temp dir + rename)."""
    snap = snapshot_engine_state(engine, index_dump)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt-tmp-", dir=parent)
    try:
        arrays = {f"sw_{k}": v for k, v in snap["sw"].items()}
        arrays.update({f"tb_{k}": v for k, v in snap["tb"].items()})
        snap["meta"]["index"] = _detach_index_arrays(
            snap["meta"].get("index", {}), arrays)
        # Integrity (v3): per-array CRC32s, then a manifest checksum over
        # the final metadata so a flipped byte in index.json itself is
        # also caught at load.
        snap["meta"]["checksums"] = {
            name: _array_crc(arr) for name, arr in arrays.items()}
        snap["meta"]["manifest_crc"] = _manifest_crc(snap["meta"])
        np.savez(os.path.join(tmp, "state.npz"), **arrays)
        with open(os.path.join(tmp, "index.json"), "w") as fh:
            json.dump(snap["meta"], fh)
        if os.path.exists(path):
            old = path + f".old-{os.getpid()}"
            os.rename(path, old)
            os.rename(tmp, path)
            import shutil

            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, path)
    except Exception:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(path: str) -> Dict:
    with open(os.path.join(path, "index.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported checkpoint format: {meta.get('format')}")
    verify = meta.get("format", 0) >= 3
    if verify:
        stored = meta.get("manifest_crc")
        if stored is None or _manifest_crc(meta) != int(stored):
            raise CheckpointCorruptError(
                f"checkpoint manifest checksum mismatch in {path}/"
                "index.json: the manifest is corrupted or was edited — "
                "refusing to restore")
    try:
        # dict() forces every lazily-loaded array out of the zip, so a
        # truncated/torn state.npz fails HERE, typed, not mid-restore.
        data = dict(np.load(os.path.join(path, "state.npz")))
    except CheckpointCorruptError:
        raise
    except Exception as exc:  # noqa: BLE001 — torn/truncated archive
        raise CheckpointCorruptError(
            f"checkpoint state.npz in {path} is unreadable (torn or "
            f"truncated write?): {exc}") from exc
    if verify:
        for name, crc in meta.get("checksums", {}).items():
            if name not in data:
                raise CheckpointCorruptError(
                    f"checkpoint array {name!r} listed in the manifest is "
                    f"missing from state.npz in {path}")
            if _array_crc(data[name]) != int(crc):
                raise CheckpointCorruptError(
                    f"checkpoint array {name!r} failed its CRC32 in "
                    f"{path} (bit flip or torn write) — refusing to "
                    "restore")
    meta["index"] = _attach_index_arrays(meta.get("index", {}), data)
    return {"meta": meta, "arrays": data}


def restore_engine_state(engine, ckpt: Dict) -> None:
    """Load checkpointed slot arrays into an engine of the same geometry."""
    import jax.numpy as jnp

    meta = ckpt["meta"]
    if meta["num_slots"] != engine.num_slots:
        raise ValueError(
            f"checkpoint has {meta['num_slots']} slots, engine has "
            f"{engine.num_slots}; geometry must match")
    arrays = ckpt["arrays"]
    sw = engine.sw_state
    tb = engine.tb_state
    shape = np.asarray(sw.win_start).shape  # matches engine layout (1D or 2D)
    engine.sw_state = type(sw)(*(
        jnp.asarray(arrays[f"sw_{f}"].reshape(shape)) for f in sw._fields))
    engine.tb_state = type(tb)(*(
        jnp.asarray(arrays[f"tb_{f}"].reshape(shape)) for f in tb._fields))


# ---------------------------------------------------------------------------
# Per-key export/import (geometry-free rebalance)
# ---------------------------------------------------------------------------
# Checkpoints are geometry-locked (slot arrays restore 1:1). Rebalancing —
# growing the slot table, changing shard counts, moving to different
# hardware — goes through per-KEY state instead: export every live
# (key -> packed state row), import assigns fresh slots in the target and
# writes the rows back. Works across any source/target geometry, flat or
# sharded, as long as the index is enumerable (checkpointable=True).


def _limiter_table_dump(storage) -> Dict:
    """Registered limiter policies, keyed by lid (import-side validation).

    Each row carries its policy generation (``gen``; 0 = as registered)
    so a standby replaying the dump can tell a LIVE policy update —
    which it must apply via ``set_policy`` at the primary's stamp — from
    registration drift, which stays a hard error (ARCHITECTURE §15)."""
    table = getattr(storage, "table", None)
    return {
        str(lid): {
            "algo": algo,
            "max_permits": cfg.max_permits,
            "window_ms": cfg.window_ms,
            "refill_rate": cfg.refill_rate,
            "gen": (table.row_generation(lid) if table is not None
                    and hasattr(table, "row_generation") else 0),
        }
        for lid, (algo, cfg) in storage._configs.items()
    }


def limiter_policy_dump(storage) -> Dict:
    """Public form of :func:`_limiter_table_dump`: the storage's policy
    rows in exactly the shape the control-plane ``set_policy`` op (and
    :func:`apply_limiter_policies`) consumes.  The fleet controller's
    broadcast and anti-entropy paths (``control/fleet.py``) are built
    on this — one row format end to end, so a checkpoint restore, a
    replication bootstrap, and a leader broadcast all converge a node
    through the same idempotent apply."""
    return _limiter_table_dump(storage)


def apply_limiter_policies(storage, limiters: Dict, *,
                           register_missing: bool = False) -> None:
    """Reconcile a limiter dump against a target storage.

    - Missing lids are registered in lid order when ``register_missing``
      (the standby-bootstrap path); otherwise they are a hard error.
    - Shape drift (algo or window) always raises — replicated rows
      would silently mis-decide under a different window.
    - RATE drift with a strictly newer ``gen`` is a live policy update
      (ARCHITECTURE §15): applied via ``set_policy`` at the dump's
      exact generation stamp, so a promoted standby serves the
      post-update generation.  Rate drift without a newer generation is
      true registration drift and raises, as before.
    """
    from ratelimiter_tpu.core.config import RateLimitConfig

    have = storage._configs
    table = getattr(storage, "table", None)
    for lid in sorted(limiters, key=int):
        cfg = limiters[lid]
        lid_i = int(lid)
        src_gen = int(cfg.get("gen", 0))
        if lid_i not in have:
            if not register_missing:
                raise ValueError(
                    f"limiter id {lid_i} is not registered on the "
                    "target; register identical limiters in the same "
                    "order first")
            got = storage.register_limiter(
                cfg["algo"],
                RateLimitConfig(max_permits=cfg["max_permits"],
                                window_ms=cfg["window_ms"],
                                refill_rate=cfg["refill_rate"]))
            if got != lid_i:
                raise ValueError(
                    f"standby assigned lid {got} where the primary has "
                    f"{lid_i}; register limiters in the same order on "
                    "both sides (or let replication do all registration)")
            if src_gen > 0 and table is not None \
                    and hasattr(table, "set_policy"):
                # Freshly registered from a dump that already carries a
                # live update: stamp the primary's generation.
                storage.set_policy(lid_i, RateLimitConfig(
                    max_permits=cfg["max_permits"],
                    window_ms=cfg["window_ms"],
                    refill_rate=cfg["refill_rate"]), generation=src_gen)
            continue
        algo, existing = have[lid_i]
        if algo != cfg["algo"] or existing.window_ms != cfg["window_ms"]:
            raise ValueError(
                f"limiter {lid_i} diverges from the dump in its "
                "algo/window shape; replicated state cannot be served "
                "under a different window")
        rates_match = (existing.max_permits == cfg["max_permits"]
                       and existing.refill_rate == cfg["refill_rate"])
        local_gen = (table.row_generation(lid_i)
                     if table is not None
                     and hasattr(table, "row_generation") else 0)
        if rates_match:
            if src_gen > local_gen and table is not None \
                    and hasattr(table, "bump_generation"):
                table.bump_generation(src_gen)
            continue
        if src_gen > local_gen and hasattr(storage, "set_policy"):
            storage.set_policy(lid_i, RateLimitConfig(
                max_permits=cfg["max_permits"],
                window_ms=cfg["window_ms"],
                refill_rate=cfg["refill_rate"],
                enable_local_cache=existing.enable_local_cache,
                local_cache_ttl_ms=existing.local_cache_ttl_ms,
            ), generation=src_gen)
            continue
        raise ValueError(
            f"limiter {lid_i} mismatch: the target's rates diverge from "
            "the dump's registration with no newer policy generation to "
            "justify it; register identical limiters in the same order "
            "(live set_policy updates carry their generation and apply)")


def export_keys(storage) -> Dict:
    """All live per-key state of a storage.

    Keyed (Python) indexes export ``{algo: [[key, row-ints], ...]}`` —
    importable into ANY geometry (keys re-hash in the target).  Native flat
    indexes export fingerprint payloads ``{kind: 'fp', h1, h2, rows}`` —
    importable into flat native targets of any size (fingerprints are
    geometry-independent for LRU-assigned tables) but not re-shardable.
    """
    # Flush BEFORE dumping: a flush can assign/evict, reusing a dumped
    # slot — the fp export reads rows by slot, so a stale dump would
    # attribute another key's state to a dumped fingerprint.
    storage.flush()
    storage.engine.block_until_ready()
    index_dump = dump_slot_indexes(storage)
    out: Dict = {
        "format": FORMAT_VERSION,
        "limiters": _limiter_table_dump(storage),
        "algos": {},
    }
    for algo, payload in index_dump["algos"].items():
        if payload.get("kind") == "native_fp":
            slots = payload["slots"]
            out["algos"][algo] = {
                "kind": "fp",
                "h1": payload["h1"],
                "h2": payload["h2"],
                "rows": (storage.engine.read_rows(algo, slots)
                         if len(slots) else np.empty((0, 0), np.int32)),
            }
            continue
        if payload.get("kind") == "partitioned_native_fp":
            # Host-partitioned index: fingerprints are geometry-free once
            # merged with their global slot ids (the partitioned dump is
            # only partition-ADDRESSED, not partition-HASHED), so the
            # export is the same flat 'fp' payload — importable into flat
            # native targets; import into a partitioned target refuses
            # (fingerprints cannot be re-routed).
            index = storage._index[algo]
            h1, h2, slots = index.dump_fp()
            out["algos"][algo] = {
                "kind": "fp",
                "h1": h1,
                "h2": h2,
                "rows": (storage.engine.read_rows(algo, slots)
                         if len(slots) else np.empty((0, 0), np.int32)),
            }
            continue
        if payload.get("kind") == "sharded_native_fp":
            raise ValueError(
                "sharded native dumps cannot be exported per key "
                "(fingerprints cannot be re-sharded); construct the "
                "storage with checkpointable=True for keyed export")
        entries = payload["entries"]
        if not entries:
            out["algos"][algo] = []
            continue
        slots = [slot for _, slot in entries]
        rows = storage.engine.read_rows(algo, slots)
        out["algos"][algo] = [
            [key, [int(v) for v in row]] for (key, _), row in zip(entries, rows)
        ]
    return out


def import_keys(storage, dump: Dict) -> None:
    """Assign slots for exported keys in ``storage`` and write their state.

    The target may have any geometry (more slots, different shard count,
    flat vs sharded). Keys route through the target's own index, so shard
    placement follows the target's hash — this IS the rebalance.

    Refuses up front (before touching the target) when the dump's format
    differs, when limiter registrations don't line up, or when the target
    lacks capacity for the new keys — a partial import would silently hand
    fresh quota to keys the export showed as consumed.
    """
    if dump.get("format", FORMAT_VERSION) not in SUPPORTED_VERSIONS:
        raise ValueError(
            f"unsupported export format: {dump.get('format')}")
    # Limiter ids inside index keys are SOURCE lids; they must mean the
    # same policy in the target or imported state attaches to the wrong
    # limiter (or to none).  Rate drift carrying a newer policy
    # generation is a live update and is adopted (the exported keys'
    # state was consumed under the dump's policies); anything else
    # refuses before touching the target.
    apply_limiter_policies(storage, dump.get("limiters", {}),
                           register_missing=False)
    # Capacity pre-check: every key not already present needs a free slot.
    # For sharded targets the check is PER SHARD — capacity there is not
    # fungible (a key's shard is fixed by hash), so a global count could
    # pass while one shard overflows mid-import, leaving a partial import.
    for algo, entries in dump.get("algos", {}).items():
        index = storage._index[algo]
        if isinstance(entries, dict) and entries.get("kind") == "fp":
            if not hasattr(index, "assign_batch_fps"):
                raise ValueError(
                    "fingerprint export requires a flat native-index "
                    "target (fingerprints cannot be re-keyed or "
                    "re-sharded)")
            present = index.lookup_fps(entries["h1"], entries["h2"]) >= 0
            new = int((~present).sum())
            free = index.num_slots - len(index)
            if new > free:
                raise ValueError(
                    f"target storage is too small for the export ({new} "
                    f"new {algo} fingerprints, {free} free slots)")
        elif hasattr(index, "_sub") or hasattr(index, "_parts"):
            # Capacity is per shard/partition — a key's placement is fixed
            # by hash, so a global count could pass while one bucket
            # overflows mid-import, leaving a partial import.
            from ratelimiter_tpu.parallel.sharded import shard_of_key

            subs = index._sub if hasattr(index, "_sub") else index._parts
            per_sub_cap = (index.slots_per_shard if hasattr(index, "_sub")
                           else index.slots_per_part)
            new_per_sub = [0] * len(subs)
            for key, _ in entries:
                key = tuple(key) if isinstance(key, list) else key
                bucket = shard_of_key(key, len(subs))
                if subs[bucket].get(key) is None:
                    new_per_sub[bucket] += 1
            word = "shard" if hasattr(index, "_sub") else "partition"
            for bucket, (sub, new) in enumerate(zip(subs, new_per_sub)):
                free = per_sub_cap - len(sub)
                if new > free:
                    raise ValueError(
                        f"target {word} {bucket} is too small for the "
                        f"export ({new} new {algo} keys, {free} free "
                        "slots)")
        else:
            new = sum(
                1 for key, _ in entries
                if index.get(tuple(key) if isinstance(key, list) else key)
                is None)
            free = index.num_slots - len(index)
            if new > free:
                raise ValueError(
                    f"target storage is too small for the export ({new} new "
                    f"{algo} keys, {free} free slots)")
    for algo, entries in dump.get("algos", {}).items():
        if isinstance(entries, dict) and entries.get("kind") == "fp":
            if not len(entries["h1"]):
                continue
            index = storage._index[algo]
            # Dump order is MRU-first; assign REVERSED so the source's
            # most-recent fingerprint is also assigned last (= most recent
            # in the target), preserving eviction order across a rebalance.
            slots, evicted = index.assign_batch_fps(
                entries["h1"][::-1], entries["h2"][::-1])
            if len(evicted):  # pre-check makes this unreachable
                raise ValueError(
                    "eviction during import despite capacity check")
            rows = np.asarray(entries["rows"], dtype=np.int32)[::-1]
            storage.engine.write_rows(algo, slots, rows)
            continue
        if not entries:
            continue
        index = storage._index[algo]
        slots = []
        for key, _ in entries:
            key = tuple(key) if isinstance(key, list) else key
            slot, evicted = index.assign(key)
            if evicted is not None:  # pre-check makes this unreachable
                raise ValueError("eviction during import despite capacity check")
            slots.append(slot)
        rows = np.asarray([row for _, row in entries], dtype=np.int32)
        storage.engine.write_rows(algo, slots, rows)
    storage.engine.block_until_ready()


# ---------------------------------------------------------------------------
# Index dump/load (Python SlotIndex only — see module docstring)
# ---------------------------------------------------------------------------

def _dump_flat(index) -> list:
    with index._lock:
        return [[list(k) if isinstance(k, tuple) else k, slot]
                for k, slot in index._map.items()]


def _fp_payload(index) -> Dict:
    """Fingerprint dump of a native index (h1/h2/slot numpy arrays, MRU
    order).  save_checkpoint moves the arrays into state.npz."""
    h1, h2, slots = index.dump_fp()
    return {"h1": h1, "h2": h2, "slots": slots}


def _restore_flat(index, entries) -> None:
    with index._lock:
        index._map.clear()
        used = set()
        for key, slot in entries:
            key = tuple(key) if isinstance(key, list) else key
            index._map[key] = int(slot)
            used.add(int(slot))
        index._free = [s for s in range(index.num_slots - 1, -1, -1)
                       if s not in used]


def dump_shard_slot_indexes(storage, shard: int) -> Dict:
    """Serialize ONE shard's key->slot sub-indexes (local slot ids) in
    the same payload shape ``restore_slot_indexes`` accepts on a FLAT
    storage of ``slots_per_shard`` geometry — the per-shard replication
    stream's index journal (replication/sharded.py): a per-shard standby
    is just an ordinary flat standby, so its promotion path is the
    ordinary ``promote_from_replica``."""
    out: Dict = {"algos": {}}
    for algo, index in storage._index.items():
        if not hasattr(index, "_sub"):
            raise ValueError("per-shard index dump needs the sharded "
                             "slot index")
        sub = index._sub[int(shard)]
        if hasattr(sub, "dump_fp"):
            payload = _fp_payload(sub)
            payload["kind"] = "native_fp"
            out["algos"][algo] = payload
        elif hasattr(sub, "_map"):
            out["algos"][algo] = {"kind": "flat",
                                  "entries": _dump_flat(sub)}
        else:
            raise ValueError("slot sub-index is not enumerable")
    return out


def dump_slot_indexes(storage) -> Dict:
    """Serialize key->slot maps of a TpuBatchedStorage.

    Python indexes dump their keys; native indexes dump (h1, h2, slot)
    fingerprint triples at native speed (rl_index_dump) — checkpoints
    round-trip either way.  Fingerprints are one-way, so dumps that must
    carry keys (cross-shard rebalance) need the Python index
    (checkpointable=True).
    """
    out: Dict = {"algos": {}}
    for algo, index in storage._index.items():
        if hasattr(index, "_map"):
            out["algos"][algo] = {"kind": "flat", "entries": _dump_flat(index)}
        elif hasattr(index, "_parts"):
            # Host-parallel partitioned index: per-partition fingerprint
            # dumps (local slots) + the routing-hash identity, since a
            # restore under different routing would orphan every entry.
            out["algos"][algo] = {
                "kind": "partitioned_native_fp",
                "part_hash": SHARD_HASH_VERSION,
                "n_parts": index.n_parts,
                "per_part": [_fp_payload(s) for s in index._parts],
            }
        elif hasattr(index, "dump_fp"):
            payload = _fp_payload(index)
            payload["kind"] = "native_fp"
            out["algos"][algo] = payload
        elif hasattr(index, "_sub"):
            if all(hasattr(s, "_map") for s in index._sub):
                base = index.slots_per_shard
                entries = []
                for shard, sub in enumerate(index._sub):
                    for key, local in _dump_flat(sub):
                        entries.append([key, shard * base + local])
                out["algos"][algo] = {
                    "kind": "sharded",
                    # Key->shard hash identity: a restore into a binary with
                    # a different shard hash would silently orphan every
                    # entry (lookups would miss the restored shard).
                    "shard_hash": SHARD_HASH_VERSION,
                    "entries": entries,
                }
            elif all(hasattr(s, "dump_fp") for s in index._sub):
                out["algos"][algo] = {
                    "kind": "sharded_native_fp",
                    "shard_hash": SHARD_HASH_VERSION,
                    "n_shards": index.n_shards,
                    "per_shard": [_fp_payload(s) for s in index._sub],
                }
            else:
                raise ValueError("slot sub-indexes are not enumerable")
        else:
            raise ValueError("slot index is not enumerable")
    return out


def restore_slot_indexes(storage, dump: Dict) -> None:
    for algo, payload in dump.get("algos", {}).items():
        index = storage._index[algo]
        kind = payload.get("kind")
        if kind == "native_fp":
            if hasattr(index, "_parts"):
                raise ValueError(
                    "flat fingerprint checkpoint cannot restore into a "
                    "host-partitioned index: fingerprints are one-way, so "
                    "entries cannot be re-routed to their partitions "
                    "(restore with host_parallel=0, or export/import per "
                    "key)")
            if not hasattr(index, "restore_fp"):
                raise ValueError(
                    "fingerprint checkpoint needs the native index "
                    "(restoring binary lacks it)")
            index.restore_fp(payload["h1"], payload["h2"], payload["slots"])
            continue
        if kind == "partitioned_native_fp":
            if payload.get("part_hash") != SHARD_HASH_VERSION:
                raise ValueError(
                    f"checkpoint used partition hash "
                    f"{payload.get('part_hash')!r}; this binary routes "
                    f"with {SHARD_HASH_VERSION!r} — fingerprints cannot "
                    "be re-partitioned (export/import per key instead)")
            if (not hasattr(index, "_parts")
                    or payload["n_parts"] != index.n_parts):
                raise ValueError(
                    "partitioned fingerprint checkpoint needs a "
                    f"host-parallel index with {payload['n_parts']} "
                    "partitions (restore with the same host_parallel)")
            for sub, part_p in zip(index._parts, payload["per_part"]):
                sub.restore_fp(part_p["h1"], part_p["h2"], part_p["slots"])
            continue
        if kind == "sharded_native_fp":
            if payload.get("shard_hash") != SHARD_HASH_VERSION:
                raise ValueError(
                    f"checkpoint used shard hash "
                    f"{payload.get('shard_hash')!r}; this binary routes "
                    f"with {SHARD_HASH_VERSION!r} — fingerprints cannot be "
                    "re-sharded (export/import per key instead)")
            if (not hasattr(index, "_sub")
                    or payload["n_shards"] != index.n_shards
                    or not all(hasattr(s, "restore_fp")
                               for s in index._sub)):
                raise ValueError(
                    "sharded fingerprint checkpoint needs a native sharded "
                    f"index with {payload['n_shards']} shards")
            for sub, shard_p in zip(index._sub, payload["per_shard"]):
                sub.restore_fp(shard_p["h1"], shard_p["h2"],
                               shard_p["slots"])
            continue
        entries = payload["entries"]
        if payload.get("kind") == "sharded" and hasattr(index, "_sub"):
            stored_hash = payload.get("shard_hash", LEGACY_SHARD_HASH)
            if stored_hash != SHARD_HASH_VERSION:
                # A dump written under a different KNOWN routing hash
                # restores safely only if every entry already sits where
                # the CURRENT hash routes its key.  Checking placement
                # directly is divergence-proof: it needs no model of what
                # the old hash did — any entry whose old placement matches
                # the current routing resolves correctly, and everything
                # else fails loudly (e.g. v0 int/bool keys, v1 string
                # keys, both of which routed differently than today).
                from ratelimiter_tpu.parallel.sharded import shard_of_key

                sps = index.slots_per_shard
                ok = stored_hash in PLACEMENT_CHECK_HASHES and all(
                    shard_of_key(tuple(key) if isinstance(key, list)
                                 else key, index.n_shards) == gslot // sps
                    for key, gslot in entries)
                if not ok:
                    raise ValueError(
                        f"checkpoint used shard hash {stored_hash!r}; this "
                        f"binary routes with {SHARD_HASH_VERSION!r} — "
                        "restoring would orphan entries (export/import per "
                        "key instead)")
        if hasattr(index, "_map"):
            _restore_flat(index, entries)
        elif hasattr(index, "_sub"):
            base = index.slots_per_shard
            per_shard = [[] for _ in index._sub]
            for key, gslot in entries:
                per_shard[gslot // base].append([key, gslot % base])
            for sub, sub_entries in zip(index._sub, per_shard):
                _restore_flat(sub, sub_entries)
        else:
            raise ValueError("cannot restore into a native slot index")
