"""Checkpoint / resume for device-resident limiter state.

The reference delegates durability to Redis AOF persistence
(docker-compose.yml enables --appendonly): counters survive an app restart
because they live in Redis.  In this framework the source of truth is HBM,
which dies with the process — so durability is an explicit subsystem
(SURVEY.md §5.4): snapshot the slot arrays and the key->slot index to disk,
restore them on boot.

Format: a directory with
  - ``state.npz``  — the SW/TB slot arrays (numpy int64)
  - ``index.json`` — limiter registrations + key->slot mappings + metadata

Snapshots are crash-consistent (written to a temp dir, atomically renamed)
but geometry-locked (slot arrays restore 1:1; enforced by metadata check).
Cross-geometry migration — growing the table, changing shard counts,
flat <-> sharded — uses the per-KEY path instead: :func:`export_keys` /
:func:`import_keys` (also on ``TpuBatchedStorage``), which re-assign slots
in the target and carry each key's packed state row across.

The native slot index cannot enumerate its keys (it stores fingerprints
only), so checkpointable deployments either use the Python index
(``TpuBatchedStorage(checkpointable=True)``) or supply key enumeration at
snapshot time from the service tier.  The device state itself snapshots
regardless of index type.
"""

from __future__ import annotations

import json
import os
import tempfile
import time
from typing import Dict, Optional

import numpy as np


# v1: TBState carried a stored deadline array; v2 derives it from
# last_refill + 2*window and drops the lane. Restore iterates the CURRENT
# field set, so v1 checkpoints load in v2 binaries (the extra tb_deadline
# array is ignored); v2 checkpoints refuse to load in v1 binaries via the
# version check rather than failing on a missing array.
FORMAT_VERSION = 2
SUPPORTED_VERSIONS = (1, 2)

# Identity of the key->shard routing hash used by sharded indexes
# (parallel/sharded.py:shard_of_key): crc32-of-repr for string keys,
# splitmix64 for int keys.  Stored in sharded index dumps so a restore into
# a binary with a different routing function fails loudly instead of
# silently orphaning entries.
SHARD_HASH_VERSION = "crc32-repr/splitmix64-v1"
# Sharded dumps written before the shard_hash field existed were produced by
# binaries that routed int user keys via crc32-of-repr (strings routed the
# same as today).  A missing field therefore marks the LEGACY hash, not the
# current one — restoring a legacy dump with int user keys under the current
# splitmix64 routing would silently orphan every int-key entry.
LEGACY_SHARD_HASH = "crc32-repr-v0"


def snapshot_engine_state(engine, index_dump: Optional[Dict] = None) -> Dict:
    """Materialize the device state to host numpy (one blocking transfer)."""
    engine.block_until_ready()
    sw = engine.sw_state
    tb = engine.tb_state
    return {
        "sw": {f: np.asarray(getattr(sw, f)).reshape(-1) for f in sw._fields},
        "tb": {f: np.asarray(getattr(tb, f)).reshape(-1) for f in tb._fields},
        "meta": {
            "format": FORMAT_VERSION,
            "num_slots": engine.num_slots,
            "taken_at_ms": time.time_ns() // 1_000_000,
            "index": index_dump or {},
        },
    }


def save_checkpoint(path: str, engine, index_dump: Optional[Dict] = None) -> None:
    """Write an atomic on-disk checkpoint (temp dir + rename)."""
    snap = snapshot_engine_state(engine, index_dump)
    parent = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(parent, exist_ok=True)
    tmp = tempfile.mkdtemp(prefix=".ckpt-tmp-", dir=parent)
    try:
        arrays = {f"sw_{k}": v for k, v in snap["sw"].items()}
        arrays.update({f"tb_{k}": v for k, v in snap["tb"].items()})
        np.savez(os.path.join(tmp, "state.npz"), **arrays)
        with open(os.path.join(tmp, "index.json"), "w") as fh:
            json.dump(snap["meta"], fh)
        if os.path.exists(path):
            old = path + f".old-{os.getpid()}"
            os.rename(path, old)
            os.rename(tmp, path)
            import shutil

            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, path)
    except Exception:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
        raise


def load_checkpoint(path: str) -> Dict:
    with open(os.path.join(path, "index.json")) as fh:
        meta = json.load(fh)
    if meta.get("format") not in SUPPORTED_VERSIONS:
        raise ValueError(f"unsupported checkpoint format: {meta.get('format')}")
    data = np.load(os.path.join(path, "state.npz"))
    return {"meta": meta, "arrays": dict(data)}


def restore_engine_state(engine, ckpt: Dict) -> None:
    """Load checkpointed slot arrays into an engine of the same geometry."""
    import jax.numpy as jnp

    meta = ckpt["meta"]
    if meta["num_slots"] != engine.num_slots:
        raise ValueError(
            f"checkpoint has {meta['num_slots']} slots, engine has "
            f"{engine.num_slots}; geometry must match")
    arrays = ckpt["arrays"]
    sw = engine.sw_state
    tb = engine.tb_state
    shape = np.asarray(sw.win_start).shape  # matches engine layout (1D or 2D)
    engine.sw_state = type(sw)(*(
        jnp.asarray(arrays[f"sw_{f}"].reshape(shape)) for f in sw._fields))
    engine.tb_state = type(tb)(*(
        jnp.asarray(arrays[f"tb_{f}"].reshape(shape)) for f in tb._fields))


# ---------------------------------------------------------------------------
# Per-key export/import (geometry-free rebalance)
# ---------------------------------------------------------------------------
# Checkpoints are geometry-locked (slot arrays restore 1:1). Rebalancing —
# growing the slot table, changing shard counts, moving to different
# hardware — goes through per-KEY state instead: export every live
# (key -> packed state row), import assigns fresh slots in the target and
# writes the rows back. Works across any source/target geometry, flat or
# sharded, as long as the index is enumerable (checkpointable=True).


def _limiter_table_dump(storage) -> Dict:
    """Registered limiter policies, keyed by lid (import-side validation)."""
    return {
        str(lid): {
            "algo": algo,
            "max_permits": cfg.max_permits,
            "window_ms": cfg.window_ms,
            "refill_rate": cfg.refill_rate,
        }
        for lid, (algo, cfg) in storage._configs.items()
    }


def export_keys(storage) -> Dict:
    """All live per-key state of a storage: {algo: [[key, row-ints], ...]}."""
    index_dump = dump_slot_indexes(storage)
    storage.flush()
    storage.engine.block_until_ready()
    out: Dict = {
        "format": FORMAT_VERSION,
        "limiters": _limiter_table_dump(storage),
        "algos": {},
    }
    for algo, payload in index_dump["algos"].items():
        entries = payload["entries"]
        if not entries:
            out["algos"][algo] = []
            continue
        slots = [slot for _, slot in entries]
        rows = storage.engine.read_rows(algo, slots)
        out["algos"][algo] = [
            [key, [int(v) for v in row]] for (key, _), row in zip(entries, rows)
        ]
    return out


def import_keys(storage, dump: Dict) -> None:
    """Assign slots for exported keys in ``storage`` and write their state.

    The target may have any geometry (more slots, different shard count,
    flat vs sharded). Keys route through the target's own index, so shard
    placement follows the target's hash — this IS the rebalance.

    Refuses up front (before touching the target) when the dump's format
    differs, when limiter registrations don't line up, or when the target
    lacks capacity for the new keys — a partial import would silently hand
    fresh quota to keys the export showed as consumed.
    """
    if dump.get("format", FORMAT_VERSION) not in SUPPORTED_VERSIONS:
        raise ValueError(
            f"unsupported export format: {dump.get('format')}")
    # Limiter ids inside index keys are SOURCE lids; they must mean the
    # same policy in the target or imported state attaches to the wrong
    # limiter (or to none).
    target = _limiter_table_dump(storage)
    for lid, src_cfg in dump.get("limiters", {}).items():
        dst_cfg = target.get(lid)
        if dst_cfg != src_cfg:
            raise ValueError(
                f"limiter id {lid} mismatch: export has {src_cfg}, "
                f"target has {dst_cfg}; register identical limiters in the "
                "same order before importing")
    # Capacity pre-check: every key not already present needs a free slot.
    # For sharded targets the check is PER SHARD — capacity there is not
    # fungible (a key's shard is fixed by hash), so a global count could
    # pass while one shard overflows mid-import, leaving a partial import.
    for algo, entries in dump.get("algos", {}).items():
        index = storage._index[algo]
        if hasattr(index, "_sub"):
            from ratelimiter_tpu.parallel.sharded import shard_of_key

            new_per_shard = [0] * index.n_shards
            for key, _ in entries:
                key = tuple(key) if isinstance(key, list) else key
                shard = shard_of_key(key, index.n_shards)
                if index._sub[shard].get(key) is None:
                    new_per_shard[shard] += 1
            for shard, (sub, new) in enumerate(zip(index._sub, new_per_shard)):
                free = index.slots_per_shard - len(sub)
                if new > free:
                    raise ValueError(
                        f"target shard {shard} is too small for the export "
                        f"({new} new {algo} keys, {free} free slots)")
        else:
            new = sum(
                1 for key, _ in entries
                if index.get(tuple(key) if isinstance(key, list) else key)
                is None)
            free = index.num_slots - len(index)
            if new > free:
                raise ValueError(
                    f"target storage is too small for the export ({new} new "
                    f"{algo} keys, {free} free slots)")
    for algo, entries in dump.get("algos", {}).items():
        if not entries:
            continue
        index = storage._index[algo]
        slots = []
        for key, _ in entries:
            key = tuple(key) if isinstance(key, list) else key
            slot, evicted = index.assign(key)
            if evicted is not None:  # pre-check makes this unreachable
                raise ValueError("eviction during import despite capacity check")
            slots.append(slot)
        rows = np.asarray([row for _, row in entries], dtype=np.int32)
        storage.engine.write_rows(algo, slots, rows)
    storage.engine.block_until_ready()


# ---------------------------------------------------------------------------
# Index dump/load (Python SlotIndex only — see module docstring)
# ---------------------------------------------------------------------------

def _dump_flat(index) -> list:
    with index._lock:
        return [[list(k) if isinstance(k, tuple) else k, slot]
                for k, slot in index._map.items()]


def _restore_flat(index, entries) -> None:
    with index._lock:
        index._map.clear()
        used = set()
        for key, slot in entries:
            key = tuple(key) if isinstance(key, list) else key
            index._map[key] = int(slot)
            used.add(int(slot))
        index._free = [s for s in range(index.num_slots - 1, -1, -1)
                       if s not in used]


def dump_slot_indexes(storage) -> Dict:
    """Serialize key->slot maps of a TpuBatchedStorage.

    Works for the Python flat index and the sharded index (global slot =
    shard * slots_per_shard + local).  The native index stores fingerprints
    only — construct the storage with checkpointable=True to use the
    enumerable Python index.
    """
    out: Dict = {"algos": {}}
    for algo, index in storage._index.items():
        if hasattr(index, "_map"):
            out["algos"][algo] = {"kind": "flat", "entries": _dump_flat(index)}
        elif hasattr(index, "_sub"):
            if not all(hasattr(s, "_map") for s in index._sub):
                raise ValueError(
                    "native slot sub-indexes are not enumerable; construct "
                    "the storage with checkpointable=True to use Python subs")
            base = index.slots_per_shard
            entries = []
            for shard, sub in enumerate(index._sub):
                for key, local in _dump_flat(sub):
                    entries.append([key, shard * base + local])
            out["algos"][algo] = {
                "kind": "sharded",
                # Key->shard hash identity: a restore into a binary with a
                # different shard hash would silently orphan every entry
                # (lookups would miss the restored shard), so it is refused.
                "shard_hash": SHARD_HASH_VERSION,
                "entries": entries,
            }
        else:
            raise ValueError(
                "native slot index is not enumerable; construct the storage "
                "with checkpointable=True to use the Python index")
    return out


def restore_slot_indexes(storage, dump: Dict) -> None:
    for algo, payload in dump.get("algos", {}).items():
        index = storage._index[algo]
        entries = payload["entries"]
        if payload.get("kind") == "sharded" and hasattr(index, "_sub"):
            stored_hash = payload.get("shard_hash", LEGACY_SHARD_HASH)
            if stored_hash != SHARD_HASH_VERSION:
                # A dump written under a different routing hash restores
                # safely only if every entry already sits where the CURRENT
                # hash routes its key (true for legacy string keys — crc32
                # of repr then and now).  Checking placement directly is
                # divergence-proof: it needs no model of what the old hash
                # did, so legacy int/bool keys (which routed differently)
                # fail it, and any entry that happens to match routes —
                # and therefore resolves — correctly.
                from ratelimiter_tpu.parallel.sharded import shard_of_key

                sps = index.slots_per_shard
                ok = stored_hash == LEGACY_SHARD_HASH and all(
                    shard_of_key(tuple(key) if isinstance(key, list)
                                 else key, index.n_shards) == gslot // sps
                    for key, gslot in entries)
                if not ok:
                    raise ValueError(
                        f"checkpoint used shard hash {stored_hash!r}; this "
                        f"binary routes with {SHARD_HASH_VERSION!r} — "
                        "restoring would orphan entries (export/import per "
                        "key instead)")
        if hasattr(index, "_map"):
            _restore_flat(index, entries)
        elif hasattr(index, "_sub"):
            base = index.slots_per_shard
            per_shard = [[] for _ in index._sub]
            for key, gslot in entries:
                per_shard[gslot // base].append([key, gslot % base])
            for sub, sub_entries in zip(index._sub, per_shard):
                _restore_flat(sub, sub_entries)
        else:
            raise ValueError("cannot restore into a native slot index")
