"""Host-parallel slot index: T native sub-indexes, one worker thread each.

The C hash probe is DRAM-latency-bound (~54 ns/request single-threaded —
bench notes in ARCHITECTURE.md), which caps the host at ~18M assigns/s
while the relay device step and the wire could go faster.  Partitioning
the key space over T native sub-indexes (same splitmix64 routing as the
device-sharded index) lets T ctypes calls run truly in parallel — the C
calls release the GIL — so batch assignment scales with memory
parallelism instead of serializing on one probe stream.

Semantics: identical to ShardedSlotIndex's host side — eviction is
per-partition LRU (a key's slot never migrates between partitions), and
global slot id = partition * slots_per_part + local slot.  This is the
same recency trade the device-sharded deployment already makes; the
single-LRU NativeSlotIndex remains the default.

Used by TpuBatchedStorage(host_parallel=T) on single-device engines; the
sharded engine keeps its own per-shard routing (one partition per device
shard).
"""

from __future__ import annotations

import concurrent.futures as cf
from typing import Hashable, Optional, Set, Tuple

import numpy as np

from ratelimiter_tpu.engine.errors import consume_pending_clears
from ratelimiter_tpu.engine.native_index import NativeSlotIndex


def _part_of_int_keys(key_ids: np.ndarray, n_parts: int) -> np.ndarray:
    from ratelimiter_tpu.parallel.sharded import shard_of_int_keys

    return shard_of_int_keys(key_ids, n_parts)


def _part_of_key(key, n_parts: int) -> int:
    from ratelimiter_tpu.parallel.sharded import shard_of_key

    return shard_of_key(key, n_parts)


class PartitionedSlotIndex:
    """Drop-in NativeSlotIndex with T-way host parallelism.

    Exposes the same vectorized surface (assign_batch_ints[_multi],
    assign_batch_strs, the *_uniques relay family) plus the scalar
    SlotIndex contract.  Fingerprint dump/restore enumerates per
    partition, so checkpoints carry the exact per-partition LRU orders.
    """

    def __init__(self, num_slots: int, n_parts: int = 4):
        if num_slots % n_parts:
            raise ValueError("num_slots must divide evenly by n_parts")
        self.num_slots = int(num_slots)
        self.n_parts = int(n_parts)
        self.slots_per_part = self.num_slots // self.n_parts
        self._parts = [NativeSlotIndex(self.slots_per_part)
                       for _ in range(self.n_parts)]
        self._pool = cf.ThreadPoolExecutor(
            self.n_parts, thread_name_prefix="slotidx")

    def close(self) -> None:
        self._pool.shutdown(wait=False)

    # -- scalar interface ------------------------------------------------------
    def _local_pins(self, pinned, part):
        if not pinned:
            return None
        spp = self.slots_per_part
        return {s % spp for s in pinned if s // spp == part}

    def get(self, key: Hashable) -> Optional[int]:
        p = _part_of_key(key, self.n_parts)
        local = self._parts[p].get(key)
        return None if local is None else p * self.slots_per_part + local

    def assign(self, key: Hashable,
               pinned: Optional[Set[int]] = None,
               hold_pin: bool = False) -> Tuple[int, Optional[int]]:
        p = _part_of_key(key, self.n_parts)
        base = p * self.slots_per_part
        local, evicted = self._parts[p].assign(
            key, pinned=self._local_pins(pinned, p), hold_pin=hold_pin)
        return base + local, None if evicted is None else base + evicted

    def remove(self, key: Hashable) -> Optional[int]:
        p = _part_of_key(key, self.n_parts)
        local = self._parts[p].remove(key)
        return None if local is None else p * self.slots_per_part + local

    def __len__(self) -> int:
        return sum(len(p) for p in self._parts)

    # -- vectorized interface --------------------------------------------------
    def _scatter_merge(self, n, parts_pos, results, kind, rank_bits=0):
        """Merge per-partition outputs back to request order.

        kind 'slots': results are (slots, ev) -> (slots i32[n], clears).
        kind 'uniques': results are (uwords, uidx, rank, ev) -> global
        (uwords concat with partition slot offsets folded into the slot
        field, uidx i32[n] offset per partition, rank i32[n], clears).
        """
        spp = self.slots_per_part
        if kind == "slots":
            out = np.empty(n, dtype=np.int32)
            clears: list = []
            for p, (pos, res) in enumerate(zip(parts_pos, results)):
                if res is None:
                    continue
                slots, ev = res
                out[pos] = slots + p * spp
                clears.extend(p * spp + int(e) for e in ev)
            return out, clears
        rb = rank_bits
        uw_all, clears = [], []
        uidx = np.empty(n, dtype=np.int32)
        rank = np.empty(n, dtype=np.int32)
        offset = 0
        for p, (pos, res) in enumerate(zip(parts_pos, results)):
            if res is None:
                continue
            uw, ui, rk, ev = res
            # Fold the partition's global slot base into the word's slot
            # field: slot rides in bits rank_bits+1.. so adding
            # base << (rank_bits+1) re-addresses it globally.
            uw_all.append(uw + np.uint32(p * spp << (rb + 1)))
            uidx[pos] = ui + offset
            rank[pos] = rk
            offset += len(uw)
            clears.extend(p * spp + int(e) for e in ev)
        uwords = (np.concatenate(uw_all) if uw_all
                  else np.empty(0, dtype=np.uint32))
        return uwords, uidx, rank, clears

    def _collect(self, futs, unpin_of):
        """Gather per-partition futures; if any partition raised, release
        the pins the SUCCESSFUL partitions took (their results never reach
        the caller, so nothing else could unpin them), surface EVERY
        eviction the batch applied — successful partitions' lists plus the
        failing partitions' partial ones — as global ``pending_clears`` on
        the re-raised error, and re-raise.  Without that, slots the C
        index already remapped to new keys would keep stale device state
        (ADVICE r3)."""
        results, err = [], None
        spp = self.slots_per_part
        clears: list = []
        for p, f in enumerate(futs):
            if f is None:
                results.append(None)
                continue
            try:
                results.append(f.result())
            except Exception as exc:  # noqa: BLE001 — re-raised below
                err = err if err is not None else exc
                clears.extend(consume_pending_clears(exc, p * spp))
                results.append(None)
        if err is not None:
            for p, res in enumerate(results):
                if res is None:
                    continue
                if unpin_of is not None:
                    self._parts[p].unpin_batch(unpin_of(res))
                # Every assign result ends with its eviction list.
                clears.extend(p * spp + int(e) for e in res[-1])
            try:  # keep the original type; just carry the clears
                err.pending_clears = (np.asarray(clears, dtype=np.int64)
                                      if clears else None)
            except AttributeError:  # exotic __slots__ exception: best effort
                pass
            raise err
        return results

    def _parallel(self, key_ids, pinned, run, unpin_of=None):
        """Split a batch by partition, run per-partition C calls on the
        pool (GIL released inside), return (parts_pos, results).
        ``unpin_of(result) -> local slots`` must be given when the run
        holds pins, so a partial failure releases them.  Routing is one
        native pass (hash + stable counting sort) when available, so
        each partition's positions are a contiguous slice of one order
        array instead of T O(n) mask scans."""
        from ratelimiter_tpu.engine.native_index import shard_route

        r = shard_route(key_ids, self.n_parts)
        if r is not None:
            _, order, counts = r
            offs = np.zeros(self.n_parts + 1, dtype=np.int64)
            np.cumsum(counts, out=offs[1:])
            parts_pos = [order[offs[p]:offs[p + 1]]
                         for p in range(self.n_parts)]
        else:
            parts = _part_of_int_keys(key_ids, self.n_parts)
            parts_pos = [np.where(parts == p)[0]
                         for p in range(self.n_parts)]
        futs = []
        for p, pos in enumerate(parts_pos):
            if not len(pos):
                futs.append(None)
                continue
            futs.append(self._pool.submit(
                run, p, pos, self._local_pins(pinned, p)))
        return parts_pos, self._collect(futs, unpin_of)

    def assign_batch_ints(self, keys: np.ndarray, lid: int,
                          pinned: Optional[Set[int]] = None,
                          hold_pins: bool = False):
        keys = np.ascontiguousarray(keys, dtype=np.int64)

        def run(p, pos, pins):
            return self._parts[p].assign_batch_ints(
                keys[pos], lid, pinned=pins, hold_pins=hold_pins)

        parts_pos, results = self._parallel(
            keys, pinned, run,
            unpin_of=(lambda res: res[0]) if hold_pins else None)
        slots, clears = self._scatter_merge(len(keys), parts_pos, results,
                                            "slots")
        return slots, np.asarray(clears, dtype=np.int32)

    def assign_batch_ints_multi(self, keys: np.ndarray, lids: np.ndarray,
                                pinned: Optional[Set[int]] = None,
                                hold_pins: bool = False):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        lids = np.ascontiguousarray(lids, dtype=np.uint64)

        def run(p, pos, pins):
            return self._parts[p].assign_batch_ints_multi(
                keys[pos], lids[pos], pinned=pins, hold_pins=hold_pins)

        parts_pos, results = self._parallel(
            keys, pinned, run,
            unpin_of=(lambda res: res[0]) if hold_pins else None)
        slots, clears = self._scatter_merge(len(keys), parts_pos, results,
                                            "slots")
        return slots, np.asarray(clears, dtype=np.int32)

    def assign_batch_ints_uniques(self, keys: np.ndarray, lid: int,
                                  rank_bits: int,
                                  pinned: Optional[Set[int]] = None,
                                  hold_pins: bool = False):
        keys = np.ascontiguousarray(keys, dtype=np.int64)

        def run(p, pos, pins):
            return self._parts[p].assign_batch_ints_uniques(
                keys[pos], lid, rank_bits, pinned=pins,
                hold_pins=hold_pins)

        parts_pos, results = self._parallel(
            keys, pinned, run,
            unpin_of=(lambda res: (res[0] >> np.uint32(rank_bits + 1)).astype(np.int32)) if hold_pins else None)
        return self._scatter_merge(len(keys), parts_pos, results, "uniques",
                                   rank_bits)

    def assign_batch_ints_multi_uniques(self, keys: np.ndarray,
                                        lids: np.ndarray, rank_bits: int,
                                        pinned: Optional[Set[int]] = None,
                                        hold_pins: bool = False):
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        lids = np.ascontiguousarray(lids, dtype=np.uint64)

        def run(p, pos, pins):
            return self._parts[p].assign_batch_ints_multi_uniques(
                keys[pos], lids[pos], rank_bits, pinned=pins,
                hold_pins=hold_pins)

        parts_pos, results = self._parallel(
            keys, pinned, run,
            unpin_of=(lambda res: (res[0] >> np.uint32(rank_bits + 1)).astype(np.int32)) if hold_pins else None)
        return self._scatter_merge(len(keys), parts_pos, results, "uniques",
                                   rank_bits)

    # Strings: hash the whole window ONCE natively (fingerprints straight
    # off the interned UTF-8 buffers), route by h1 — the exact quantity
    # shard_of_key's string branch computes scalar-side, so both paths
    # agree on a key's partition — and feed each partition its
    # fingerprint slice: the per-partition walks then do zero hashing.
    # Fallback (no native hasher): the r5 per-key Python routing loop.
    def _parallel_strs_fp(self, keys, lid, pinned, run_fp, start, n,
                          unpin_of=None):
        from ratelimiter_tpu.engine.native_index import (
            hash_str_keys,
            route_hashes,
        )

        fp = hash_str_keys(keys, lid, start, n)
        if fp is None:
            return None
        h1, h2 = fp
        part, order, counts = route_hashes(h1, self.n_parts)
        offs = np.zeros(self.n_parts + 1, dtype=np.int64)
        np.cumsum(counts, out=offs[1:])
        h1st, h2st = h1[order], h2[order]
        parts_pos = [order[offs[p]:offs[p + 1]]
                     for p in range(self.n_parts)]
        futs = []
        for p, pos in enumerate(parts_pos):
            if not len(pos):
                futs.append(None)
                continue
            lo, hi = int(offs[p]), int(offs[p + 1])
            futs.append(self._pool.submit(
                run_fp, p, h1st[lo:hi], h2st[lo:hi],
                self._local_pins(pinned, p)))
        return parts_pos, self._collect(futs, unpin_of)

    def _parallel_strs(self, keys, lid, pinned, run, unpin_of=None):
        parts = np.fromiter(
            (_part_of_key((lid, k), self.n_parts) for k in keys),
            dtype=np.int64, count=len(keys))
        parts_pos = [np.where(parts == p)[0] for p in range(self.n_parts)]
        futs = []
        for p, pos in enumerate(parts_pos):
            if not len(pos):
                futs.append(None)
                continue
            futs.append(self._pool.submit(
                run, p, [keys[i] for i in pos], self._local_pins(pinned, p)))
        return parts_pos, self._collect(futs, unpin_of)

    def assign_batch_strs(self, keys, lid: int,
                          pinned: Optional[Set[int]] = None,
                          hold_pins: bool = False,
                          start: int = 0, count: int | None = None):
        n = (len(keys) - start) if count is None else count

        def run_fp(p, h1, h2, pins):
            return self._parts[p].assign_batch_fps(
                h1, h2, pinned=pins, hold_pins=hold_pins)

        unpin = (lambda res: res[0]) if hold_pins else None
        r = self._parallel_strs_fp(keys, lid, pinned, run_fp,
                                   start, n, unpin_of=unpin)
        if r is not None:
            parts_pos, results = r
            slots, clears = self._scatter_merge(
                n, parts_pos, results, "slots")
            return slots, np.asarray(clears, dtype=np.int32)

        sub_keys = keys if (start == 0 and n == len(keys)) else keys[
            start:start + n]

        def run(p, sub, pins):
            return self._parts[p].assign_batch_strs(
                sub, lid, pinned=pins, hold_pins=hold_pins)

        parts_pos, results = self._parallel_strs(
            sub_keys, lid, pinned, run, unpin_of=unpin)
        slots, clears = self._scatter_merge(n, parts_pos, results,
                                            "slots")
        return slots, np.asarray(clears, dtype=np.int32)

    def assign_batch_strs_uniques(self, keys, lid: int, rank_bits: int,
                                  pinned: Optional[Set[int]] = None,
                                  hold_pins: bool = False,
                                  start: int = 0,
                                  count: int | None = None):
        n = (len(keys) - start) if count is None else count
        unpin = (lambda res: (res[0] >> np.uint32(rank_bits + 1))
                 .astype(np.int32)) if hold_pins else None

        def run_fp(p, h1, h2, pins):
            return self._parts[p].assign_batch_fps_uniques(
                h1, h2, rank_bits, pinned=pins, hold_pins=hold_pins)

        if all(hasattr(s, "assign_batch_fps_uniques")
               for s in self._parts):
            r = self._parallel_strs_fp(keys, lid, pinned, run_fp,
                                       start, n, unpin_of=unpin)
            if r is not None:
                parts_pos, results = r
                return self._scatter_merge(n, parts_pos, results,
                                           "uniques", rank_bits)

        sub_keys = keys if (start == 0 and n == len(keys)) else keys[
            start:start + n]

        def run(p, sub, pins):
            return self._parts[p].assign_batch_strs_uniques(
                sub, lid, rank_bits, pinned=pins, hold_pins=hold_pins)

        parts_pos, results = self._parallel_strs(
            sub_keys, lid, pinned, run, unpin_of=unpin)
        return self._scatter_merge(n, parts_pos, results, "uniques",
                                   rank_bits)

    # -- fingerprint enumeration (checkpoint/restore) --------------------------
    def dump_fp(self):
        """Per-partition (h1, h2, local slots) stacked with partition slot
        bases folded in; concatenation order is partition-major so
        restore_fp can split it back exactly."""
        h1s, h2s, slots = [], [], []
        for p, part in enumerate(self._parts):
            h1, h2, sl = part.dump_fp()
            h1s.append(h1)
            h2s.append(h2)
            slots.append(sl + np.int32(p * self.slots_per_part))
        return (np.concatenate(h1s) if h1s else np.empty(0, np.uint64),
                np.concatenate(h2s) if h2s else np.empty(0, np.uint64),
                np.concatenate(slots) if slots else np.empty(0, np.int32))

    def pin_batch(self, slots) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        part = slots // self.slots_per_part
        for q, sub in enumerate(self._parts):
            m = part == q
            if m.any():
                sub.pin_batch(slots[m] - np.int32(q * self.slots_per_part))

    def unpin_batch(self, slots) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        part = slots // self.slots_per_part
        for q, sub in enumerate(self._parts):
            m = part == q
            if m.any():
                sub.unpin_batch(slots[m] - np.int32(q * self.slots_per_part))

    # NOTE: no restore_fp here on purpose — fingerprints don't carry their
    # key's partition routing, so only the checkpoint path (which stores
    # per-partition payloads) can restore; a flat fingerprint dump is
    # rejected at the checkpoint layer (engine/checkpoint.py).

    def lookup_fps(self, h1: np.ndarray, h2: np.ndarray) -> np.ndarray:
        # Fingerprints don't carry the partition; probe every partition
        # (restore/rebalance path only — not on the hot path).
        out = np.full(len(h1), -1, dtype=np.int32)
        for p, sub in enumerate(self._parts):
            local = sub.lookup_fps(h1, h2)
            hit = (out == -1) & (local >= 0)
            out[hit] = local[hit] + p * self.slots_per_part
        return out
