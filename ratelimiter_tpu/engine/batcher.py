"""Micro-batcher: coalesces concurrent tryAcquire calls into device batches.

The reference's unit of concurrency is a servlet thread blocking on a Redis
round-trip (~800 us, ARCHITECTURE.md latency model); ours is a Future that
resolves when its device batch's results land.  Threads submit requests; a
dedicated flusher thread dispatches a batch when either

- the pending batch reaches the size trigger (``max_batch``, or the
  adaptive controller's applied trigger), or
- the oldest pending request has waited the flush deadline
  (``max_delay_ms``, or the controller's applied deadline — SURVEY.md §7
  "Batching latency vs p99"),

whichever comes first.  With an ``AdaptiveFlushController`` attached
(engine/flush_control.py), both bounds track the measured device-step
time, hard-clamped within the configured ones.

**Double-buffered assembly (r11).**  Requests are packed at submit time
into a preallocated combined staging buffer (``_Pending``), so batch
N+1's host assembly happens on the submitters' threads while batch N is
in flight; a flush swaps the active buffer for a recycled standby and —
with a ``dispatch_staged`` callback — dispatch collapses to one device
upload plus a cached jit call.  This is the same overlap structure the
stream path's prefetch pipeline uses (ARCHITECTURE §6b), applied to the
interactive micro path.

**Pipelined dispatch/drain.**  Dispatching a batch (enqueue on device,
state advanced) and draining it (the blocking device->host fetch that
resolves the waiters' futures) are decoupled: the flusher only dispatches;
a pool of drain threads fetches.  Up to ``max_inflight`` batches ride the
wire at once — the fetches themselves overlap each other, not just the
next dispatch, which matters on a high-latency link (the tunneled
device's ~110 ms fetch is round-trip latency, not occupancy): throughput
goes from one batch per round trip to one batch per flush interval.
Correctness does not depend on drain order: dispatches are serialized
(single flusher + the dispatch lock), so device state advances in
submission order; each drain only reads its own batch's output buffer.

Eviction-clears stay safe for the same reason: cleared slots are zeroed in
the dispatch stream ahead of the batch that reuses them.

**Admission control & overload protection.**  ``submit`` used to accept
unbounded work and strand waiters if the flusher died.  Now:

- ``max_pending`` bounds each algo's pending queue; a submit over the
  bound is shed with a typed ``OverloadedError`` (reason ``queue_full``)
  instead of queuing forever.
- ``deadline_ms`` gives each request a *queue* budget: a request that
  cannot be dispatched within its deadline (e.g. a 90 s compile or a
  hung device holds the dispatch lock) is failed with ``OverloadedError``
  (reason ``deadline``) at take time or by the watchdog.  The budget
  covers queue wait only — once dispatched, a batch's drain latency is
  the device's business (first-compile stalls must not shed).
- a watchdog thread expires queued deadlines even while the flusher is
  wedged inside a dispatch, and detects a dead flusher (failing everything
  queued rather than hanging callers).
- ``close()`` fails every still-pending future with a typed
  ``ShutdownError`` after a bounded wait — a caller blocked on
  ``Future.result()`` is never stranded by shutdown.
"""

from __future__ import annotations

import math
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Set

import numpy as np

from ratelimiter_tpu.engine.errors import OverloadedError, ShutdownError
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("engine.batcher")

#: Initial staging-buffer lane count (the _MICRO_FLOOR bucket); buffers
#: grow by doubling so every capacity is a valid dispatch bucket.
_STAGE_CAP = 32


class _Pending:
    """One algo's pending queue, double-buffered (r11).

    Requests are packed **at submit time** into a preallocated combined
    i64[4, cap] staging buffer (row 0 slots / 1 lids / 2 permits / 3 the
    batch timestamp lane — engine/engine.py:MICRO_STAGE_ROWS), so batch
    N+1's assembly happens on the submitters' threads while batch N is in
    flight, and flush-time "assembly" collapses to one device upload.
    Padding lanes carry their fill values permanently: a take hands the
    staged buffer to the dispatch as-is, and recycling re-fills only the
    lanes a batch actually used.  The per-request Python lists that
    remain (futures/deadlines/t_sub) are host-resolution bookkeeping the
    device never sees.
    """

    __slots__ = ("buf", "n", "futures", "deadlines", "t_sub", "traces",
                 "clears", "born")

    #: Parallel per-request lists that shed/forget filtering must keep
    #: in lockstep with the staging-buffer lanes.
    LISTS = ("futures", "deadlines", "t_sub", "traces")

    def __init__(self, cap: int = _STAGE_CAP):
        self.buf = np.empty((4, cap), dtype=np.int64)
        self.buf[0] = -1  # slots   (pad: masked lane)
        self.buf[1] = 0   # lids
        self.buf[2] = 1   # permits
        self.buf[3, 0] = 0  # batch timestamp (stamped at dispatch)
        self.n = 0
        self.futures: List[Future] = []
        self.deadlines: List[float] = []  # monotonic queue deadlines (inf=none)
        self.t_sub: List[float] = []      # perf_counter at submit (tracing)
        self.traces: List[int] = []       # 64-bit trace ids (0 = untraced)
        self.clears: List[int] = []
        self.born: float | None = None  # monotonic time of oldest request

    @property
    def cap(self) -> int:
        return self.buf.shape[1]

    def append(self, slot: int, lid: int, permits: int) -> None:
        i = self.n
        if i == self.cap:
            self._grow(self.cap * 2)
        self.buf[0, i] = slot
        self.buf[1, i] = lid
        self.buf[2, i] = permits
        self.n = i + 1

    def extend(self, slots, lids, permits) -> None:
        i, n = self.n, len(slots)
        need = i + n
        if need > self.cap:
            grown = self.cap * 2
            while grown < need:
                grown *= 2
            self._grow(grown)
        self.buf[0, i:need] = slots
        self.buf[1, i:need] = lids
        self.buf[2, i:need] = permits
        self.n = need

    def _grow(self, cap: int) -> None:
        new = np.empty((4, cap), dtype=np.int64)
        new[0] = -1
        new[1] = 0
        new[2] = 1
        new[:, : self.n] = self.buf[:, : self.n]
        self.buf = new

    def slot_list(self) -> List[int]:
        return self.buf[0, : self.n].tolist()

    def compact(self, keep: List[int]) -> None:
        """Keep only the requests at the given indices (shed/forget),
        restoring padding fills behind the new tail."""
        k = len(keep)
        if k:
            idx = np.asarray(keep, dtype=np.int64)
            for row, _fill in ((0, -1), (1, 0), (2, 1)):
                self.buf[row, :k] = self.buf[row, idx]
        self.buf[0, k: self.n] = -1
        self.buf[1, k: self.n] = 0
        self.buf[2, k: self.n] = 1
        self.n = k
        for name in self.LISTS:
            vals = getattr(self, name)
            setattr(self, name, [vals[i] for i in keep])

    def recycle(self) -> None:
        """Reset for reuse as the next standby buffer.  New list objects:
        the drain pipeline still holds the dispatched batch's futures."""
        self.buf[0, : self.n] = -1
        self.buf[1, : self.n] = 0
        self.buf[2, : self.n] = 1
        self.n = 0
        self.futures = []
        self.deadlines = []
        self.t_sub = []
        self.traces = []
        self.clears = []
        self.born = None


class MicroBatcher:
    """One batching queue per algorithm kind ('sw' | 'tb')."""

    def __init__(
        self,
        dispatch: Dict[str, Callable],      # algo -> fn(slots, lids, permits) -> handle
        clear: Dict[str, Callable],         # algo -> fn(slots) -> None
        drain: Dict[str, Callable] | None = None,  # algo -> fn(handle, n) -> dict
        dispatch_staged: Dict[str, Callable] | None = None,
        max_batch: int = 8192,
        max_delay_ms: float = 0.5,
        max_inflight: int = 4,
        max_pending: int = 0,
        deadline_ms: float = 0.0,
        controller=None,
        meter_registry=None,
        tracer=None,
        recorder=None,
    ):
        self._dispatch = dispatch
        # Staged fast path (r11): algo -> fn(staged_buf, n) -> handle.
        # The flusher hands queued batches over as the pre-packed
        # combined staging buffer (see _Pending) instead of three Python
        # lists; callers without one (tests, simple backends,
        # dispatch_direct) keep the list contract.
        self._dispatch_staged = dispatch_staged or {}
        # Adaptive flush control (engine/flush_control.py): when present,
        # the flusher reads its applied deadline/size trigger each cycle
        # and the drain feeds it the measured device-step time.
        self._controller = controller
        # Without a drain fn the dispatch result IS the output dict
        # (synchronous mode — tests and simple backends).
        self._drain = drain or {}
        # Request-lifecycle tracing (observability/trace.py): stages are
        # stamped regardless (one perf_counter per submit) and observed
        # only when a tracer is attached.  The flight recorder gets one
        # coalesced event per shed burst (not per shed request).
        self._tracer = tracer
        self._recorder = recorder
        self._clear = clear
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_inflight = max(int(max_inflight), 1)
        # Admission control (0 disables either bound — the library default;
        # service wiring turns both on via ratelimiter.overload.* props).
        self.max_pending = int(max_pending)
        self.deadline_ms = float(deadline_ms)
        self.shed_total = 0           # queue-full sheds (submit refused)
        self.deadline_total = 0       # queued requests expired pre-dispatch
        self.max_depth_seen = 0       # high-water mark of any algo queue
        self.last_shed_s = 0.0        # monotonic stamp of the last shed
        self.abandoned_total = 0      # queued requests withdrawn via forget()
        self._shed_counter = (
            meter_registry.counter(
                "ratelimiter.overload.shed",
                "Requests shed at submit: pending queue at max_pending")
            if meter_registry is not None else None)
        self._deadline_counter = (
            meter_registry.counter(
                "ratelimiter.overload.deadline_exceeded",
                "Queued requests failed: not dispatched within deadline_ms")
            if meter_registry is not None else None)
        self._depth_gauge = (
            meter_registry.gauge(
                "ratelimiter.overload.queue_depth",
                "Pending micro-batch queue depth (largest algo queue)")
            if meter_registry is not None else None)
        self._cv = threading.Condition()
        self._pending: Dict[str, _Pending] = {a: _Pending() for a in dispatch}
        # Recycled standby staging buffers (the other half of the double
        # buffer): _take swaps one in, the flusher returns the dispatched
        # one once its upload completed.  Oversized buffers from a burst
        # are dropped instead of pooled.
        self._spare: Dict[str, List[_Pending]] = {a: [] for a in dispatch}
        self._spare_cap_max = max(2 * self.max_batch, 4 * _STAGE_CAP)
        self._waiters: Set[Future] = set()  # every unresolved submit future
        self._dispatch_lock = threading.Lock()  # serializes device batches
        self._closed = False
        self._flusher_dead = False
        # Concurrent fetches: one worker per in-flight batch; the semaphore
        # is the backpressure bound on the device queue.
        self._drain_pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="ratelimiter-drain")
        self._inflight_sem = threading.Semaphore(self.max_inflight)
        self._flusher = threading.Thread(
            target=self._run, name="ratelimiter-flusher", daemon=True)
        self._flusher.start()
        # Watchdog: expires queued deadlines even while the flusher is
        # wedged inside a dispatch, and fails the queue if the flusher
        # dies.  Cheap (one lock + O(pending) scan per tick).
        self._watch_stop = threading.Event()
        self._watch_interval = (
            max(0.005, min(0.05, self.deadline_ms / 4000.0))
            if self.deadline_ms > 0 else 0.05)
        self._watchdog = threading.Thread(
            target=self._watch, name="ratelimiter-watchdog", daemon=True)
        self._watchdog.start()

    # -- submission -----------------------------------------------------------
    def submit(self, algo: str, slot: int, lid: int, permits: int,
               deadline_ms: float | None = None,
               trace_id: int = 0) -> Future:
        """Queue one decision; returns its Future.

        ``deadline_ms`` overrides the batcher-wide queue-deadline budget
        for this request (None = default; 0 = no deadline).
        ``trace_id`` is an optional 64-bit trace id carried to the drain
        (observability/telemetry.py lineage).  Raises
        ``OverloadedError`` when the pending queue is at ``max_pending``
        or the flusher has died, ``ShutdownError`` when closed.
        """
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise ShutdownError("batcher closed")
            if self._flusher_dead:
                raise OverloadedError(
                    "flusher thread died; nothing will dispatch this queue",
                    reason="flusher_dead", retry_after_ms=1000.0)
            pend = self._pending[algo]
            self._check_admission(pend, 1)
            if pend.born is None:
                pend.born = time.monotonic()
            budget = self.deadline_ms if deadline_ms is None else deadline_ms
            pend.append(slot, lid, permits)
            pend.futures.append(fut)
            pend.deadlines.append(
                time.monotonic() + budget / 1000.0 if budget and budget > 0
                else math.inf)
            pend.t_sub.append(time.perf_counter())
            pend.traces.append(int(trace_id))
            if pend.n > self.max_depth_seen:
                self.max_depth_seen = pend.n
            self._waiters.add(fut)
            self._cv.notify()
        return fut

    def _check_admission(self, pend: _Pending, incoming: int) -> None:
        """Queue-full shed check (cv held)."""
        if not self.max_pending or pend.n + incoming <= self.max_pending:
            return
        self.shed_total += incoming
        self.last_shed_s = time.monotonic()
        if self._shed_counter is not None:
            self._shed_counter.add(incoming)
        if self._recorder is not None:
            self._recorder.record(
                "overload.shed", coalesce_ms=1000.0,
                reason="queue_full", depth=pend.n)
        # The queue drains one max_batch per dispatch cycle; a rough
        # cycle estimate keeps the hint cheap and honest.
        cycles = max(pend.n / max(self.max_batch, 1), 1.0)
        raise OverloadedError(
            f"pending queue full ({pend.n} >= {self.max_pending})",
            reason="queue_full",
            retry_after_ms=cycles * max(self.max_delay_s * 1000.0, 1.0))

    def submit_many(self, algo: str, slots, lids, permits,
                    deadline_ms: float | None = None,
                    trace_ids=None) -> List[Future]:
        """Bulk :meth:`submit` for a pipelined burst whose slots were
        assigned in one batched index call (storage.acquire_async_many):
        one cv acquisition and three vectorized staging-buffer writes
        instead of a Python round trip per request.  All-or-nothing
        admission: a burst that would cross ``max_pending`` is shed
        whole."""
        n = len(slots)
        futs = [Future() for _ in range(n)]
        with self._cv:
            if self._closed:
                raise ShutdownError("batcher closed")
            if self._flusher_dead:
                raise OverloadedError(
                    "flusher thread died; nothing will dispatch this queue",
                    reason="flusher_dead", retry_after_ms=1000.0)
            pend = self._pending[algo]
            self._check_admission(pend, n)
            if pend.born is None:
                pend.born = time.monotonic()
            budget = self.deadline_ms if deadline_ms is None else deadline_ms
            deadline = (time.monotonic() + budget / 1000.0
                        if budget and budget > 0 else math.inf)
            pend.extend(slots, lids, permits)
            pend.futures.extend(futs)
            pend.deadlines.extend([deadline] * n)
            pend.t_sub.extend([time.perf_counter()] * n)
            pend.traces.extend([int(t) for t in trace_ids] if trace_ids
                               else [0] * n)
            if pend.n > self.max_depth_seen:
                self.max_depth_seen = pend.n
            self._waiters.update(futs)
            self._cv.notify()
        return futs

    def submit_block(self, algo: str, slots, lids, permits,
                     deadline_ms: float | None = None,
                     trace_id: int = 0) -> Future:
        """One future for a whole columnar burst (the sidecar's v5 batch
        frame): the n requests stage exactly like :meth:`submit_many` —
        contiguous lanes, all-or-nothing admission, one shared deadline —
        but resolve through a SINGLE future whose result maps each output
        key to its lanes' array slice ({"allowed": bool[n], ...}), so a
        thousand-row frame costs one Future and one set_result instead of
        a thousand.  The future object rides every one of its lanes in
        the parallel staging lists (tagged ``_lanes = n``), which keeps
        compaction, forget(), deadline expiry, and close() positional:
        the shared deadline makes expiry all-or-nothing, forget() drops
        every lane at once, and repeated _fail/cancel calls are no-ops
        after the first."""
        n = len(slots)
        fut = Future()
        fut._lanes = n
        if n == 0:
            fut.set_result({})
            return fut
        with self._cv:
            if self._closed:
                raise ShutdownError("batcher closed")
            if self._flusher_dead:
                raise OverloadedError(
                    "flusher thread died; nothing will dispatch this queue",
                    reason="flusher_dead", retry_after_ms=1000.0)
            pend = self._pending[algo]
            self._check_admission(pend, n)
            if pend.born is None:
                pend.born = time.monotonic()
            budget = self.deadline_ms if deadline_ms is None else deadline_ms
            deadline = (time.monotonic() + budget / 1000.0
                        if budget and budget > 0 else math.inf)
            pend.extend(slots, lids, permits)
            pend.futures.extend([fut] * n)
            pend.deadlines.extend([deadline] * n)
            pend.t_sub.extend([time.perf_counter()] * n)
            pend.traces.extend([int(trace_id)] * n)
            if pend.n > self.max_depth_seen:
                self.max_depth_seen = pend.n
            self._waiters.add(fut)
            self._cv.notify()
        return fut

    def queue_depth(self) -> int:
        """Largest per-algo pending queue (the admission-control bound's
        operand), for health reporting."""
        with self._cv:
            return max((p.n for p in self._pending.values()), default=0)

    def add_clear(self, algo: str, slot: int) -> None:
        """Schedule a slot zeroing ahead of the next batch (eviction)."""
        with self._cv:
            pend = self._pending[algo]
            if pend.born is None:
                pend.born = time.monotonic()
            pend.clears.append(slot)
            self._cv.notify()

    def pending_slots(self, algo: str) -> Set[int]:
        """Slots referenced by queued requests (pin set for eviction)."""
        with self._cv:
            return set(self._pending[algo].slot_list())

    def pending_slots_sharded(self, algo: str,
                              slots_per_shard: int) -> Dict[int, Set[int]]:
        """Queued-request slots as ``{shard: {local slot}}`` — the pin
        sets the per-shard stream pipelines hand each lane, computed in
        one pass under the cv instead of a global set re-split per
        shard per chunk."""
        out: Dict[int, Set[int]] = {}
        with self._cv:
            for g in self._pending[algo].slot_list():
                out.setdefault(g // slots_per_shard,
                               set()).add(g % slots_per_shard)
        return out

    def forget(self, futures) -> int:
        """Withdraw still-QUEUED requests whose futures the caller has
        abandoned (e.g. a sidecar connection died mid-burst): they are
        removed from the pending queue and cancelled, so a dead client's
        frames stop consuming device capacity and their slots stop
        pinning eviction.  Requests already dispatched are untouched —
        their futures resolve normally (the caller must still consume
        those).  Returns the number withdrawn."""
        targets = set(futures)
        removed: List[Future] = []
        with self._cv:
            for pend in self._pending.values():
                if not pend.futures or targets.isdisjoint(pend.futures):
                    continue
                keep = [i for i, f in enumerate(pend.futures)
                        if f not in targets]
                removed.extend(f for f in pend.futures if f in targets)
                pend.compact(keep)
                if not pend.n and not pend.clears:
                    # An empty queue must not keep waking the flusher.
                    pend.born = None
            for fut in removed:
                self._waiters.discard(fut)
        for fut in removed:
            fut.cancel()
        self.abandoned_total += len(removed)
        return len(removed)

    # -- flushing -------------------------------------------------------------
    def _take(self, algo: str) -> _Pending | None:
        """Swap the active staging buffer out (cv held): the taken batch
        is already packed; the standby buffer (recycled from a previous
        dispatch when one is available) starts filling immediately."""
        pend = self._pending[algo]
        if not pend.n and not pend.clears:
            return None
        spare = self._spare[algo]
        self._pending[algo] = spare.pop() if spare else _Pending()
        return pend

    def _recycle(self, algo: str, pend: _Pending) -> None:
        """Return a dispatched batch's staging buffer to the standby pool
        (its device upload has completed — the dispatch call copies)."""
        if pend.cap > self._spare_cap_max:
            return  # burst-grown buffer: let it go instead of pinning RAM
        pend.recycle()
        with self._cv:
            spare = self._spare.get(algo)
            if spare is not None and len(spare) < 2:
                spare.append(pend)

    def flush(self) -> None:
        """Dispatch everything pending (admin/reset/shutdown and read
        barriers).  Returns once the batches are in the device stream —
        later reads observe them (dispatch order == device order); the
        waiters' futures resolve asynchronously via the drainer."""
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        self._execute(taken)

    def _finish(self, futures: List[Future]) -> None:
        """Drop resolved futures from the stranding-watch set."""
        with self._cv:
            for fut in futures:
                self._waiters.discard(fut)

    def _fail(self, fut: Future, exc: Exception) -> None:
        if not fut.done():
            fut.set_exception(exc)
        with self._cv:
            self._waiters.discard(fut)

    def _resolve(self, algo: str, handle, futures: List[Future],
                 stamps=None, pend: "_Pending | None" = None) -> None:
        """Fetch a dispatched batch's results and resolve its futures.

        ``stamps`` is the lifecycle-tracing tuple ``(t_sub_list, t_take,
        t_disp)``; the drain adds the device-done and resolved stamps
        and hands the batch to the tracer AFTER every waiter resolved
        (observability stays off the caller's critical path)."""
        out = None
        try:
            drain = self._drain.get(algo)
            out = drain(handle, len(futures)) if drain else handle
            t_dev = time.perf_counter()
            if self._controller is not None and stamps is not None:
                # Adaptive flush feedback: the measured device stage
                # (dispatch enqueued -> results fetched) for this batch.
                self._controller.observe(t_dev - stamps[2], len(futures))
            i, nf = 0, len(futures)
            while i < nf:
                fut = futures[i]
                # submit_block rides one future across its lanes; such a
                # future resolves ONCE, to the lanes' array slices.
                lanes = getattr(fut, "_lanes", 1)
                j = min(i + lanes, nf)
                if not fut.done():  # close() may have failed it already
                    if lanes == 1:
                        fut.set_result({k: v[i] for k, v in out.items()})
                    else:
                        fut.set_result({k: np.asarray(v[i:j])
                                        for k, v in out.items()})
                i = j
        except Exception as exc:  # noqa: BLE001 — fail every waiter
            for fut in futures:
                if not fut.done():
                    fut.set_exception(exc)
        else:
            if self._tracer is not None and stamps is not None:
                t_subs, t_take, t_disp = stamps[:3]
                traces = stamps[3] if len(stamps) > 3 else None
                try:
                    self._tracer.observe_batch(
                        algo, out, t_subs, t_take, t_disp, t_dev,
                        time.perf_counter(), trace_ids=traces)
                except Exception:  # noqa: BLE001 — tracing must not fail waiters
                    log.exception("latency tracer failed (ignored)")
        finally:
            self._finish(futures)
            if pend is not None:
                # The fetch completed, so the device is done reading the
                # staged buffer (the jit call may alias the host numpy
                # memory zero-copy — recycling any earlier would corrupt
                # an in-flight batch).
                self._recycle(algo, pend)

    def _enqueue_drain(self, algo: str, handle, futures: List[Future],
                       stamps=None, pend: "_Pending | None" = None) -> None:
        self._inflight_sem.acquire()  # backpressure on the device queue

        def job():
            try:
                self._resolve(algo, handle, futures, stamps, pend)
            finally:
                self._inflight_sem.release()

        try:
            self._drain_pool.submit(job)
        except RuntimeError:  # pool shut down mid-close: resolve inline
            job()

    def _execute(self, taken) -> None:
        with self._dispatch_lock:
            self._execute_locked(taken)

    def _shed_expired(self, pend: _Pending, now: float,
                      in_queue: bool = False) -> None:
        """Fail requests whose queue deadline passed before dispatch.

        Mutates ``pend`` in place (both taken batches and — under the cv,
        from the watchdog — the live queues).  The deadline budget covers
        queue wait only; a dispatched batch is never expired.
        """
        if not pend.futures or all(d > now for d in pend.deadlines):
            return
        keep = [i for i, d in enumerate(pend.deadlines) if d > now]
        expired = [f for f, d in zip(pend.futures, pend.deadlines)
                   if d <= now]
        n = len(expired)
        self.deadline_total += n
        self.last_shed_s = now
        if self._deadline_counter is not None:
            self._deadline_counter.add(n)
        if self._recorder is not None:
            self._recorder.record("overload.shed", coalesce_ms=1000.0,
                                  reason="deadline", count=n)
        log.warning("shed %d queued request(s): queue deadline exceeded "
                    "before dispatch%s", n,
                    " (watchdog)" if in_queue else "")
        pend.compact(keep)
        exc = OverloadedError(
            "queue deadline exceeded before dispatch", reason="deadline",
            retry_after_ms=max(self.max_delay_s * 1000.0, 1.0))
        for fut in expired:
            self._fail(fut, exc)

    def _execute_locked(self, taken) -> None:
        for algo, pend in taken.items():
            if pend is None:
                continue
            self._shed_expired(pend, time.monotonic())
            t_take = time.perf_counter()  # assembly starts (tracing)
            staged_fn = self._dispatch_staged.get(algo)
            try:
                if pend.clears:
                    self._clear[algo](pend.clears)
                if pend.n:
                    log.debug("dispatch algo=%s batch=%d clears=%d",
                              algo, pend.n, len(pend.clears))
                    if staged_fn is not None:
                        # Staged fast path: the batch was packed at
                        # submit time; hand the combined buffer over
                        # whole (one upload inside).
                        handle = staged_fn(pend.buf, pend.n)
                    else:
                        handle = self._dispatch[algo](
                            pend.buf[0, :pend.n].tolist(),
                            pend.buf[1, :pend.n].tolist(),
                            pend.buf[2, :pend.n].tolist())
                    futures = pend.futures
                    stamps = (pend.t_sub, t_take, time.perf_counter(),
                              pend.traces)
                    # The staging buffer recycles at DRAIN time (the jit
                    # call may alias the host numpy memory zero-copy —
                    # it is free only once the results were fetched).
                    # With no other batch in flight, the drain-pool
                    # handoff (task queue + worker wake) is pure added
                    # latency — the fetch releases the GIL anyway, and
                    # in a request-response loop the next submissions
                    # only arrive AFTER these futures resolve.  Resolve
                    # inline; pipelined load keeps the pool.
                    recycled = pend if staged_fn is not None else None
                    if (staged_fn is not None
                            and self._inflight_sem._value
                            >= self.max_inflight
                            and self._inflight_sem.acquire(blocking=False)):
                        try:
                            self._resolve(algo, handle, futures, stamps,
                                          recycled)
                        finally:
                            self._inflight_sem.release()
                    else:
                        self._enqueue_drain(algo, handle, futures, stamps,
                                            recycled)
                elif staged_fn is not None:
                    self._recycle(algo, pend)
            except Exception as exc:  # noqa: BLE001 — fail every waiter
                log.warning("dispatch failed algo=%s batch=%d: %s",
                            algo, pend.n, exc)
                for fut in pend.futures:
                    if not fut.done():
                        fut.set_exception(exc)
                self._finish(pend.futures)

    def dispatch_direct(self, algo: str, slots, lids, permits, clears=None):
        """Synchronous whole-batch dispatch (the vectorized/bench path).

        Flushes everything pending first, then runs this batch under the
        same dispatch lock — so direct batches serialize with queued
        traffic and see a consistent state stream.  The direct batch's own
        fetch happens inline (its results are independent of the queued
        batches' fetches, which continue to drain in the background).
        """
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        with self._dispatch_lock:
            self._execute_locked(taken)
            if clears:
                self._clear[algo](clears)
            handle = self._dispatch[algo](slots, lids, permits)
        drain = self._drain.get(algo)
        return drain(handle, len(slots)) if drain else handle

    def _watch(self) -> None:
        """Overload watchdog: queue-deadline expiry that does not depend on
        the flusher being schedulable (it may be wedged inside a 90 s
        compile holding the dispatch lock), plus dead-flusher detection so
        queued callers fail instead of blocking forever."""
        while not self._watch_stop.wait(self._watch_interval):
            with self._cv:
                if self._closed:
                    return
                now = time.monotonic()
                for pend in self._pending.values():
                    self._shed_expired(pend, now, in_queue=True)
                if self._depth_gauge is not None:
                    self._depth_gauge.set(max(
                        (p.n for p in self._pending.values()), default=0))
                if not self._flusher_dead and not self._flusher.is_alive():
                    self._flusher_dead = True
                if self._flusher_dead:
                    taken = {a: self._take(a) for a in self._pending}
                else:
                    continue
            self._fail_taken(taken, OverloadedError(
                "flusher thread died; request abandoned",
                reason="flusher_dead", retry_after_ms=1000.0))

    def _fail_taken(self, taken, exc: Exception) -> None:
        for pend in taken.values():
            if pend is None:
                continue
            for fut in pend.futures:
                self._fail(fut, exc)

    def _run(self) -> None:
        try:
            self._run_loop()
        except Exception:  # noqa: BLE001 — flusher must never die silently
            log.exception("flusher died; failing all queued requests")
            with self._cv:
                self._flusher_dead = True
                taken = {a: self._take(a) for a in self._pending}
            self._fail_taken(taken, OverloadedError(
                "flusher thread died; request abandoned",
                reason="flusher_dead", retry_after_ms=1000.0))

    def _run_loop(self) -> None:
        while True:
            locked = False
            with self._cv:
                while not self._closed:
                    now = time.monotonic()
                    ready, wait = [], None
                    # Adaptive flush (engine/flush_control.py): the
                    # controller's applied deadline/size trigger replace
                    # the static bounds, re-read every cycle; both are
                    # clamped so they never exceed the configured ones.
                    # Pacing the flush against the device-step time only
                    # pays while the device pipeline is OCCUPIED (a
                    # flush faster than the service rate just queues at
                    # the dispatch lock); with every in-flight slot free
                    # the wait is pure added latency, so an idle device
                    # flushes at the controller's floor.
                    if self._controller is not None:
                        idle = (self._inflight_sem._value
                                >= self.max_inflight)
                        delay_s = min(self._controller.floor_s if idle
                                      else self._controller.delay_s(),
                                      self.max_delay_s)
                        trigger = min(self._controller.size_trigger(),
                                      self.max_batch)
                    else:
                        delay_s, trigger = self.max_delay_s, self.max_batch
                    for algo, pend in self._pending.items():
                        if pend.born is None:
                            continue
                        age = now - pend.born
                        if pend.n >= trigger or age >= delay_s:
                            ready.append(algo)
                        else:
                            remaining = delay_s - age
                            wait = remaining if wait is None else min(wait, remaining)
                    if ready:
                        # Deadline hit — but if a dispatch is mid-flight,
                        # do NOT freeze the batch yet: a batch taken now
                        # would sit waiting for the lock while new
                        # arrivals start a fresh queue and pay a whole
                        # extra dispatch cycle (the convoy behind the r4
                        # SLO miss's ~1.6 ms of batcher-owned latency).
                        # Keep accumulating and re-check shortly; the
                        # take happens with the lock ALREADY HELD, so
                        # the batch carries everything that arrived
                        # during the previous step.
                        if self._dispatch_lock.acquire(blocking=False):
                            locked = True
                            break
                        # Floored: with max_delay_ms=0 an unfloored wait
                        # would spin the cv at full speed for as long as
                        # the in-flight dispatch holds the lock.
                        self._cv.wait(timeout=max(
                            min(self.max_delay_s, 3e-4), 5e-5))
                        continue
                    self._cv.wait(timeout=wait)
                if self._closed and not any(
                    p.born is not None for p in self._pending.values()
                ):
                    if locked:
                        self._dispatch_lock.release()
                    return
                taken = {a: self._take(a) for a in self._pending}
            try:
                if locked:
                    self._execute_locked(taken)
                else:  # close() drained the cv loop: plain locked path
                    self._execute(taken)
            finally:
                if locked:
                    self._dispatch_lock.release()

    def close(self, timeout: float = 5.0) -> None:
        """Shut down; never strands a waiter.

        The healthy path dispatches whatever is queued and waits for the
        in-flight drains.  Every path that can hang is bounded: a stuck
        dispatch (lock never acquired), a dead flusher, or a hung drain
        all end with the remaining futures failed by a typed
        ``ShutdownError`` after ``timeout`` — a caller blocked on
        ``Future.result()`` always gets an answer.
        """
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._watch_stop.set()
        self._flusher.join(timeout=timeout)
        # Dispatch the remaining queue — but never hang on a wedged
        # dispatch: if the lock cannot be had, the queued futures are
        # failed below instead of dispatched.
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        if any(p is not None for p in taken.values()):
            if self._dispatch_lock.acquire(timeout=max(timeout, 0.1)):
                try:
                    self._execute_locked(taken)
                finally:
                    self._dispatch_lock.release()
            else:
                self._fail_taken(taken, ShutdownError(
                    "batcher closed before the batch could be dispatched"))
        # Resolve whatever is on the wire, bounded by the same timeout.
        self._drain_pool.shutdown(wait=False)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            with self._cv:
                if not self._waiters:
                    break
            time.sleep(0.005)
        with self._cv:
            stranded = [f for f in self._waiters if not f.done()]
            self._waiters.clear()
        if stranded:
            log.warning("close(): failing %d stranded future(s)",
                        len(stranded))
            exc = ShutdownError("batcher closed; request abandoned")
            for fut in stranded:
                if not fut.done():
                    fut.set_exception(exc)
