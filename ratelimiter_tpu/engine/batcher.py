"""Micro-batcher: coalesces concurrent tryAcquire calls into device batches.

The reference's unit of concurrency is a servlet thread blocking on a Redis
round-trip (~800 us, ARCHITECTURE.md latency model); ours is a Future that
resolves when its device batch's results land.  Threads submit requests; a
dedicated flusher thread dispatches a batch when either

- the pending batch reaches ``max_batch``, or
- the oldest pending request has waited ``max_delay_ms`` (adaptive flush:
  size OR deadline — SURVEY.md §7 "Batching latency vs p99"),

whichever comes first.

**Pipelined dispatch/drain.**  Dispatching a batch (enqueue on device,
state advanced) and draining it (the blocking device->host fetch that
resolves the waiters' futures) are decoupled: the flusher only dispatches;
a pool of drain threads fetches.  Up to ``max_inflight`` batches ride the
wire at once — the fetches themselves overlap each other, not just the
next dispatch, which matters on a high-latency link (the tunneled
device's ~110 ms fetch is round-trip latency, not occupancy): throughput
goes from one batch per round trip to one batch per flush interval.
Correctness does not depend on drain order: dispatches are serialized
(single flusher + the dispatch lock), so device state advances in
submission order; each drain only reads its own batch's output buffer.

Eviction-clears stay safe for the same reason: cleared slots are zeroed in
the dispatch stream ahead of the batch that reuses them.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, List, Set

from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("engine.batcher")


class _Pending:
    __slots__ = ("slots", "lids", "permits", "futures", "clears", "born")

    def __init__(self):
        self.slots: List[int] = []
        self.lids: List[int] = []
        self.permits: List[int] = []
        self.futures: List[Future] = []
        self.clears: List[int] = []
        self.born: float | None = None  # monotonic time of oldest request


class MicroBatcher:
    """One batching queue per algorithm kind ('sw' | 'tb')."""

    def __init__(
        self,
        dispatch: Dict[str, Callable],      # algo -> fn(slots, lids, permits) -> handle
        clear: Dict[str, Callable],         # algo -> fn(slots) -> None
        drain: Dict[str, Callable] | None = None,  # algo -> fn(handle, n) -> dict
        max_batch: int = 8192,
        max_delay_ms: float = 0.5,
        max_inflight: int = 4,
    ):
        self._dispatch = dispatch
        # Without a drain fn the dispatch result IS the output dict
        # (synchronous mode — tests and simple backends).
        self._drain = drain or {}
        self._clear = clear
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self.max_inflight = max(int(max_inflight), 1)
        self._cv = threading.Condition()
        self._pending: Dict[str, _Pending] = {a: _Pending() for a in dispatch}
        self._dispatch_lock = threading.Lock()  # serializes device batches
        self._closed = False
        # Concurrent fetches: one worker per in-flight batch; the semaphore
        # is the backpressure bound on the device queue.
        self._drain_pool = ThreadPoolExecutor(
            max_workers=self.max_inflight,
            thread_name_prefix="ratelimiter-drain")
        self._inflight_sem = threading.Semaphore(self.max_inflight)
        self._flusher = threading.Thread(
            target=self._run, name="ratelimiter-flusher", daemon=True)
        self._flusher.start()

    # -- submission -----------------------------------------------------------
    def submit(self, algo: str, slot: int, lid: int, permits: int) -> Future:
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher closed")
            pend = self._pending[algo]
            if pend.born is None:
                pend.born = time.monotonic()
            pend.slots.append(slot)
            pend.lids.append(lid)
            pend.permits.append(permits)
            pend.futures.append(fut)
            self._cv.notify()
        return fut

    def add_clear(self, algo: str, slot: int) -> None:
        """Schedule a slot zeroing ahead of the next batch (eviction)."""
        with self._cv:
            pend = self._pending[algo]
            if pend.born is None:
                pend.born = time.monotonic()
            pend.clears.append(slot)
            self._cv.notify()

    def pending_slots(self, algo: str) -> Set[int]:
        """Slots referenced by queued requests (pin set for eviction)."""
        with self._cv:
            return set(self._pending[algo].slots)

    # -- flushing -------------------------------------------------------------
    def _take(self, algo: str) -> _Pending | None:
        pend = self._pending[algo]
        if not pend.slots and not pend.clears:
            return None
        self._pending[algo] = _Pending()
        return pend

    def flush(self) -> None:
        """Dispatch everything pending (admin/reset/shutdown and read
        barriers).  Returns once the batches are in the device stream —
        later reads observe them (dispatch order == device order); the
        waiters' futures resolve asynchronously via the drainer."""
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        self._execute(taken)

    def _resolve(self, algo: str, handle, futures: List[Future]) -> None:
        """Fetch a dispatched batch's results and resolve its futures."""
        try:
            drain = self._drain.get(algo)
            out = drain(handle, len(futures)) if drain else handle
            for i, fut in enumerate(futures):
                fut.set_result({k: v[i] for k, v in out.items()})
        except Exception as exc:  # noqa: BLE001 — fail every waiter
            for fut in futures:
                if not fut.done():
                    fut.set_exception(exc)

    def _enqueue_drain(self, algo: str, handle, futures: List[Future]) -> None:
        self._inflight_sem.acquire()  # backpressure on the device queue

        def job():
            try:
                self._resolve(algo, handle, futures)
            finally:
                self._inflight_sem.release()

        try:
            self._drain_pool.submit(job)
        except RuntimeError:  # pool shut down mid-close: resolve inline
            job()

    def _execute(self, taken) -> None:
        with self._dispatch_lock:
            self._execute_locked(taken)

    def _execute_locked(self, taken) -> None:
        for algo, pend in taken.items():
            if pend is None:
                continue
            try:
                if pend.clears:
                    self._clear[algo](pend.clears)
                if pend.slots:
                    log.debug("dispatch algo=%s batch=%d clears=%d",
                              algo, len(pend.slots), len(pend.clears))
                    handle = self._dispatch[algo](
                        pend.slots, pend.lids, pend.permits)
                    self._enqueue_drain(algo, handle, pend.futures)
            except Exception as exc:  # noqa: BLE001 — fail every waiter
                log.warning("dispatch failed algo=%s batch=%d: %s",
                            algo, len(pend.slots), exc)
                for fut in pend.futures:
                    if not fut.done():
                        fut.set_exception(exc)

    def dispatch_direct(self, algo: str, slots, lids, permits, clears=None):
        """Synchronous whole-batch dispatch (the vectorized/bench path).

        Flushes everything pending first, then runs this batch under the
        same dispatch lock — so direct batches serialize with queued
        traffic and see a consistent state stream.  The direct batch's own
        fetch happens inline (its results are independent of the queued
        batches' fetches, which continue to drain in the background).
        """
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        with self._dispatch_lock:
            self._execute_locked(taken)
            if clears:
                self._clear[algo](clears)
            handle = self._dispatch[algo](slots, lids, permits)
        drain = self._drain.get(algo)
        return drain(handle, len(slots)) if drain else handle

    def _run(self) -> None:
        while True:
            locked = False
            with self._cv:
                while not self._closed:
                    now = time.monotonic()
                    ready, wait = [], None
                    for algo, pend in self._pending.items():
                        if pend.born is None:
                            continue
                        age = now - pend.born
                        if len(pend.slots) >= self.max_batch or age >= self.max_delay_s:
                            ready.append(algo)
                        else:
                            remaining = self.max_delay_s - age
                            wait = remaining if wait is None else min(wait, remaining)
                    if ready:
                        # Deadline hit — but if a dispatch is mid-flight,
                        # do NOT freeze the batch yet: a batch taken now
                        # would sit waiting for the lock while new
                        # arrivals start a fresh queue and pay a whole
                        # extra dispatch cycle (the convoy behind the r4
                        # SLO miss's ~1.6 ms of batcher-owned latency).
                        # Keep accumulating and re-check shortly; the
                        # take happens with the lock ALREADY HELD, so
                        # the batch carries everything that arrived
                        # during the previous step.
                        if self._dispatch_lock.acquire(blocking=False):
                            locked = True
                            break
                        # Floored: with max_delay_ms=0 an unfloored wait
                        # would spin the cv at full speed for as long as
                        # the in-flight dispatch holds the lock.
                        self._cv.wait(timeout=max(
                            min(self.max_delay_s, 3e-4), 5e-5))
                        continue
                    self._cv.wait(timeout=wait)
                if self._closed and not any(
                    p.born is not None for p in self._pending.values()
                ):
                    if locked:
                        self._dispatch_lock.release()
                    return
                taken = {a: self._take(a) for a in self._pending}
            try:
                if locked:
                    self._execute_locked(taken)
                else:  # close() drained the cv loop: plain locked path
                    self._execute(taken)
            finally:
                if locked:
                    self._dispatch_lock.release()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._flusher.join(timeout=5)
        self.flush()
        # Resolve whatever is still on the wire before returning.
        self._drain_pool.shutdown(wait=True)
