"""Micro-batcher: coalesces concurrent tryAcquire calls into device batches.

The reference's unit of concurrency is a servlet thread blocking on a Redis
round-trip (~800 us, ARCHITECTURE.md latency model); ours is a Future that
resolves when the next device batch lands.  Threads submit requests; a
dedicated flusher thread dispatches a batch when either

- the pending batch reaches ``max_batch``, or
- the oldest pending request has waited ``max_delay_ms`` (adaptive flush:
  size OR deadline — SURVEY.md §7 "Batching latency vs p99"),

whichever comes first.  The queue lock is released during device execution
so new requests accumulate while the previous batch runs (host/device
pipelining); dispatches are serialized, preserving batch order, which is
what makes eviction-clears safe (cleared slots are zeroed in the same
dispatch stream before the batch that reuses them).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Callable, Dict, List, Set


class _Pending:
    __slots__ = ("slots", "lids", "permits", "futures", "clears", "born")

    def __init__(self):
        self.slots: List[int] = []
        self.lids: List[int] = []
        self.permits: List[int] = []
        self.futures: List[Future] = []
        self.clears: List[int] = []
        self.born: float | None = None  # monotonic time of oldest request


class MicroBatcher:
    """One batching queue per algorithm kind ('sw' | 'tb')."""

    def __init__(
        self,
        dispatch: Dict[str, Callable],      # algo -> fn(slots, lids, permits) -> dict
        clear: Dict[str, Callable],         # algo -> fn(slots) -> None
        max_batch: int = 8192,
        max_delay_ms: float = 0.5,
    ):
        self._dispatch = dispatch
        self._clear = clear
        self.max_batch = int(max_batch)
        self.max_delay_s = float(max_delay_ms) / 1000.0
        self._cv = threading.Condition()
        self._pending: Dict[str, _Pending] = {a: _Pending() for a in dispatch}
        self._dispatch_lock = threading.Lock()  # serializes device batches
        self._closed = False
        self._flusher = threading.Thread(
            target=self._run, name="ratelimiter-flusher", daemon=True)
        self._flusher.start()

    # -- submission -----------------------------------------------------------
    def submit(self, algo: str, slot: int, lid: int, permits: int) -> Future:
        fut: Future = Future()
        with self._cv:
            if self._closed:
                raise RuntimeError("batcher closed")
            pend = self._pending[algo]
            if pend.born is None:
                pend.born = time.monotonic()
            pend.slots.append(slot)
            pend.lids.append(lid)
            pend.permits.append(permits)
            pend.futures.append(fut)
            self._cv.notify()
        return fut

    def add_clear(self, algo: str, slot: int) -> None:
        """Schedule a slot zeroing ahead of the next batch (eviction)."""
        with self._cv:
            pend = self._pending[algo]
            if pend.born is None:
                pend.born = time.monotonic()
            pend.clears.append(slot)
            self._cv.notify()

    def pending_slots(self, algo: str) -> Set[int]:
        """Slots referenced by queued requests (pin set for eviction)."""
        with self._cv:
            return set(self._pending[algo].slots)

    # -- flushing -------------------------------------------------------------
    def _take(self, algo: str) -> _Pending | None:
        pend = self._pending[algo]
        if not pend.slots and not pend.clears:
            return None
        self._pending[algo] = _Pending()
        return pend

    def flush(self) -> None:
        """Synchronously dispatch everything pending (admin/reset/shutdown)."""
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        self._execute(taken)

    def _execute(self, taken) -> None:
        with self._dispatch_lock:
            self._execute_locked(taken)

    def _execute_locked(self, taken) -> None:
        for algo, pend in taken.items():
            if pend is None:
                continue
            try:
                if pend.clears:
                    self._clear[algo](pend.clears)
                if pend.slots:
                    out = self._dispatch[algo](pend.slots, pend.lids, pend.permits)
                    for i, fut in enumerate(pend.futures):
                        fut.set_result({k: v[i] for k, v in out.items()})
            except Exception as exc:  # noqa: BLE001 — fail every waiter
                for fut in pend.futures:
                    if not fut.done():
                        fut.set_exception(exc)

    def dispatch_direct(self, algo: str, slots, lids, permits, clears=None):
        """Synchronous whole-batch dispatch (the vectorized/bench path).

        Flushes everything pending first, then runs this batch under the same
        dispatch lock — so direct batches serialize with queued traffic and
        see a consistent state stream.
        """
        with self._cv:
            taken = {a: self._take(a) for a in self._pending}
        with self._dispatch_lock:
            self._execute_locked(taken)
            if clears:
                self._clear[algo](clears)
            return self._dispatch[algo](slots, lids, permits)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._closed:
                    now = time.monotonic()
                    ready, wait = [], None
                    for algo, pend in self._pending.items():
                        if pend.born is None:
                            continue
                        age = now - pend.born
                        if len(pend.slots) >= self.max_batch or age >= self.max_delay_s:
                            ready.append(algo)
                        else:
                            remaining = self.max_delay_s - age
                            wait = remaining if wait is None else min(wait, remaining)
                    if ready:
                        break
                    self._cv.wait(timeout=wait)
                if self._closed and not any(
                    p.born is not None for p in self._pending.values()
                ):
                    return
                taken = {a: self._take(a) for a in self._pending}
            self._execute(taken)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._flusher.join(timeout=5)
        self.flush()
