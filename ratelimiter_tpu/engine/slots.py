"""Host-side key -> slot index.

The device state is a fixed-capacity slot array (engine/state.py); this
index owns the mapping from (limiter_id, key) strings to slot ids.  It is
the TPU build's analog of two reference mechanisms at once:

- Redis's keyspace + TTL eviction (keys hash into Redis; expired keys are
  collected lazily) — here: LRU-ordered assignment with eviction of the
  least-recently-touched key when the slot array is full;
- the Caffeine cache's role as the host-side key bookkeeping
  (BASELINE.json north star: "the Caffeine local cache is repurposed as the
  host-side key->slot index").

Eviction contract: an evicted slot's device state MUST be cleared before the
slot is reused (a zeroed slot behaves as an absent key).  ``assign`` returns
the slot to clear, and callers (the micro-batcher) schedule the clear ahead
of the reusing batch.  Slots referenced by the currently-pending batch can
be pinned so eviction never pulls state out from under queued requests.

A faster C++ implementation with the same interface lives in
``native/slot_index.cpp`` (see engine/native_index.py); this pure-Python
version is the portable fallback and the semantic reference for the
scalar ops.  Recency is defined at BATCH granularity: all touches of a
key within one batch-assign call count as a single touch at its first
occurrence (the native index exploits this to skip LRU re-links on
repeat hits — the dominant host cost on Zipf traffic; Redis makes the
same resolution trade with its sampled LRU).  This scalar index sees one
key per call, so each call is its own batch and the contracts coincide.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Dict, Hashable, Optional, Set, Tuple

import numpy as np


class SlotIndex:
    """LRU slot assignment over a fixed slot capacity."""

    def __init__(self, num_slots: int):
        if num_slots <= 0:
            raise ValueError("num_slots must be positive")
        self.num_slots = int(num_slots)
        self._lock = threading.Lock()
        self._map: "OrderedDict[Hashable, int]" = OrderedDict()  # key -> slot, LRU order
        self._free = list(range(self.num_slots - 1, -1, -1))
        # Refcounted held pins (streams: assign -> dispatch-enqueue window).
        self._pins: Dict[int, int] = {}
        # Slots removed (admin reset) while pinned: freed on last unpin via
        # the dirty list, and reported as their own eviction when reassigned
        # so the caller re-clears the (possibly stale) device state first.
        self._deferred: Set[int] = set()
        self._dirty: list = []

    def get(self, key: Hashable) -> Optional[int]:
        """Slot for key, or None; refreshes recency."""
        with self._lock:
            slot = self._map.get(key)
            if slot is not None:
                self._map.move_to_end(key)
            return slot

    def assign(
        self, key: Hashable, pinned: Optional[Set[int]] = None,
        hold_pin: bool = False
    ) -> Tuple[int, Optional[int]]:
        """Slot for key, allocating (and possibly evicting) if absent.

        Returns (slot, evicted_slot): ``evicted_slot`` is not None when an
        LRU victim was displaced — its device state must be cleared before
        this slot's next use.  Raises RuntimeError if every slot is pinned.
        """
        def held(slot):
            if hold_pin:
                self._pins[slot] = self._pins.get(slot, 0) + 1
            return slot

        with self._lock:
            slot = self._map.get(key)
            if slot is not None:
                self._map.move_to_end(key)
                return held(slot), None
            if self._free:
                slot = self._free.pop()
                self._map[key] = slot
                return held(slot), None
            # Removed-while-pinned slots, since unpinned: may carry a stale
            # write from the formerly-pinned dispatch — reported as their
            # own eviction so the caller clears them before reuse.  A dirty
            # slot can have been RE-pinned since it was listed (a queued
            # request via the per-call pinned set): skip those, exactly as
            # the LRU eviction scan below does.
            for i in range(len(self._dirty) - 1, -1, -1):
                slot = self._dirty[i]
                if self._pins.get(slot) or (pinned and slot in pinned):
                    continue
                del self._dirty[i]
                self._map[key] = slot
                return held(slot), slot
            # Evict the least-recently-used non-pinned key.
            for victim_key, victim_slot in self._map.items():
                if pinned and victim_slot in pinned:
                    continue
                if self._pins.get(victim_slot):
                    continue
                del self._map[victim_key]
                self._map[key] = victim_slot
                return held(victim_slot), victim_slot
            raise RuntimeError("all slots pinned; increase num_slots or flush")

    def pin_batch(self, slots) -> None:
        """Refcounted pins (duplicates fine) held across a dispatch-prep
        window so concurrent assigns can't evict these slots."""
        with self._lock:
            for s in np.asarray(slots):
                s = int(s)
                if 0 <= s < self.num_slots:
                    self._pins[s] = self._pins.get(s, 0) + 1

    def unpin_batch(self, slots) -> None:
        with self._lock:
            for s in np.asarray(slots):
                s = int(s)
                c = self._pins.get(s, 0)
                if c <= 1:
                    self._pins.pop(s, None)
                    if c == 1 and s in self._deferred:
                        self._deferred.discard(s)
                        self._dirty.append(s)
                else:
                    self._pins[s] = c - 1

    def remove(self, key: Hashable) -> Optional[int]:
        """Drop a key (admin reset); returns its slot (caller clears it).

        A slot with a live pin refcount (a stream's assign->dispatch window)
        is not freed immediately — it joins the dirty list at last unpin so
        a new key can never receive the pinned dispatch's stale write."""
        with self._lock:
            slot = self._map.pop(key, None)
            if slot is not None:
                if self._pins.get(slot):
                    self._deferred.add(slot)
                else:
                    self._free.append(slot)
            return slot

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)
