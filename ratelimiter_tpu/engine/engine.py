"""Single-device decision engine.

Owns the device-resident slot state for both algorithms, the jitted step
functions (donated state buffers — updates happen in place in HBM), and the
batch padding discipline (power-of-two buckets so XLA compiles a handful of
shapes, then every flush hits the cache).

This is the device half of ``TpuBatchedStorage``; the host half (key->slot
index + micro-batcher) lives in engine/slots.py and engine/batcher.py.
"""

from __future__ import annotations

import functools
import threading
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ratelimiter_tpu.engine.state import LimiterTable, SWState, TBState
from ratelimiter_tpu.ops.flat import sw_flat_bits, tb_flat_bits
from ratelimiter_tpu.ops.relay import (
    sw_relay_bits,
    sw_relay_counts,
    tb_relay_bits,
    tb_relay_counts,
)
from ratelimiter_tpu.ops.packed import (
    decode_sw_fused,
    decode_tb_fused,
    sw_scan_bits,
    sw_step_fused,
    tb_scan_bits,
    tb_step_fused,
)
from ratelimiter_tpu.ops.sliding_window import (
    make_sw_packed,
    sw_pack_state,
    sw_peek_p,
    sw_reset_p,
    sw_unpack_state,
)
from ratelimiter_tpu.ops.token_bucket import (
    make_tb_packed,
    tb_pack_state,
    tb_peek_p,
    tb_reset_p,
    tb_unpack_state,
)

_MIN_BATCH = 256
# Micro-batch floor (r6): interactive traffic through the micro-batcher
# produces 1-100-request batches, and padding them to 256 lanes made the
# device step ~0.7 ms on the CPU backend — most of the local-SLO p50 miss
# (BENCH_r05 latency_slo_local: p50 1558 us vs the 1000 us target).
# Small batches now bucket at {32, 64, 128} before joining the pow2
# ladder; three extra compile shapes, device step cost proportional to
# lanes.  Streams never see these shapes (their chunks are >= 2^19).
_MICRO_FLOOR = 32

# Staged micro-batch layout (r11): one i64[4, B] host buffer carries the
# whole batch — row 0 slots (pad -1), row 1 limiter ids (pad 0), row 2
# permits (pad 1), row 3 lane 0 the batch timestamp.  One device_put per
# dispatch instead of four: on the CPU backend each small-array put costs
# ~50-70 us of runtime overhead regardless of size, and four of them were
# most of the 0.88 ms assembly stage the latency SLO missed on.
MICRO_STAGE_ROWS = 4


def _sw_micro_step_combined(state, tarrs, staged):
    return sw_step_fused(state, tarrs,
                         staged[0].astype(jnp.int32),
                         staged[1].astype(jnp.int32),
                         staged[2], staged[3, 0])


def _tb_micro_step_combined(state, tarrs, staged):
    return tb_step_fused(state, tarrs,
                         staged[0].astype(jnp.int32),
                         staged[1].astype(jnp.int32),
                         staged[2], staged[3, 0])


# Module-level jitted singletons, NOT per-engine closures: jax's tracing
# and executable caches key on the underlying function identity, so every
# DeviceEngine in a process shares one compile per (algo, bucket, table
# shape) — a per-engine closure would re-trace (~0.3 s) and possibly
# re-compile on every storage construction.
_MICRO_STEPS = {
    "sw": jax.jit(_sw_micro_step_combined, donate_argnums=0),
    "tb": jax.jit(_tb_micro_step_combined, donate_argnums=0),
}


def _bucket_size(n: int) -> int:
    size = _MICRO_FLOOR
    while size < n:
        size *= 2
    return size


def _pad_i32(x: np.ndarray, size: int, fill: int) -> jnp.ndarray:
    out = np.full(size, fill, dtype=np.int32)
    out[: len(x)] = x
    return jnp.asarray(out)


def _pad_i64(x: np.ndarray, size: int, fill: int) -> jnp.ndarray:
    out = np.full(size, fill, dtype=np.int64)
    out[: len(x)] = x
    return jnp.asarray(out)


class DeviceEngine:
    """Batched decision engine over TPU-resident counter arrays."""

    # Replication (replication/log.py) works at this engine's packed-row
    # granularity; the sharded engine partitions state differently and is
    # not journaled yet.
    supports_replication = True

    def __init__(self, num_slots: int, table: LimiterTable):
        self.num_slots = int(num_slots)
        self.table = table
        # Optional dirty-slot journal (engine/state.py:SlotJournal): when
        # attached, every mutation path marks the slots it touches before
        # dispatching, so a replication log can ship per-epoch deltas.
        # None (the default) keeps the hot path at one attribute check.
        self.journal = None
        # The step functions donate the state buffers (in-place HBM updates),
        # so every access — including read-only peeks, which must not grab a
        # reference that a concurrent step is about to invalidate — is
        # serialized through this lock.
        self._lock = threading.RLock()
        # State lives packed (i32 lanes — see ops/{sliding_window,token_bucket})
        # for gather/scatter speed; the sw_state/tb_state properties expose the
        # i64 field view for checkpointing and inspection.
        self.sw_packed = make_sw_packed(self.num_slots)
        self.tb_packed = make_tb_packed(self.num_slots)
        # Fused steps return all outputs in one array — one D2H transfer per
        # batch instead of four (the transfer-latency fix; ops/packed.py).
        # The micro path runs them through the COMBINED staged form
        # (_micro_step: one i64[4, B] upload carries slots/lids/permits/
        # now) so the list and staged dispatch surfaces share one
        # compiled executable per (algo, bucket).
        self._sw_scan = jax.jit(sw_scan_bits, donate_argnums=0)
        self._tb_scan = jax.jit(tb_scan_bits, donate_argnums=0)
        self._sw_flat = jax.jit(sw_flat_bits, donate_argnums=0)
        self._tb_flat = jax.jit(tb_flat_bits, donate_argnums=0)
        # Relay word layout (ops/relay.py): slot_bits must cover num_slots
        # with the all-ones padding word left over; the remaining bits of
        # the uint32 carry the duplicate rank + last flag.
        self.slot_bits = max(int(self.num_slots).bit_length(), 1)
        self.rank_bits = 31 - self.slot_bits
        self._sw_relay = jax.jit(functools.partial(
            sw_relay_bits, rank_bits=self.rank_bits), donate_argnums=0)
        self._tb_relay = jax.jit(functools.partial(
            tb_relay_bits, rank_bits=self.rank_bits), donate_argnums=0)
        self._relay_counts = {}  # (algo, out_dtype name, sorted) -> jitted step
        self._relay_weighted = {}  # (algo, r_steps) -> jitted weighted step
        # Largest per-request permits the weighted relay carries (uint8
        # CSR permits lane); larger permits take the sorted flat path.
        self.weighted_permit_cap = 255
        # Resident tenant-id map per algo (ops/relay.py:*_relay_counts_
        # resident): one slot = one (limiter, key), so a slot's lid is
        # immutable while assigned; the digest-multi path uploads only
        # the deltas and reads policies from this array.
        self.sw_lid_map = jnp.zeros(self.num_slots, dtype=jnp.int32)
        self.tb_lid_map = jnp.zeros(self.num_slots, dtype=jnp.int32)
        self._relay_resident = {}  # (algo, out_dtype name, sorted) -> jitted step
        self._sw_peek = jax.jit(sw_peek_p)
        self._tb_peek = jax.jit(tb_peek_p)
        # Settle the Pallas probes NOW, before any step kernel compiles:
        # a probe firing lazily inside another program's lowering nests a
        # second remote compile on toolchains that cannot serve one, and
        # the resulting failure would stick as a permanent fallback.
        from ratelimiter_tpu.ops import pallas as pallas_kernels

        pallas_kernels.settle_all()
        self._sw_reset = jax.jit(sw_reset_p, donate_argnums=0)
        self._tb_reset = jax.jit(tb_reset_p, donate_argnums=0)

    # -- dirty-slot journal hooks (replication) --------------------------------
    # Each hook takes the HOST lane array plus (optionally) the same
    # array already converted for the dispatch: a device journal
    # (engine/state.py:DeviceSlotJournal) marks from the device copy —
    # zero extra host work or upload — while the host journal keeps its
    # numpy path (handing it a device array would force a sync fetch).
    def _mark(self, algo: str, slots, dev=None) -> None:
        j = self.journal
        if j is not None:
            j.mark(algo, dev if dev is not None
                   and getattr(j, "device", False) else slots)

    def _mark_words(self, algo: str, words, dev=None) -> None:
        """Mark from relay uwords (slot in the high bits; padding words
        decode past num_slots and are filtered by the journal)."""
        j = self.journal
        if j is not None:
            j.mark_words(algo, dev if dev is not None
                         and getattr(j, "device", False) else words,
                         self.rank_bits)

    # -- i64 field view (checkpoint/compat) ------------------------------------
    @property
    def sw_state(self) -> SWState:
        return sw_unpack_state(self.sw_packed)

    @sw_state.setter
    def sw_state(self, state: SWState) -> None:
        if self.journal is not None:
            self.journal.mark_all("sw")
        self.sw_packed = sw_pack_state(
            SWState(*(jnp.asarray(f) for f in state)))

    @property
    def tb_state(self) -> TBState:
        return tb_unpack_state(self.tb_packed)

    @tb_state.setter
    def tb_state(self, state: TBState) -> None:
        if self.journal is not None:
            self.journal.mark_all("tb")
        self.tb_packed = tb_pack_state(
            TBState(*(jnp.asarray(f) for f in state)))

    # -- acquire --------------------------------------------------------------
    # Each step is split into DISPATCH (enqueue on device, state updated,
    # returns a lazy output handle — engine lock held only here) and DRAIN
    # (the blocking device->host fetch + decode, outside the lock).  The
    # split is what lets the micro-batcher keep several batches in flight:
    # the next dispatch runs while previous fetches are still on the wire.

    def _acquire_dispatch(self, algo: str, slots, limiter_ids, permits,
                          now_ms: int):
        """List-surface dispatch: stage the batch into a combined buffer
        and run the same staged step the micro-batcher's flusher uses —
        one upload, one cached executable per (algo, bucket)."""
        n = len(slots)
        size = _bucket_size(n)
        staged = np.empty((MICRO_STAGE_ROWS, size), dtype=np.int64)
        staged[0] = -1
        staged[1] = 0
        staged[2] = 1
        staged[0, :n] = np.asarray(slots, dtype=np.int64)
        staged[1, :n] = np.asarray(limiter_ids, dtype=np.int64)
        staged[2, :n] = np.asarray(permits, dtype=np.int64)
        staged[3, 0] = now_ms
        return self.micro_staged_dispatch(algo, staged, n)

    def sw_acquire_dispatch(self, slots, limiter_ids, permits, now_ms: int):
        """Dispatch a sliding-window batch; returns a lazy fused handle
        (pass to :meth:`sw_acquire_drain` with the batch length)."""
        return self._acquire_dispatch("sw", slots, limiter_ids, permits,
                                      now_ms)

    @staticmethod
    def sw_acquire_drain(handle, n: int):
        return decode_sw_fused(np.asarray(handle)[:, :n])

    def sw_acquire(self, slots, limiter_ids, permits, now_ms: int):
        """Batched sliding-window tryAcquire. Returns dict of numpy arrays
        (allowed, mutated, observed, cache_value), trimmed to the input size."""
        handle = self.sw_acquire_dispatch(slots, limiter_ids, permits, now_ms)
        return self.sw_acquire_drain(handle, len(slots))

    def tb_acquire_dispatch(self, slots, limiter_ids, permits, now_ms: int):
        return self._acquire_dispatch("tb", slots, limiter_ids, permits,
                                      now_ms)

    @staticmethod
    def tb_acquire_drain(handle, n: int):
        return decode_tb_fused(np.asarray(handle)[:, :n])

    def tb_acquire(self, slots, limiter_ids, permits, now_ms: int):
        handle = self.tb_acquire_dispatch(slots, limiter_ids, permits, now_ms)
        return self.tb_acquire_drain(handle, len(slots))

    # -- staged micro-batch dispatch (double-buffered assembly, r11) ----------
    # The micro-batcher packs requests into an i64[4, cap] staging buffer
    # AT SUBMIT TIME (engine/batcher.py:_Pending), so by flush time the
    # batch is already laid out and dispatch is one upload + one cached
    # jit call.  Layout: MICRO_STAGE_ROWS doc at the top of this module.

    def micro_staged_dispatch(self, algo: str, staged: np.ndarray, n: int):
        """Dispatch a pre-staged micro-batch: ``staged`` is the combined
        i64[4, cap] host buffer (cap a pow2 >= _MICRO_FLOOR, padding lanes
        already holding their fill values, timestamp at [3, 0]); ``n`` is
        the live lane count.  Returns the lazy fused handle for
        :meth:`micro_staged_drain`.  The device copy happens outside the
        engine lock so a staged upload overlaps a concurrent dispatch."""
        size = _bucket_size(n)
        if size != staged.shape[1]:
            staged = np.ascontiguousarray(staged[:, :size])
        self._mark(algo, staged[0, :n])
        step = _MICRO_STEPS[algo]
        # The staged numpy buffer goes to the jit call DIRECTLY (~30 us
        # vs ~100 us via an explicit device_put first — the §6b
        # committed-array trap).  On CPU the call may ALIAS the host
        # memory zero-copy: the caller must not mutate the buffer until
        # the batch's results were fetched (the batcher recycles staging
        # buffers at drain time for exactly this reason).
        with self._lock:
            if algo == "sw":
                self.sw_packed, packed = step(
                    self.sw_packed, self.table.device_arrays, staged)
            else:
                self.tb_packed, packed = step(
                    self.tb_packed, self.table.device_arrays, staged)
        return packed

    @staticmethod
    def micro_staged_drain(algo: str, handle, n: int):
        decode = decode_sw_fused if algo == "sw" else decode_tb_fused
        return decode(np.asarray(handle)[:, :n])

    @staticmethod
    def micro_compile_count() -> int:
        """Number of compiled micro-step signatures (staged path,
        process-wide — the steps are module-level singletons), for the
        no-recompile steady-state assertion in bench/device_only.py."""
        return sum(fn._cache_size() for fn in _MICRO_STEPS.values())

    # -- scan dispatch (K sub-batches, bit-packed decisions) -------------------
    # The hyperscale streaming path: one device dispatch for K*B decisions,
    # returning a lazy uint8[K, ceil(B/8)] handle — the caller fetches it
    # (np.asarray) outside the lock, overlapping the next dispatch.

    def sw_scan_dispatch(self, slots_kb, lids, permits_kb, now_k):
        return self._scan_dispatch("sw", slots_kb, lids, permits_kb, now_k)

    def tb_scan_dispatch(self, slots_kb, lids, permits_kb, now_k):
        return self._scan_dispatch("tb", slots_kb, lids, permits_kb, now_k)

    def _scan_dispatch(self, algo, slots_kb, lids, permits_kb, now_k):
        slots_host = slots_kb
        slots_kb = jnp.asarray(np.ascontiguousarray(slots_kb, dtype=np.int32))
        self._mark(algo, slots_host, dev=slots_kb)
        if np.ndim(lids) == 0:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        if permits_kb is not None:
            pdt = (np.uint8 if getattr(permits_kb, "dtype", None) == np.uint8
                   else np.int32)
            permits_kb = jnp.asarray(
                np.ascontiguousarray(permits_kb, dtype=pdt))
        now_k = jnp.asarray(np.ascontiguousarray(now_k, dtype=np.int64))
        with self._lock:
            if algo == "sw":
                self.sw_packed, bits = self._sw_scan(
                    self.sw_packed, self.table.device_arrays,
                    slots_kb, lids, permits_kb, now_k)
            else:
                self.tb_packed, bits = self._tb_scan(
                    self.tb_packed, self.table.device_arrays,
                    slots_kb, lids, permits_kb, now_k)
        return bits

    # -- flat mega-batch dispatch (ops/flat.py) --------------------------------
    # The streaming hot path: one flat sorted batch per dispatch (all
    # requests share the dispatch timestamp), bit-packed decisions back.

    def sw_flat_dispatch(self, slots, lids, permits, now_ms):
        return self._flat_dispatch("sw", slots, lids, permits, now_ms)

    def tb_flat_dispatch(self, slots, lids, permits, now_ms):
        return self._flat_dispatch("tb", slots, lids, permits, now_ms)

    def _flat_dispatch(self, algo, slots, lids, permits, now_ms):
        slots_host = slots
        slots = jnp.asarray(np.ascontiguousarray(slots, dtype=np.int32))
        self._mark(algo, slots_host, dev=slots)
        if np.ndim(lids) == 0:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        if permits is not None:
            # uint8 lanes (all permits <= 255) ship 4x fewer bytes; the
            # step upcasts to i64 internally either way.
            pdt = (np.uint8 if getattr(permits, "dtype", None) == np.uint8
                   else np.int32)
            permits = jnp.asarray(np.ascontiguousarray(permits, dtype=pdt))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, bits = self._sw_flat(
                    self.sw_packed, self.table.device_arrays,
                    slots, lids, permits, now)
            else:
                self.tb_packed, bits = self._tb_flat(
                    self.tb_packed, self.table.device_arrays,
                    slots, lids, permits, now)
        return bits

    # -- relay dispatch (ops/relay.py) -----------------------------------------
    # The unit-permit streaming hot path: slot + duplicate-rank + last flag
    # packed into one uint32 per request by the host index; the device step
    # is gather + elementwise + masked scatter + packbits (no sort/scan).

    def relay_usable(self) -> bool:
        from ratelimiter_tpu.ops import relay as relay_ops

        return relay_ops.relay_usable(self.rank_bits,
                                      self.table.max_permits_registered)

    def sw_relay_dispatch(self, words, lids, now_ms):
        return self._relay_dispatch("sw", words, lids, now_ms)

    def tb_relay_dispatch(self, words, lids, now_ms):
        return self._relay_dispatch("tb", words, lids, now_ms)

    def _relay_dispatch(self, algo, words, lids, now_ms):
        """words uint32[B] (padding 0xFFFFFFFF); lids scalar or i32[B];
        returns a lazy uint8[B/8] arrival-order allow bitmask handle."""
        words_host = words
        words = jnp.asarray(np.ascontiguousarray(words, dtype=np.uint32))
        self._mark_words(algo, words_host, dev=words)
        if np.ndim(lids) == 0:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, bits = self._sw_relay(
                    self.sw_packed, self.table.device_arrays, words, lids, now)
            else:
                self.tb_packed, bits = self._tb_relay(
                    self.tb_packed, self.table.device_arrays, words, lids, now)
        return bits

    def counts_dtype(self):
        from ratelimiter_tpu.ops import relay as relay_ops

        return relay_ops.counts_dtype(self.table.max_permits_registered)

    def _relay_fused_ok(self, algo: str, u_padded: int) -> bool:
        """Whether a scalar-lid sorted digest dispatch of ``u_padded``
        lanes takes the fused Pallas relay step (geometry + probe +
        measured election; ops/pallas/relay_step.py)."""
        from ratelimiter_tpu.ops.pallas import relay_step

        shape = (self.sw_packed if algo == "sw" else self.tb_packed).shape
        return relay_step.enabled(shape, u_padded, self.rank_bits)

    # -- weighted relay dispatch (ops/relay.py:*_relay_weighted) ---------------
    def sw_weighted_dispatch(self, uwords, perms_rank, roff, lid,
                             now_ms, r_steps):
        return self._weighted_dispatch("sw", uwords, perms_rank, roff,
                                       lid, now_ms, r_steps)

    def tb_weighted_dispatch(self, uwords, perms_rank, roff, lid,
                             now_ms, r_steps):
        return self._weighted_dispatch("tb", uwords, perms_rank, roff,
                                       lid, now_ms, r_steps)

    def _weighted_dispatch(self, algo, uwords, perms_rank, roff, lid,
                           now_ms, r_steps):
        """uwords uint32[U] (slot | count; padding 0xFFFFFFFF; segments
        in count-descending order), perms_rank uint8[N+U] rank-major
        compacted permits, roff i32[R] per-rank offsets; returns the
        lazy uint8[r_steps, U/8] decision-bit handle (bit [r, j] = r-th
        request of count-sorted segment j)."""
        from ratelimiter_tpu.ops.relay import (
            sw_relay_weighted,
            tb_relay_weighted,
        )

        uwords_host = uwords
        uwords = jnp.asarray(np.ascontiguousarray(uwords, dtype=np.uint32))
        self._mark_words(algo, uwords_host, dev=uwords)
        key = (algo, int(r_steps))
        fn = self._relay_weighted.get(key)
        if fn is None:
            base = sw_relay_weighted if algo == "sw" else tb_relay_weighted
            fn = jax.jit(functools.partial(
                base, rank_bits=self.rank_bits, r_steps=int(r_steps)),
                donate_argnums=0)
            self._relay_weighted[key] = fn
        perms_rank = jnp.asarray(
            np.ascontiguousarray(perms_rank, dtype=np.uint8))
        roff = jnp.asarray(np.ascontiguousarray(roff, dtype=np.int32))
        lid = jnp.asarray(np.int32(lid))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, bits = fn(
                    self.sw_packed, self.table.device_arrays, uwords,
                    perms_rank, roff, lid, now)
            else:
                self.tb_packed, bits = fn(
                    self.tb_packed, self.table.device_arrays, uwords,
                    perms_rank, roff, lid, now)
        return bits

    def sw_weighted_counts_dispatch(self, uwords, wlane, lid, now_ms,
                                    out_dtype):
        return self._weighted_counts_dispatch("sw", uwords, wlane, lid,
                                              now_ms, out_dtype)

    def tb_weighted_counts_dispatch(self, uwords, wlane, lid, now_ms,
                                    out_dtype):
        return self._weighted_counts_dispatch("tb", uwords, wlane, lid,
                                              now_ms, out_dtype)

    def _weighted_counts_dispatch(self, algo, uwords, wlane, lid, now_ms,
                                  out_dtype):
        """Coalesced weighted digest dispatch
        (ops/relay.py:*_relay_weighted_counts): uwords uint32[U] (slot |
        clamped count; padding 0xFFFFFFFF), wlane uint8[U] the segment's
        uniform per-request weight; returns the lazy out_dtype[U]
        per-unique allowed-count handle (the host reconstructs
        ``rank < counts[uidx]``).  Only valid when every repeat of a key
        inside the chunk carries the same weight — the stream loop
        elects this per chunk and falls back to the scan otherwise."""
        from ratelimiter_tpu.ops.relay import (
            sw_relay_weighted_counts,
            tb_relay_weighted_counts,
        )

        uwords_host = uwords
        uwords = jnp.asarray(np.ascontiguousarray(uwords, dtype=np.uint32))
        self._mark_words(algo, uwords_host, dev=uwords)
        jdt = jnp.uint8 if out_dtype == np.uint8 else jnp.uint16
        key = (algo, out_dtype().dtype.name, "wcounts")
        fn = self._relay_counts.get(key)
        if fn is None:
            base = (sw_relay_weighted_counts if algo == "sw"
                    else tb_relay_weighted_counts)
            fn = jax.jit(functools.partial(
                base, rank_bits=self.rank_bits, out_dtype=jdt),
                donate_argnums=0)
            self._relay_counts[key] = fn
        wlane = jnp.asarray(np.ascontiguousarray(wlane, dtype=np.uint8))
        lid = jnp.asarray(np.int32(lid))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, counts = fn(
                    self.sw_packed, self.table.device_arrays, uwords,
                    wlane, lid, now)
            else:
                self.tb_packed, counts = fn(
                    self.tb_packed, self.table.device_arrays, uwords,
                    wlane, lid, now)
        return counts

    def sw_relay_counts_dispatch(self, uwords, lids, now_ms, out_dtype,
                                 slots_sorted=False):
        return self._relay_counts_dispatch("sw", uwords, lids, now_ms,
                                           out_dtype,
                                           slots_sorted=slots_sorted)

    def tb_relay_counts_dispatch(self, uwords, lids, now_ms, out_dtype,
                                 slots_sorted=False):
        return self._relay_counts_dispatch("tb", uwords, lids, now_ms,
                                           out_dtype,
                                           slots_sorted=slots_sorted)

    def sw_relay_counts_split_dispatch(self, s3, mwords, lids, now_ms,
                                       out_dtype):
        return self._relay_counts_split_dispatch("sw", s3, mwords, lids,
                                                 now_ms, out_dtype)

    def tb_relay_counts_split_dispatch(self, s3, mwords, lids, now_ms,
                                       out_dtype):
        return self._relay_counts_split_dispatch("tb", s3, mwords, lids,
                                                 now_ms, out_dtype)

    def _relay_counts_split_dispatch(self, algo, s3, mwords, lids, now_ms,
                                     out_dtype):
        """Split-digest dispatch (ops/relay.py:_relay_counts_split, r5):
        s3 uint8[S, 3] singleton slot plane (padding 0xFFFFFF), mwords
        uint32[M] multi-count uwords (padding 0xFFFFFFFF); returns ONE
        lazy uint8[S/8 + M*itemsize] handle: packed singleton allow
        bits followed by the multis' count bytes."""
        from ratelimiter_tpu.ops.relay import (
            sw_relay_counts_split,
            tb_relay_counts_split,
        )

        if self.journal is not None:
            # Singleton plane: little-endian 24-bit slots (padding 0xFFFFFF
            # decodes past num_slots — the journal filters it).
            s3a = np.asarray(s3, dtype=np.int64)
            self.journal.mark(
                algo, s3a[:, 0] | (s3a[:, 1] << 8) | (s3a[:, 2] << 16))
            self._mark_words(algo, mwords)

        jdt = jnp.uint8 if out_dtype == np.uint8 else jnp.uint16
        key = (algo, out_dtype().dtype.name, "split")
        fn = self._relay_counts.get(key)
        if fn is None:
            base = (sw_relay_counts_split if algo == "sw"
                    else tb_relay_counts_split)
            fn = jax.jit(functools.partial(
                base, rank_bits=self.rank_bits, out_dtype=jdt),
                donate_argnums=0)
            self._relay_counts[key] = fn
        s3 = jnp.asarray(np.ascontiguousarray(s3, dtype=np.uint8))
        mwords = jnp.asarray(np.ascontiguousarray(mwords, dtype=np.uint32))
        if np.ndim(lids) == 0:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, out = fn(
                    self.sw_packed, self.table.device_arrays, s3, mwords,
                    lids, now)
            else:
                self.tb_packed, out = fn(
                    self.tb_packed, self.table.device_arrays, s3, mwords,
                    lids, now)
        return out

    def sw_relay_counts_resident_dispatch(self, uwords, delta_slots,
                                          delta_lids, now_ms, out_dtype,
                                          slots_sorted=False):
        return self._relay_resident_dispatch("sw", uwords, delta_slots,
                                             delta_lids, now_ms, out_dtype,
                                             slots_sorted=slots_sorted)

    def tb_relay_counts_resident_dispatch(self, uwords, delta_slots,
                                          delta_lids, now_ms, out_dtype,
                                          slots_sorted=False):
        return self._relay_resident_dispatch("tb", uwords, delta_slots,
                                             delta_lids, now_ms, out_dtype,
                                             slots_sorted=slots_sorted)

    def _relay_resident_dispatch(self, algo, uwords, delta_slots, delta_lids,
                                 now_ms, out_dtype, slots_sorted=False):
        """Digest dispatch with device-resident lids: uwords uint32[U];
        delta (slot, lid) i32 pairs for slots whose lid the device doesn't
        know yet (padding slot = -1).  Returns the lazy counts handle."""
        from ratelimiter_tpu.ops.relay import (
            sw_relay_counts_resident,
            tb_relay_counts_resident,
        )

        uwords_host = uwords
        uwords = jnp.asarray(np.ascontiguousarray(uwords, dtype=np.uint32))
        self._mark_words(algo, uwords_host, dev=uwords)

        jdt = jnp.uint8 if out_dtype == np.uint8 else jnp.uint16
        key = (algo, out_dtype().dtype.name, bool(slots_sorted))
        fn = self._relay_resident.get(key)
        if fn is None:
            base = (sw_relay_counts_resident if algo == "sw"
                    else tb_relay_counts_resident)
            fn = jax.jit(functools.partial(
                base, rank_bits=self.rank_bits, out_dtype=jdt,
                slots_sorted=bool(slots_sorted)),
                donate_argnums=(0, 1))
            self._relay_resident[key] = fn
        delta_slots = jnp.asarray(
            np.ascontiguousarray(delta_slots, dtype=np.int32))
        delta_lids = jnp.asarray(
            np.ascontiguousarray(delta_lids, dtype=np.int32))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, self.sw_lid_map, counts = fn(
                    self.sw_packed, self.sw_lid_map,
                    self.table.device_arrays, uwords, delta_slots,
                    delta_lids, now)
            else:
                self.tb_packed, self.tb_lid_map, counts = fn(
                    self.tb_packed, self.tb_lid_map,
                    self.table.device_arrays, uwords, delta_slots,
                    delta_lids, now)
        return counts

    def _relay_counts_dispatch(self, algo, uwords, lids, now_ms, out_dtype,
                               slots_sorted=False):
        """uwords uint32[U] (slot | clamped count; padding 0xFFFFFFFF);
        returns a lazy out_dtype[U] per-unique allowed-count handle.
        ``slots_sorted`` (host sorted the uniques by slot): the step runs
        the FUSED Pallas relay kernel (ops/pallas/relay_step.py — one
        memory-resident gather+update+scatter pass) when the measured
        per-path election picked it on this device, else the composed
        XLA step with the dense presorted block sweep."""
        uwords_host = uwords
        uwords = jnp.asarray(np.ascontiguousarray(uwords, dtype=np.uint32))
        self._mark_words(algo, uwords_host, dev=uwords)
        jdt = jnp.uint8 if out_dtype == np.uint8 else jnp.uint16
        fused = bool(slots_sorted) and np.ndim(lids) == 0 and (
            self._relay_fused_ok(algo, len(uwords)))
        key = (algo, out_dtype().dtype.name,
               "fused" if fused else bool(slots_sorted))
        fn = self._relay_counts.get(key)
        if fn is None:
            if fused:
                from ratelimiter_tpu.ops.pallas import relay_step

                base = (relay_step.sw_relay_counts_fused if algo == "sw"
                        else relay_step.tb_relay_counts_fused)
                fn = jax.jit(functools.partial(
                    base, rank_bits=self.rank_bits, out_dtype=jdt,
                    interpret=relay_step.interpret_mode()),
                    donate_argnums=0)
            else:
                base = sw_relay_counts if algo == "sw" else tb_relay_counts
                fn = jax.jit(functools.partial(
                    base, rank_bits=self.rank_bits, out_dtype=jdt,
                    slots_sorted=bool(slots_sorted)),
                    donate_argnums=0)
            self._relay_counts[key] = fn
        if np.ndim(lids) == 0:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        now = jnp.int64(now_ms)
        with self._lock:
            if algo == "sw":
                self.sw_packed, counts = fn(
                    self.sw_packed, self.table.device_arrays, uwords, lids,
                    now)
            else:
                self.tb_packed, counts = fn(
                    self.tb_packed, self.table.device_arrays, uwords, lids,
                    now)
        return counts

    # -- lease RESERVE / CREDIT (ops/lease.py; leases/) ------------------------
    # The lease flavor of the decision dispatch: charge (or return) a
    # per-key permit budget in one gather -> roll/refill -> greedy grant
    # -> scatter pass, atomically under the same engine lock every other
    # dispatch serializes through.  Rare by design (one reserve amortizes
    # over a whole client-side budget), so these run synchronously —
    # dispatch + fetch in one call.

    def lease_reserve(self, algo: str, slots, limiter_ids, requested,
                      now_ms: int):
        """Atomically grant up to ``requested[i]`` permits against each
        slot's live counters.  Returns ``(granted i64[n], ws i64[n])``
        where ``ws`` is the window the charge landed in (sliding window;
        zeros for the token bucket) — a later :meth:`lease_credit` must
        present it."""
        from ratelimiter_tpu.ops import lease as lease_ops

        n = len(slots)
        size = _bucket_size(n)
        self._mark(algo, np.asarray(slots))
        step = lease_ops.RESERVE_STEPS[algo]
        slots_p = _pad_i32(np.asarray(slots, dtype=np.int32), size, -1)
        lids_p = _pad_i32(np.asarray(limiter_ids, dtype=np.int32), size, 0)
        req_p = _pad_i64(np.asarray(requested, dtype=np.int64), size, 0)
        with self._lock:
            if algo == "sw":
                self.sw_packed, granted, ws = step(
                    self.sw_packed, self.table.device_arrays,
                    slots_p, lids_p, req_p, jnp.int64(now_ms))
            else:
                self.tb_packed, granted, ws = step(
                    self.tb_packed, self.table.device_arrays,
                    slots_p, lids_p, req_p, jnp.int64(now_ms))
        return np.asarray(granted)[:n], np.asarray(ws)[:n]

    def lease_credit(self, algo: str, slots, limiter_ids, credit, grant_ws,
                     now_ms: int) -> np.ndarray:
        """Return unused reserved permits (lease renewal/release).
        ``grant_ws`` is the per-lane window stamp :meth:`lease_reserve`
        returned (sliding window: a rolled window drops the credit — the
        charge already ages out with the window).  Returns the permits
        actually credited per lane."""
        from ratelimiter_tpu.ops import lease as lease_ops

        n = len(slots)
        size = _bucket_size(n)
        self._mark(algo, np.asarray(slots))
        step = lease_ops.CREDIT_STEPS[algo]
        slots_p = _pad_i32(np.asarray(slots, dtype=np.int32), size, -1)
        lids_p = _pad_i32(np.asarray(limiter_ids, dtype=np.int32), size, 0)
        cr_p = _pad_i64(np.asarray(credit, dtype=np.int64), size, 0)
        ws_p = _pad_i64(np.asarray(grant_ws, dtype=np.int64), size, 0)
        with self._lock:
            if algo == "sw":
                self.sw_packed, credited = step(
                    self.sw_packed, self.table.device_arrays,
                    slots_p, lids_p, cr_p, ws_p, jnp.int64(now_ms))
            else:
                self.tb_packed, credited = step(
                    self.tb_packed, self.table.device_arrays,
                    slots_p, lids_p, cr_p, ws_p, jnp.int64(now_ms))
        return np.asarray(credited)[:n]

    # -- read-only ------------------------------------------------------------
    def sw_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        n = len(slots)
        size = _bucket_size(n)
        with self._lock:
            out = self._sw_peek(
                self.sw_packed,
                self.table.device_arrays,
                _pad_i32(np.asarray(slots, dtype=np.int32), size, 0),
                _pad_i32(np.asarray(limiter_ids, dtype=np.int32), size, 0),
                jnp.int64(now_ms),
            )
        return np.asarray(out)[:n]

    def tb_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        n = len(slots)
        size = _bucket_size(n)
        with self._lock:
            out = self._tb_peek(
                self.tb_packed,
                self.table.device_arrays,
                _pad_i32(np.asarray(slots, dtype=np.int32), size, 0),
                _pad_i32(np.asarray(limiter_ids, dtype=np.int32), size, 0),
                jnp.int64(now_ms),
            )
        return np.asarray(out)[:n]

    # -- reset ----------------------------------------------------------------
    def sw_clear(self, slots: Sequence[int]) -> None:
        self._mark("sw", slots)
        size = _bucket_size(max(len(slots), 1))
        with self._lock:
            self.sw_packed = self._sw_reset(
                self.sw_packed, _pad_i32(np.asarray(slots, dtype=np.int32), size, -1))

    def tb_clear(self, slots: Sequence[int]) -> None:
        self._mark("tb", slots)
        size = _bucket_size(max(len(slots), 1))
        with self._lock:
            self.tb_packed = self._tb_reset(
                self.tb_packed, _pad_i32(np.asarray(slots, dtype=np.int32), size, -1))

    # -- raw packed-row access (export/import rebalance; engine/checkpoint.py)
    def read_rows(self, algo: str, slots) -> np.ndarray:
        """Packed state rows for the given slots (host numpy i32[n, lanes])."""
        with self._lock:
            packed = self.sw_packed if algo == "sw" else self.tb_packed
            return np.asarray(packed[jnp.asarray(
                np.ascontiguousarray(slots, dtype=np.int32))])

    def write_rows(self, algo: str, slots, rows: np.ndarray) -> None:
        """Overwrite packed state rows (import side of a rebalance)."""
        self._mark(algo, slots)
        with self._lock:
            idx = jnp.asarray(np.ascontiguousarray(slots, dtype=np.int32))
            vals = jnp.asarray(np.ascontiguousarray(rows, dtype=np.int32))
            if algo == "sw":
                self.sw_packed = self.sw_packed.at[idx].set(vals)
            else:
                self.tb_packed = self.tb_packed.at[idx].set(vals)

    def warm_micro_shapes(self, algos=("sw", "tb"),
                          sizes=(32, 64, 128)) -> None:
        """Pre-compile the small-shape micro steps so an interactive
        deployment's first micro-batch doesn't pay an XLA compile inside
        a caller's latency budget.  Warms the legacy list path at the
        _MICRO_FLOOR bucket AND the staged combined path at every size in
        ``sizes`` — dispatched twice per size from two distinct staging
        buffers, mirroring the batcher's double-buffered assembly, so the
        steady-state micro loop never compiles (asserted by
        bench/device_only.py).  Warm batches are all padding lanes
        (slot -1): every kernel masks them out and the journal filters
        them, so no state or replication traffic is touched.

        Sizes that are not dispatch buckets are ROUNDED UP to their
        bucket (pow2 ladder from the 32-lane floor) and deduped: a warm
        dispatch whose n is below its buffer width would slice down and
        silently compile a lane count the batcher never produces —
        warming the wrong executable while the real buckets still
        compile inside the first request's latency budget."""
        sizes = sorted({_bucket_size(max(int(n), 1)) for n in sizes})
        for algo in algos:
            for size in sizes:
                # Both in-flight buffers of the double-buffered assembly:
                # identical shape (the compile cache is keyed on it), but
                # dispatching from two distinct host arrays proves the
                # staged path is buffer-identity-agnostic at warm time.
                for _ in range(2):
                    staged = np.empty((MICRO_STAGE_ROWS, size),
                                      dtype=np.int64)
                    staged[0] = -1
                    staged[1] = 0
                    staged[2] = 1
                    staged[3, 0] = 0
                    # n == size so the dispatch buckets AT this size
                    # (a smaller n would slice down to the floor bucket
                    # and warm only that one shape).
                    self.micro_staged_drain(
                        algo,
                        self.micro_staged_dispatch(algo, staged, size),
                        size)

    def block_until_ready(self) -> None:
        with self._lock:
            jax.block_until_ready((self.sw_packed, self.tb_packed))

    def make_slot_index(self):
        # Prefer the C++ index (tens of M ops/s); identical semantics to the
        # Python SlotIndex (tests/test_native_index.py proves equivalence).
        from ratelimiter_tpu.engine.native_index import (
            NativeSlotIndex,
            native_available,
        )

        if native_available():
            return NativeSlotIndex(self.num_slots)
        from ratelimiter_tpu.engine.slots import SlotIndex

        return SlotIndex(self.num_slots)
