"""Device-resident state: slot arrays + the multi-tenant limiter table.

The Redis keyspace of the reference (one string counter per window bucket,
one hash per token bucket — ARCHITECTURE.md memory model) becomes
struct-of-arrays state in HBM, indexed by *slot id*.  The host-side
``SlotIndex`` (engine/slots.py) owns the key -> slot assignment; device code
never sees string keys.

A slot whose state is all zeros behaves exactly like an absent Redis key:
the sliding-window rollover clears buckets whose window has passed, and a
zero token-bucket deadline reads as expired (lazy init to full capacity).
This makes slot allocation free — freshly allocated and reset slots are
simply zeroed.

``LimiterTable`` holds per-tenant policy (one row per named limiter config,
the analog of the three Spring beans in config/RateLimiterConfig.java:46-95,
scaled to 100K+ tenants): decisions gather their policy row by limiter id,
so one device batch can mix tenants freely.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("engine.state")


class SWState(NamedTuple):
    """Sliding-window per-slot state (two window buckets + PEXPIRE deadlines).

    win_start — window-start timestamp the curr bucket belongs to
    curr      — current-window bucket counter
    curr_dl   — curr bucket's expiry deadline (last increment + window)
    prev      — previous-window bucket counter
    prev_dl   — prev bucket's expiry deadline
    """

    win_start: jax.Array  # i64[S]
    curr: jax.Array       # i64[S]
    curr_dl: jax.Array    # i64[S]
    prev: jax.Array       # i64[S]
    prev_dl: jax.Array    # i64[S]


class TBState(NamedTuple):
    """Token-bucket per-slot state (the Redis hash {tokens, last_refill}).

    The PEXPIRE deadline is not stored: it is always ``last_refill + 2*window``
    (both are written together on every allow), so expiry is recomputed from
    ``last_refill`` and the limiter's ttl2 — one fewer i64 lane through the
    gather/scatter hot path.  ``last_refill == 0`` is the absent-key sentinel
    (a fresh slot reads as an expired bucket, i.e. lazy init to full capacity,
    exactly like a missing Redis key)."""

    tokens_fp: jax.Array    # i64[S]
    last_refill: jax.Array  # i64[S]


class TableArrays(NamedTuple):
    """Per-limiter policy rows (gathered by limiter id on device)."""

    max_permits: jax.Array  # i64[T]
    window_ms: jax.Array    # i64[T]
    cap_fp: jax.Array       # i64[T] (token bucket)
    rate_fp: jax.Array      # i64[T] (token bucket)
    ttl2_ms: jax.Array      # i64[T] (2 * window — token bucket TTL)


def _zeros(num_slots: int) -> jax.Array:
    return jnp.zeros((num_slots,), dtype=jnp.int64)


def make_sw_state(num_slots: int) -> SWState:
    # Distinct buffers per field: the step donates the whole pytree, and
    # aliased buffers cannot be donated twice.
    return SWState(*(_zeros(num_slots) for _ in range(5)))


def make_tb_state(num_slots: int) -> TBState:
    return TBState(*(_zeros(num_slots) for _ in range(2)))


class LimiterTable:
    """Host-side registry of limiter configs with a device mirror.

    Row 0 is a sentinel (window 1 ms, zero permits) so padded/clamped lookups
    are always in-range and never divide by zero.
    """

    SENTINEL_ROWS = 1

    def __init__(self, capacity: int = 64):
        self._lock = threading.Lock()
        self._capacity = max(int(capacity), 2)
        self._n = self.SENTINEL_ROWS
        self._max_permits = np.zeros(self._capacity, dtype=np.int64)
        self._window_ms = np.ones(self._capacity, dtype=np.int64)
        self._cap_fp = np.zeros(self._capacity, dtype=np.int64)
        self._rate_fp = np.zeros(self._capacity, dtype=np.int64)
        self._ttl2_ms = np.ones(self._capacity, dtype=np.int64)
        self._device: TableArrays | None = None
        # Policy generation (control/, ARCHITECTURE §15): a monotonic
        # counter bumped by every live set_policy, plus the generation
        # each row last changed at.  Decisions are evaluated against the
        # table contents at dispatch time; the generation is the
        # fence_info-style metadata that lets the oracle, the hybrid
        # serving cache, degraded-mode seeds and replicated standbys all
        # agree on WHICH policy admitted a decision.
        self._generation = 0
        self._row_gen = np.zeros(self._capacity, dtype=np.int64)
        self.implicit_grows = 0

    def register(self, config: RateLimitConfig) -> int:
        """Add a policy row; returns its limiter id.

        Safe during traffic: the device mirror is updated row-wise (five
        scalar device updates) instead of being invalidated, so concurrent
        dispatches never trigger a full-table re-upload mid-flight.  Only
        a capacity grow (table shape change — which also recompiles the
        step) rebuilds the mirror from host arrays.
        """
        config.validate()
        with self._lock:
            if self._n == self._capacity:
                self._grow()
                self._device = None  # shape changed: rebuild lazily
            lid = self._n
            self._n += 1
            self._max_permits[lid] = config.max_permits
            self._window_ms[lid] = config.window_ms
            self._cap_fp[lid] = config.max_permits_fp
            self._rate_fp[lid] = config.refill_rate_fp
            self._ttl2_ms[lid] = 2 * config.window_ms
            if self._device is not None:
                d = self._device
                self._device = TableArrays(
                    max_permits=d.max_permits.at[lid].set(config.max_permits),
                    window_ms=d.window_ms.at[lid].set(config.window_ms),
                    cap_fp=d.cap_fp.at[lid].set(config.max_permits_fp),
                    rate_fp=d.rate_fp.at[lid].set(config.refill_rate_fp),
                    ttl2_ms=d.ttl2_ms.at[lid].set(2 * config.window_ms),
                )
            return lid

    def set_policy(self, lid: int, config: RateLimitConfig,
                   generation: Optional[int] = None) -> int:
        """Live-update one registered policy row; returns the new policy
        generation.

        ``generation`` installs an externally-dictated stamp instead of
        bumping the local counter — replication uses it so a standby
        replaying the primary's policy updates reports the PRIMARY's
        generation numbers, not its own replay count.

        Only the RATES move (max_permits / cap_fp / rate_fp): the window
        — and with it ttl2 and every window-derived shape the kernels
        bake in (bucket rollover, lease TTL clamps, relay word layout
        via max_permits_registered is rate-derived and still checked by
        callers) — is immutable, so a policy update is three scalar
        device updates exactly like :meth:`register`'s row writes, never
        a table rebuild or a step recompile.  Concurrent dispatches see
        either the old row or the new one atomically (the mirror swap
        happens under the table lock the dispatch-side ``device_arrays``
        read takes).
        """
        config.validate()
        with self._lock:
            i = int(lid)
            if not (self.SENTINEL_ROWS <= i < self._n):
                raise KeyError(f"no limiter registered under lid={lid}")
            if config.window_ms != int(self._window_ms[i]):
                raise ValueError(
                    f"set_policy cannot change the window (lid={lid}: "
                    f"{self._window_ms[i]} ms -> {config.window_ms} ms); "
                    "the window is part of the state shape — register a "
                    "new limiter instead")
            self._max_permits[i] = config.max_permits
            self._cap_fp[i] = config.max_permits_fp
            self._rate_fp[i] = config.refill_rate_fp
            if generation is None:
                self._generation += 1
                self._row_gen[i] = self._generation
            else:
                self._generation = max(self._generation, int(generation))
                self._row_gen[i] = int(generation)
            if self._device is not None:
                d = self._device
                self._device = TableArrays(
                    max_permits=d.max_permits.at[i].set(config.max_permits),
                    window_ms=d.window_ms,
                    cap_fp=d.cap_fp.at[i].set(config.max_permits_fp),
                    rate_fp=d.rate_fp.at[i].set(config.refill_rate_fp),
                    ttl2_ms=d.ttl2_ms,
                )
            return self._generation

    @property
    def generation(self) -> int:
        """Monotonic policy generation (0 until the first set_policy)."""
        with self._lock:
            return self._generation

    def row_generation(self, lid: int) -> int:
        """Generation the row last changed at (0 = as registered)."""
        with self._lock:
            return int(self._row_gen[int(lid)])

    def bump_generation(self, generation: int) -> None:
        """Adopt an externally-dictated generation floor (replication:
        a standby applying a primary's limiter dump must never report
        an older generation than the policies it now serves)."""
        with self._lock:
            if int(generation) > self._generation:
                self._generation = int(generation)

    def _grow(self) -> None:
        new_cap = self._capacity * 2
        for name in ("_max_permits", "_window_ms", "_cap_fp", "_rate_fp",
                     "_ttl2_ms", "_row_gen"):
            old = getattr(self, name)
            fresh = np.ones(new_cap, dtype=np.int64) if name in ("_window_ms", "_ttl2_ms") \
                else np.zeros(new_cap, dtype=np.int64)
            fresh[: self._capacity] = old
            setattr(self, name, fresh)
        # An implicit grow is decision-safe (the mirror rebuilds under
        # the lock and the new lid is unused until register returns) but
        # NOT free: the table shape change silently recompiles every
        # step signature and re-uploads the whole mirror mid-traffic.
        # Pre-size via ratelimiter.table.capacity instead.
        self.implicit_grows += 1
        _log.warning(
            "limiter table grew %d -> %d under traffic: the device step "
            "recompiles for the new table shape; pre-size with "
            "ratelimiter.table.capacity to avoid the stall",
            self._capacity, new_cap)
        self._capacity = new_cap

    @property
    def device_arrays(self) -> TableArrays:
        with self._lock:
            if self._device is None:
                self._device = TableArrays(
                    max_permits=jnp.asarray(self._max_permits),
                    window_ms=jnp.asarray(self._window_ms),
                    cap_fp=jnp.asarray(self._cap_fp),
                    rate_fp=jnp.asarray(self._rate_fp),
                    ttl2_ms=jnp.asarray(self._ttl2_ms),
                )
            return self._device

    def __len__(self) -> int:
        return self._n

    def host_policy(self, lid: int):
        """Host-side policy row ``(max_permits, window_ms, cap_fp,
        rate_fp, ttl2_ms)`` for one limiter id — the lease host mirrors
        (ops/lease.py) restate the device arithmetic over host rows and
        read the policy here instead of fetching device arrays."""
        with self._lock:
            i = int(lid)
            return (int(self._max_permits[i]), int(self._window_ms[i]),
                    int(self._cap_fp[i]), int(self._rate_fp[i]),
                    int(self._ttl2_ms[i]))

    @property
    def max_permits_registered(self) -> int:
        """Largest max_permits across registered policies (0 if none) —
        the relay word layout's rank-clamp ceiling must exceed this."""
        with self._lock:
            return int(self._max_permits[:self._n].max(initial=0))


class SlotJournal:
    """Host-side dirty-slot journal feeding the replication log.

    Every ``DeviceEngine`` mutation path calls :meth:`mark` with the
    host-side slot ids of the rows it is about to touch — a boolean
    scatter into a per-algo mask, O(batch) and off the device critical
    path (the dispatch itself has not been enqueued yet, so no device
    work waits on the mark).  ``drain`` atomically swaps the masks out
    and returns the coalesced dirty slot set per algo — the delta a
    replication epoch ships (replication/log.py).

    Marks are a superset of actual mutations (a denied request's slot is
    marked even though the row may be unchanged); shipping an unchanged
    row is idempotent, so over-marking costs bytes, never correctness.
    Out-of-range ids (batch padding -1, relay padding words) are
    filtered here so callers can mark their raw lane arrays.
    """

    __slots__ = ("num_slots", "_lock", "_dirty", "_all", "_oldest_ns",
                 "marks")

    def __init__(self, num_slots: int):
        self.num_slots = int(num_slots)
        self._lock = threading.Lock()
        self._dirty: Dict[str, np.ndarray] = {
            "sw": np.zeros(self.num_slots, dtype=bool),
            "tb": np.zeros(self.num_slots, dtype=bool),
        }
        self._all = {"sw": False, "tb": False}
        # Wall time of the first mark since the last drain — the age of
        # the oldest unreplicated mutation, i.e. the replication lag.
        self._oldest_ns: Optional[int] = None
        self.marks = 0

    def mark(self, algo: str, slots) -> None:
        a = np.asarray(slots).reshape(-1).astype(np.int64, copy=False)
        if not len(a):
            return
        sel = a[(a >= 0) & (a < self.num_slots)]
        with self._lock:
            self.marks += 1
            if len(sel):
                self._dirty[algo][sel] = True
                if self._oldest_ns is None:
                    self._oldest_ns = time.time_ns()

    def mark_words(self, algo: str, words, rank_bits: int) -> None:
        """Mark from relay uwords (slot in the high bits; padding words
        decode past num_slots and are filtered by :meth:`mark`)."""
        self.mark(algo, np.asarray(words).astype(np.uint64)
                  >> np.uint64(rank_bits + 1))

    def mark_matrix(self, algo: str, mat, slots_per_shard: int) -> None:
        """Mark from a sharded (n_shards, ...) LOCAL-slot matrix: local id
        + shard row offset = global slot (negative lanes are padding)."""
        m = np.asarray(mat, dtype=np.int64)
        m = m.reshape(m.shape[0], -1)
        base = (np.arange(m.shape[0], dtype=np.int64)
                * slots_per_shard)[:, None]
        self.mark(algo, np.where(m >= 0, m + base, -1))

    def mark_words_matrix(self, algo: str, wmat, rank_bits: int,
                          slots_per_shard: int) -> None:
        """Sharded relay words: per-shard LOCAL slots in the high bits
        (padding decodes past slots_per_shard and is dropped)."""
        w = np.asarray(wmat).astype(np.uint64)
        w = w.reshape(w.shape[0], -1)
        loc = (w >> np.uint64(rank_bits + 1)).astype(np.int64)
        base = (np.arange(w.shape[0], dtype=np.int64)
                * slots_per_shard)[:, None]
        self.mark(algo, np.where(loc < slots_per_shard, loc + base, -1))

    def mark_all(self, algo: str) -> None:
        """Mark every slot dirty (bulk restores/imports, or a full-state
        catch-up frame after a ship failure or a late-joining standby)."""
        with self._lock:
            self._all[algo] = True
            if self._oldest_ns is None:
                self._oldest_ns = time.time_ns()

    def drain(self) -> Tuple[Dict[str, np.ndarray], Optional[int], bool]:
        """Swap out and return ``(dirty slot ids per algo, wall ns of the
        oldest pending mark, whether any algo was marked-all)``."""
        with self._lock:
            out: Dict[str, np.ndarray] = {}
            was_all = False
            for algo, mask in self._dirty.items():
                if self._all[algo]:
                    out[algo] = np.arange(self.num_slots, dtype=np.int64)
                    self._all[algo] = False
                    mask[:] = False
                    was_all = True
                else:
                    ids = np.nonzero(mask)[0]
                    if len(ids):
                        out[algo] = ids
                        mask[ids] = False
            oldest = self._oldest_ns
            self._oldest_ns = None
            return out, oldest, was_all

    def pending(self) -> int:
        """Total dirty slots across algos (cheap visibility for tests
        and the lag gauge)."""
        with self._lock:
            return sum(self.num_slots if self._all[a] else int(m.sum())
                       for a, m in self._dirty.items())


class DeviceSlotJournal:
    """Device-resident dirty-slot journal: the touched-slot bitmap lives
    in device memory and is updated by a tiny jitted scatter riding each
    dispatch's already-uploaded lane arrays.

    The host ``SlotJournal`` pays an O(batch) numpy pass on the decision
    path per dispatch (bounds filter + boolean scatter, plus a u64 shift
    for relay words).  This journal replaces that with one asynchronous
    device op: the engine hands over the SAME device array the dispatch
    uploads (relay words, slot lanes, sharded local-slot matrices), so
    the mark costs one dispatch-call overhead and zero extra host->device
    bytes — the delta extraction is amortized into the dispatch that
    already runs.  ``drain`` fetches the bitmap off the decision path
    (the Replicator thread) and swaps in fresh zeros.

    Same contract as ``SlotJournal``: marks are a superset of mutations
    (over-marking ships idempotent truth), out-of-range ids (padding -1,
    relay padding words) are masked out on device, and marks racing a
    drain land in the next epoch (the bitmap reference swap is under the
    journal lock).  Which journal serves is a measured election
    (replication/log.py) with this one preferred; the host journal is
    the permanent fallback.
    """

    device = True  # engine hooks pass device-resident arrays when they can

    __slots__ = ("num_slots", "_lock", "_bits", "_all", "_oldest_ns",
                 "marks", "_fns")

    def __init__(self, num_slots: int):
        self.num_slots = int(num_slots)
        self._lock = threading.Lock()
        self._bits: Dict[str, jax.Array] = {
            "sw": jnp.zeros(self.num_slots, dtype=jnp.bool_),
            "tb": jnp.zeros(self.num_slots, dtype=jnp.bool_),
        }
        self._all = {"sw": False, "tb": False}
        self._oldest_ns: Optional[int] = None
        self.marks = 0
        self._fns: Dict[tuple, object] = {}

    # -- jitted mark kernels (cached per static geometry) ---------------------
    def _fn(self, kind: str, **static):
        key = (kind,) + tuple(sorted(static.items()))
        fn = self._fns.get(key)
        if fn is not None:
            return fn
        S = self.num_slots
        if kind == "slots":
            def mark(bits, arr):
                s = arr.reshape(-1).astype(jnp.int32)
                ok = (s >= 0) & (s < S)
                return bits.at[jnp.clip(s, 0, S - 1)].max(ok)
        elif kind == "words":
            rb = static["rank_bits"]

            def mark(bits, arr):
                s = (arr.reshape(-1) >> jnp.uint32(rb + 1)).astype(jnp.int32)
                ok = s < S  # padding 0xFFFFFFFF decodes past num_slots
                return bits.at[jnp.clip(s, 0, S - 1)].max(ok)
        elif kind == "matrix":
            sps = static["sps"]

            def mark(bits, arr):
                m = arr.reshape(arr.shape[0], -1).astype(jnp.int32)
                base = (jnp.arange(m.shape[0], dtype=jnp.int32)
                        * sps)[:, None]
                s = jnp.where(m >= 0, m + base, -1).reshape(-1)
                ok = (s >= 0) & (s < S)
                return bits.at[jnp.clip(s, 0, S - 1)].max(ok)
        elif kind == "words_matrix":
            rb, sps = static["rank_bits"], static["sps"]

            def mark(bits, arr):
                w = arr.reshape(arr.shape[0], -1)
                loc = (w >> jnp.uint32(rb + 1)).astype(jnp.int32)
                base = (jnp.arange(w.shape[0], dtype=jnp.int32)
                        * sps)[:, None]
                ok = loc < sps
                s = jnp.clip(jnp.where(ok, loc + base, 0),
                             0, S - 1).reshape(-1)
                return bits.at[s].max(ok.reshape(-1))
        else:  # pragma: no cover - internal misuse
            raise ValueError(kind)
        fn = jax.jit(mark, donate_argnums=0)
        self._fns[key] = fn
        return fn

    @staticmethod
    def _as_device(arr):
        if isinstance(arr, jax.Array):
            return arr
        a = np.asarray(arr)
        return None if a.size == 0 else jnp.asarray(a)

    def _apply(self, algo: str, fn, arr) -> None:
        if arr is None:
            return
        with self._lock:
            self.marks += 1
            self._bits[algo] = fn(self._bits[algo], arr)
            if self._oldest_ns is None:
                self._oldest_ns = time.time_ns()

    # -- mark surface (superset of SlotJournal's) -----------------------------
    def mark(self, algo: str, slots) -> None:
        self._apply(algo, self._fn("slots"), self._as_device(slots))

    def mark_words(self, algo: str, words, rank_bits: int) -> None:
        self._apply(algo, self._fn("words", rank_bits=int(rank_bits)),
                    self._as_device(words))

    def mark_matrix(self, algo: str, mat, slots_per_shard: int) -> None:
        self._apply(algo, self._fn("matrix", sps=int(slots_per_shard)),
                    self._as_device(mat))

    def mark_words_matrix(self, algo: str, wmat, rank_bits: int,
                          slots_per_shard: int) -> None:
        self._apply(algo, self._fn("words_matrix", rank_bits=int(rank_bits),
                                   sps=int(slots_per_shard)),
                    self._as_device(wmat))

    def mark_all(self, algo: str) -> None:
        with self._lock:
            self._all[algo] = True
            if self._oldest_ns is None:
                self._oldest_ns = time.time_ns()

    # -- drain (off the decision path) ----------------------------------------
    def drain(self) -> Tuple[Dict[str, np.ndarray], Optional[int], bool]:
        """Fetch + swap the bitmaps; same return contract as
        ``SlotJournal.drain``.  The fetch blocks on any in-flight mark
        ops for the swapped buffer — marks dispatched after the swap
        land in the NEXT epoch."""
        with self._lock:
            out: Dict[str, np.ndarray] = {}
            was_all = False
            for algo in ("sw", "tb"):
                if self._all[algo]:
                    out[algo] = np.arange(self.num_slots, dtype=np.int64)
                    self._all[algo] = False
                    self._bits[algo] = jnp.zeros(self.num_slots,
                                                 dtype=jnp.bool_)
                    was_all = True
                else:
                    host = np.asarray(self._bits[algo])
                    ids = np.nonzero(host)[0].astype(np.int64)
                    if len(ids):
                        out[algo] = ids
                        self._bits[algo] = jnp.zeros(self.num_slots,
                                                     dtype=jnp.bool_)
            oldest = self._oldest_ns
            self._oldest_ns = None
            return out, oldest, was_all

    def pending(self) -> int:
        with self._lock:
            return sum(self.num_slots if self._all[a]
                       else int(jnp.count_nonzero(b))
                       for a, b in self._bits.items())
