"""Adaptive flush control for the micro-batcher (r11).

The fixed size-or-deadline flush trigger (max_batch / max_delay_ms) makes
one latency promise for every load shape: a lightly-loaded deployment
waits the full deadline for batches the device could have served three
times over, and a saturated one flushes tiny batches faster than the
device absorbs them, paying per-dispatch assembly cost for no extra
throughput.  This controller trades the two against the **measured**
device-step time (the `device` stage the PR 7 lifecycle histograms
expose, fed here per drained batch):

- the applied flush deadline tracks ``step_ewma * headroom`` — there is
  no point flushing faster than the device can start the next step, and
  no reason to wait longer than one service interval;
- the size trigger tracks recent batch volume, so a burst flushes as
  soon as it reaches what one device step has been absorbing instead of
  waiting out the deadline.

Both outputs are **hard-clamped** to configured [floor, cap] bounds, and
samples are clamped to a multiple of the current estimate before they
enter the EWMA — a pathological reading (a 90 s first-compile stall, a
wedged fetch) nudges the estimate instead of pinning the deadline at the
cap for thousands of batches.  Applied values only move after the
proposal has pointed the same direction for ``hysteresis_steps``
consecutive observations (the flap-damping idiom of
``replication/orchestrator.py``: consecutive evidence, then act —
a single noisy sample changes nothing), so the controller converges
instead of oscillating.

Deterministic by construction: no wall clock — ``observe()`` consumes
measurements, counters implement the hysteresis — so tests drive it with
a simulated ramp (tests/test_microbatch.py).
"""

from __future__ import annotations

import threading


def _clamp(x: float, lo: float, hi: float) -> float:
    return max(lo, min(hi, x))


class AdaptiveFlushController:
    """Feeds the micro-batcher's flush deadline and size trigger from the
    measured device-step time.  Thread-safe: ``observe`` runs on drain
    threads, the getters on the flusher."""

    def __init__(
        self,
        base_delay_ms: float = 0.5,
        floor_ms: float = 0.05,
        cap_ms: float | None = None,
        size_floor: int = 32,
        size_cap: int = 8192,
        headroom: float = 1.0,
        alpha: float = 0.25,
        hysteresis_steps: int = 3,
        hysteresis_pct: float = 0.2,
        sample_clamp: float = 4.0,
        meter_registry=None,
    ):
        # cap defaults to the configured deadline: max_delay_ms is the
        # batcher's latency promise, so adaptation only ever SHRINKS the
        # wait below it, never extends it.
        cap_ms = base_delay_ms if cap_ms is None else cap_ms
        if floor_ms <= 0 or cap_ms < floor_ms:
            raise ValueError("need 0 < floor_ms <= cap_ms")
        if size_floor < 1 or size_cap < size_floor:
            raise ValueError("need 1 <= size_floor <= size_cap")
        self.floor_s = floor_ms / 1000.0
        self.cap_s = cap_ms / 1000.0
        self.size_floor = int(size_floor)
        self.size_cap = int(size_cap)
        self.headroom = float(headroom)
        self.alpha = float(alpha)
        self.hysteresis_steps = max(int(hysteresis_steps), 1)
        self.hysteresis_pct = float(hysteresis_pct)
        self.sample_clamp = float(sample_clamp)
        self._lock = threading.Lock()
        self._step_ewma: float | None = None
        self._batch_ewma: float | None = None
        self._applied_delay_s = _clamp(base_delay_ms / 1000.0,
                                       self.floor_s, self.cap_s)
        self._applied_size = self.size_cap
        self._delay_streak = 0   # signed consecutive-direction count
        self._size_streak = 0
        self.adjustments = 0     # applied-value changes (observability)
        self.clamped_samples = 0  # readings cut by sample_clamp
        self._delay_gauge = (
            meter_registry.gauge(
                "ratelimiter.microbatch.flush_delay_ms",
                "Adaptive flush controller: applied micro-batch flush "
                "deadline (ms)")
            if meter_registry is not None else None)
        self._size_gauge = (
            meter_registry.gauge(
                "ratelimiter.microbatch.size_trigger",
                "Adaptive flush controller: applied micro-batch size "
                "trigger (requests)")
            if meter_registry is not None else None)
        if self._delay_gauge is not None:
            self._delay_gauge.set(self._applied_delay_s * 1000.0)
        if self._size_gauge is not None:
            self._size_gauge.set(self._applied_size)

    # -- feedback (drain threads) ---------------------------------------------
    def observe(self, step_s: float, batch_n: int) -> None:
        """One drained batch: its device-stage seconds and lane count."""
        if step_s < 0:
            return
        with self._lock:
            if self._step_ewma is not None:
                ceil = self.sample_clamp * max(self._step_ewma, self.floor_s)
                if step_s > ceil:
                    step_s = ceil
                    self.clamped_samples += 1
                self._step_ewma += self.alpha * (step_s - self._step_ewma)
                self._batch_ewma += self.alpha * (batch_n - self._batch_ewma)
            else:
                self._step_ewma = min(step_s, self.cap_s * self.sample_clamp)
                self._batch_ewma = float(batch_n)
            self._update_delay()
            self._update_size()

    def _hysteresis(self, proposed: float, applied: float,
                    streak: int) -> tuple:
        """(new_streak, apply?): require hysteresis_steps consecutive
        same-direction proposals deviating > hysteresis_pct."""
        if applied <= 0:
            return 0, True
        dev = (proposed - applied) / applied
        if abs(dev) <= self.hysteresis_pct:
            return 0, False
        step = 1 if dev > 0 else -1
        streak = streak + step if streak * step > 0 else step
        return streak, abs(streak) >= self.hysteresis_steps

    def _update_delay(self) -> None:
        proposed = _clamp(self._step_ewma * self.headroom,
                          self.floor_s, self.cap_s)
        self._delay_streak, apply = self._hysteresis(
            proposed, self._applied_delay_s, self._delay_streak)
        if apply:
            self._applied_delay_s = proposed
            self._delay_streak = 0
            self.adjustments += 1
            if self._delay_gauge is not None:
                self._delay_gauge.set(proposed * 1000.0)

    def _update_size(self) -> None:
        # Flush a burst once it reaches ~2x what one device step has been
        # absorbing: past that point more coalescing buys bigger steps,
        # not fewer, and the oldest request is already paying for it.
        proposed = _clamp(self._batch_ewma * 2.0,
                          self.size_floor, self.size_cap)
        self._size_streak, apply = self._hysteresis(
            proposed, float(self._applied_size), self._size_streak)
        if apply:
            self._applied_size = int(round(proposed))
            self._size_streak = 0
            self.adjustments += 1
            if self._size_gauge is not None:
                self._size_gauge.set(self._applied_size)

    # -- applied values (flusher) ---------------------------------------------
    def delay_s(self) -> float:
        return self._applied_delay_s

    def size_trigger(self) -> int:
        return self._applied_size

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "delay_ms": self._applied_delay_s * 1000.0,
                "size_trigger": self._applied_size,
                "step_ewma_ms": (self._step_ewma or 0.0) * 1000.0,
                "batch_ewma": self._batch_ewma or 0.0,
                "adjustments": self.adjustments,
                "clamped_samples": self.clamped_samples,
            }
