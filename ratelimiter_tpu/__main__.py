"""``python -m ratelimiter_tpu`` — run the HTTP demo service."""

from ratelimiter_tpu.service.app import main

if __name__ == "__main__":
    main()
