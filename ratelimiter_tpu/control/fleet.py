"""Fleet-true control plane: epoch-fenced controller leadership and
cross-host policy broadcast (ARCHITECTURE §15).

PR 15's adaptive controller actuates one process's storage and observes
one process's telemetry.  This module makes the SAME controller
fleet-true without changing a line of its loop: a
:class:`FleetControlPlane` quacks like the storage the controller
expects — ``_configs`` for ceilings, ``set_policy`` for actuation,
``table.generation`` / ``row_generation`` for stamps, ``telemetry`` for
observations — but every surface is backed by the cell's control RPC:

- **Observation**: ``telemetry.all_signals`` fans the ``signals`` op
  out to every member node and SUMS the per-lid UsageSignals, so the
  hierarchical global cap finally sees fleet load, not one host's
  slice.  ``staleness_ms`` is the worst member's staleness — and
  infinity for an unreachable member, which trips the controller's
  staleness freeze (stale signals must never justify a raise).
- **Actuation**: ``set_policy`` stamps a monotone generation and
  broadcasts the row to every member over the ``set_policy`` op.
  Per-node apply is idempotent (engine/checkpoint.py:
  ``apply_limiter_policies``) and rejects older generations, so
  retries and leader races converge instead of fighting.
- **Leadership**: the plane only actuates while it HOLDS the cell: a
  majority of member :class:`~ratelimiter_tpu.replication.control.
  ControllerSeat` grants at its fence epoch, renewed within
  ``ttl_ms`` on its OWN clock.  A member answering with a higher
  epoch, or a renewal round that cannot reach a majority before the
  TTL runs out, demotes the plane immediately — it then REFUSES to
  actuate (:class:`NotLeader`), mirroring the PR 14 serving-lease
  self-fence rule.  Two controllers can never both hold a majority at
  the same epoch, and a partitioned zombie's writes die at the seats
  (``stale_rejected``), which the partitioned-controller drill proves
  (storage/chaos.py:partitioned_controller_drill).

:class:`ControllerElection` is the re-election driver: attach it to a
NodeManager (``manager.attach(election)``) and leader death is detected
and repaired from the SAME tick that probes nodes — elect at
``max(observed epoch) + 1``, then anti-entropy every member to one
generation (``converge``), measured as ``ratelimiter.control.
converge_ms``.  A freshly promoted or re-seeded standby joins through
``note_join`` (fleet/autopilot.py calls it on hand-back) and is
converged to the leader's generation before it can serve a stale one.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.observability.usage import UsageSignals
from ratelimiter_tpu.replication.control import ControlError
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("control.fleet")

STALE_UNREACHABLE_MS = float("inf")


def _mono_ms() -> float:
    return time.monotonic() * 1000.0


class NotLeader(RuntimeError):
    """Raised by an actuation attempted while not holding the cell —
    the 'refuse to actuate' half of the self-demote rule."""


class _FleetTable:
    """The ``storage.table`` duck the controller reads stamps from."""

    def __init__(self, plane: "FleetControlPlane"):
        self._plane = plane

    @property
    def generation(self) -> int:
        return self._plane.generation

    def row_generation(self, lid: int) -> int:
        return self._plane.row_gens.get(int(lid), 0)


class _FleetSignals:
    """The ``storage.telemetry`` duck: fleet-summed UsageSignals.

    ``staleness_ms`` reports from the most recent observation round
    (one RPC fan-out per tick, not two): the worst member staleness,
    or infinity if any member was unreachable — which is exactly the
    verdict a partition deserves.
    """

    def __init__(self, plane: "FleetControlPlane"):
        self._plane = plane
        self._staleness = 0.0
        self._fetched = False

    def all_signals(self, window_ms: int = 10_000,
                    ) -> Dict[int, UsageSignals]:
        merged: Dict[int, List[float]] = {}
        worst = 0.0
        for name, member in self._plane.members_snapshot():
            try:
                resp = member.signals(int(window_ms))
            except (ControlError, RuntimeError, OSError):
                worst = STALE_UNREACHABLE_MS
                continue
            worst = max(worst, float(resp.get("staleness_ms", 0.0)))
            for lid_s, vals in resp.get("signals", {}).items():
                lid = int(lid_s)
                have = merged.get(lid)
                if have is None:
                    merged[lid] = list(vals)
                else:
                    # Sum counts and rates; keep the widest window.
                    have[1] = max(have[1], vals[1])
                    for i in range(2, len(vals)):
                        have[i] += vals[i]
        self._staleness = worst
        self._fetched = True
        return {lid: UsageSignals(lid, *vals[1:])
                for lid, vals in merged.items()}

    def staleness_ms(self) -> float:
        if not self._fetched:
            self.all_signals(1000)
        return self._staleness


class FleetControlPlane:
    """Storage-shaped facade the AdaptivePolicyController runs on,
    backed by a member set of control-RPC backends
    (:class:`~ratelimiter_tpu.replication.control` op tables, usually
    via :class:`~ratelimiter_tpu.replication.remote.RemoteBackend`).

    Parameters
    ----------
    node : this controller's identity (claims and writes carry it).
    members : ``{name: RemoteBackend-like}`` — the cell's nodes.
    limiters : optional ``{lid: (algo, RateLimitConfig)}`` operator
        ceilings.  Without it the plane adopts ceilings from the
        member rows it converges (a mid-flight successor then treats
        the CURRENT effective policies as ceilings — pass the
        registered specs when the provisioned ceilings matter).
    ttl_ms : controller-lease TTL; renewals must land a majority
        within it ON THIS PLANE'S OWN CLOCK or the plane self-demotes.
    """

    def __init__(self, node: str, members: Dict[str, object], *,
                 limiters: Optional[Dict[int, tuple]] = None,
                 ttl_ms: float = 3000.0,
                 clock_ms: Optional[Callable[[], float]] = None,
                 recorder=None):
        self.node = str(node)
        self._members: Dict[str, object] = dict(members)
        self.ttl_ms = float(ttl_ms)
        self._clock_ms = clock_ms or _mono_ms
        self._lock = threading.RLock()
        # -- leadership state --
        self.epoch = 0
        self.is_leader = False
        self.last_renew_ok_ms = 0.0
        self.elections = 0
        self.demotions = 0
        self.stale_refusals = 0
        self.demote_reason: Optional[str] = None
        # -- policy state (leader's view) --
        self.generation = 0
        self.last_broadcast_generation = 0
        self.row_gens: Dict[int, int] = {}
        self.rows: Dict[str, dict] = {}
        self.node_generations: Dict[str, int] = {}
        self._configs: Dict[int, tuple] = dict(limiters or {})
        self.table = _FleetTable(self)
        self.telemetry = _FleetSignals(self)
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()

    # -- membership ------------------------------------------------------------
    def members_snapshot(self) -> List[tuple]:
        with self._lock:
            return sorted(self._members.items())

    def add_member(self, name: str, backend) -> None:
        with self._lock:
            self._members[str(name)] = backend

    def remove_member(self, name: str) -> None:
        with self._lock:
            self._members.pop(str(name), None)
            self.node_generations.pop(str(name), None)

    def _majority(self) -> int:
        with self._lock:
            return len(self._members) // 2 + 1

    # -- leadership ------------------------------------------------------------
    def observed_epoch(self) -> int:
        """The highest controller epoch any reachable seat holds."""
        best = self.epoch
        for _, member in self.members_snapshot():
            try:
                info = member.policy_info()
            except (ControlError, RuntimeError, OSError):
                continue
            best = max(best, int(info.get("controller", {})
                                 .get("epoch", 0)))
        return best

    def elect(self) -> bool:
        """Claim the cell at ``max(observed epoch) + 1``.  Leadership
        requires a MAJORITY of seats; on success the plane immediately
        anti-entropies every member to one generation (converge)."""
        epoch = self.observed_epoch() + 1
        granted, refused_higher = self._claim_round(epoch)
        if granted < self._majority():
            if refused_higher:
                self.stale_refusals += 1
            return False
        with self._lock:
            self.epoch = epoch
            self.is_leader = True
            self.demote_reason = None
            self.last_renew_ok_ms = self._clock_ms()
            self.elections += 1
        self._recorder.record("control.leader_elected", node=self.node,
                              epoch=epoch)
        self.converge()
        return True

    def _claim_round(self, epoch: int) -> tuple:
        granted = 0
        refused_higher = False
        for _, member in self.members_snapshot():
            try:
                resp = member.controller_claim(self.node, epoch,
                                               self.ttl_ms)
            except (ControlError, RuntimeError, OSError):
                continue
            if resp.get("granted"):
                granted += 1
            elif int(resp.get("epoch", 0)) > epoch:
                refused_higher = True
        return granted, refused_higher

    def renew(self) -> bool:
        """Refresh the majority lease at the held epoch.  A seat
        answering with a HIGHER epoch means we were superseded —
        demote on the spot, exactly like a fenced storage."""
        if not self.is_leader:
            return False
        granted, refused_higher = self._claim_round(self.epoch)
        if refused_higher:
            self.stale_refusals += 1
            self._demote("superseded")
            return False
        if granted >= self._majority():
            with self._lock:
                self.last_renew_ok_ms = self._clock_ms()
            return True
        return False

    def self_check(self) -> bool:
        """The own-clock lease rule: a leader that has not landed a
        majority renewal within ``ttl_ms`` must assume a rival already
        claimed its seats and demote itself — it cannot tell the
        difference, and guessing wrong actuates stale policy."""
        if not self.is_leader:
            return False
        with self._lock:
            expired = (self._clock_ms()
                       - self.last_renew_ok_ms) > self.ttl_ms
        if expired:
            self._demote("lease_expired")
            return False
        return True

    def maintain(self) -> bool:
        """One leadership heartbeat: renew, then self-check."""
        if not self.is_leader:
            return False
        self.renew()
        return self.self_check()

    def _demote(self, reason: str) -> None:
        with self._lock:
            if not self.is_leader:
                return
            self.is_leader = False
            self.demotions += 1
            self.demote_reason = reason
        self._recorder.record("control.leader_demoted", node=self.node,
                              epoch=self.epoch, reason=reason)
        _log.warning("controller %s demoted at epoch %d (%s)",
                     self.node, self.epoch, reason)

    # -- policy broadcast ------------------------------------------------------
    def set_policy(self, lid: int, config: RateLimitConfig) -> int:
        """The controller's actuation surface: stamp the next monotone
        generation and broadcast the row to every member.  Refuses
        (:class:`NotLeader`) unless the plane currently holds the cell
        AND its own-clock lease is fresh."""
        if not self.self_check():
            reason = self.demote_reason or "never elected"
            raise NotLeader(
                f"controller {self.node} does not hold the cell "
                f"(epoch {self.epoch}, {reason}) — refusing to actuate")
        lid = int(lid)
        with self._lock:
            entry = self._configs.get(lid)
            if entry is None:
                raise KeyError(
                    f"no limiter known under lid={lid} — converge() "
                    f"adopts member rows, or pass limiters= ceilings")
            algo = entry[0]
            gen = self.generation + 1
            row = {str(lid): {"algo": algo,
                              "max_permits": int(config.max_permits),
                              "window_ms": int(config.window_ms),
                              "refill_rate": float(config.refill_rate),
                              "gen": gen}}
        self._broadcast(row)
        with self._lock:
            self.generation = gen
            self.last_broadcast_generation = gen
            self.row_gens[lid] = gen
            self.rows.update(row)
        return gen

    def _broadcast(self, rows: Dict[str, dict]) -> None:
        for name, member in self.members_snapshot():
            try:
                resp = member.set_policy_rows(rows, self.epoch,
                                              self.node)
            except (ControlError, RuntimeError, OSError):
                continue  # unreachable: converge() repairs it on join
            if resp.get("stale_epoch"):
                self.stale_refusals += 1
                self._demote("superseded")
                raise NotLeader(
                    f"controller {self.node} epoch {self.epoch} was "
                    f"superseded by epoch {resp.get('epoch')} mid-"
                    f"broadcast — demoted")
            if resp.get("applied") or resp.get("stale_generation"):
                self.node_generations[name] = int(
                    resp.get("generation", 0))

    def converge(self, member_names: Optional[List[str]] = None) -> int:
        """Anti-entropy: adopt the newest member rows as the leader's
        view and push them to every member (or just ``member_names``),
        so the whole cell lands on ONE generation.  Returns it."""
        newest_gen = -1
        newest_lids: Dict = {}
        for name, member in self.members_snapshot():
            try:
                info = member.policy_info()
            except (ControlError, RuntimeError, OSError):
                continue
            self.node_generations[name] = int(info.get("generation", 0))
            if int(info.get("generation", 0)) > newest_gen:
                newest_gen = int(info.get("generation", 0))
                newest_lids = dict(info.get("lids", {}))
        if newest_gen < 0:
            return self.generation
        rows = {}
        for lid_s, row in newest_lids.items():
            rows[lid_s] = {"algo": row["algo"],
                           "max_permits": int(row["max_permits"]),
                           "window_ms": int(row["window_ms"]),
                           "refill_rate": float(row["refill_rate"]),
                           "gen": int(row.get("generation", 0))}
            self.row_gens[int(lid_s)] = int(row.get("generation", 0))
            if int(lid_s) not in self._configs:
                self._configs[int(lid_s)] = (row["algo"], RateLimitConfig(
                    max_permits=int(row["max_permits"]),
                    window_ms=int(row["window_ms"]),
                    refill_rate=float(row["refill_rate"])))
        with self._lock:
            self.generation = max(self.generation, newest_gen)
            self.rows = dict(rows)
        targets = self.members_snapshot()
        if member_names is not None:
            wanted = {str(n) for n in member_names}
            targets = [(n, m) for n, m in targets if n in wanted]
        for name, member in targets:
            try:
                resp = member.set_policy_rows(rows, self.epoch, self.node)
            except (ControlError, RuntimeError, OSError):
                continue
            if not resp.get("stale_epoch"):
                self.node_generations[name] = int(
                    resp.get("generation", 0))
        return self.generation

    # -- introspection ---------------------------------------------------------
    def fleet_status(self) -> Dict:
        """The actuator payload: who leads, at what epoch, the last
        broadcast generation, and every node's applied generation +
        seat (refreshed over RPC; unreachable nodes report null)."""
        nodes: Dict[str, Optional[dict]] = {}
        stale_rejected = 0
        for name, member in self.members_snapshot():
            try:
                info = member.policy_info()
            except (ControlError, RuntimeError, OSError):
                nodes[name] = None
                continue
            seat = info.get("controller", {})
            stale_rejected += int(seat.get("stale_rejected", 0))
            gen = int(info.get("generation", 0))
            self.node_generations[name] = gen
            nodes[name] = {"generation": gen,
                           "epoch": int(seat.get("epoch", 0)),
                           "holder": seat.get("node"),
                           "stale_rejected": int(
                               seat.get("stale_rejected", 0))}
        with self._lock:
            return {
                "node": self.node,
                "is_leader": self.is_leader,
                "epoch": self.epoch,
                "generation": self.generation,
                "last_broadcast_generation": self.last_broadcast_generation,
                "elections": self.elections,
                "demotions": self.demotions,
                "demote_reason": self.demote_reason,
                "stale_refusals": self.stale_refusals,
                "stale_rejected": stale_rejected,
                "nodes": nodes,
            }

    def converged(self) -> bool:
        gens = {g for g in self.node_generations.values()}
        return len(gens) <= 1

    def close(self) -> None:
        for _, member in self.members_snapshot():
            try:
                member.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


class ControllerElection:
    """Leader-death repair, driven from the NodeManager tick.

    ``candidates`` is an ordered list of :class:`FleetControlPlane`
    instances (usually one per would-be controller host).  Each tick:
    the sitting leader heartbeats (renew + own-clock self-check); if
    NO candidate holds the cell, candidates are tried in order — a
    candidate that cannot reach a majority of seats (it is the
    partitioned one) simply fails its claim round and the next is
    tried.  Election + convergence is timed as ``converge_ms``.

    Quacks like a fleet autopilot (``tick()`` + ``status()``), so
    ``NodeManager.attach(election)`` puts re-election on the probe
    cadence with no extra threads; ``start()`` runs a standalone
    cadence for deployments without a NodeManager.
    """

    def __init__(self, candidates: List[FleetControlPlane],
                 interval_ms: float = 500.0,
                 registry=None, recorder=None):
        self.candidates = list(candidates)
        self.interval_ms = float(interval_ms)
        self.elections = 0
        self.last_converge_ms: Optional[float] = None
        self._last_stale: Dict[int, int] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()
        if registry is not None:
            self._m_leader = registry.gauge(
                "ratelimiter.control.leader",
                "1 while a locally managed controller candidate holds "
                "the cell's controller lease (0 = no local leader)")
            self._m_elections = registry.counter(
                "ratelimiter.control.elections",
                "Controller leader elections won by locally managed "
                "candidates (leader death/supersession repairs)")
            self._m_stale = registry.counter(
                "ratelimiter.control.stale_rejected",
                "Stale-epoch controller refusals observed by locally "
                "managed candidates (their claims or policy writes "
                "answered by a seat at a higher epoch)")
            self._m_converge = registry.gauge(
                "ratelimiter.control.converge_ms",
                "Duration of the last election + generation "
                "convergence round (leader death to one fleet-wide "
                "policy generation)")
        else:
            self._m_leader = self._m_elections = None
            self._m_stale = self._m_converge = None

    def leader(self) -> Optional[FleetControlPlane]:
        return next((c for c in self.candidates if c.is_leader), None)

    def tick(self) -> None:
        for cand in self.candidates:
            if cand.is_leader:
                cand.maintain()
        if self.leader() is None:
            for cand in self.candidates:
                t0 = time.monotonic()
                try:
                    won = cand.elect()
                except (ControlError, RuntimeError, OSError):
                    won = False
                if won:
                    self.elections += 1
                    self.last_converge_ms = round(
                        (time.monotonic() - t0) * 1000.0, 3)
                    if self._m_elections is not None:
                        self._m_elections.increment()
                        self._m_converge.set(self.last_converge_ms)
                    self._recorder.record(
                        "control.leader_repaired", node=cand.node,
                        epoch=cand.epoch,
                        converge_ms=self.last_converge_ms)
                    break
        for i, cand in enumerate(self.candidates):
            seen = cand.stale_refusals
            delta = seen - self._last_stale.get(i, 0)
            if delta > 0 and self._m_stale is not None:
                for _ in range(delta):
                    self._m_stale.increment()
            self._last_stale[i] = seen
        if self._m_leader is not None:
            self._m_leader.set(1.0 if self.leader() is not None else 0.0)

    def note_join(self, name: str, backend) -> None:
        """A node joined (fresh standby hand-back, re-seed, promote):
        add it to every candidate's member set and converge it to the
        leader's generation before it can serve a stale one."""
        for cand in self.candidates:
            cand.add_member(name, backend)
        lead = self.leader()
        if lead is not None:
            lead.converge(member_names=[str(name)])

    def status(self) -> dict:
        lead = self.leader()
        return {
            "kind": "controller_election",
            "leader": lead.node if lead is not None else None,
            "epoch": lead.epoch if lead is not None else 0,
            "elections": self.elections,
            "converge_ms": self.last_converge_ms,
            "candidates": [
                {"node": c.node, "is_leader": c.is_leader,
                 "epoch": c.epoch, "demote_reason": c.demote_reason}
                for c in self.candidates
            ],
        }

    # -- standalone cadence (no NodeManager to ride) ---------------------------
    def start(self) -> "ControllerElection":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, name="controller-election", daemon=True)
            self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_ms / 1000.0):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — the repair loop survives
                _log.exception("controller election tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()
