"""Adaptive policy control plane (ROADMAP item 3, ARCHITECTURE §15).

Closes the loop from observation (the fleet telemetry plane's
``UsageSignals``) to actuation (``LimiterTable.set_policy`` row-wise
device updates): per-tenant AIMD limits, a hierarchical global
aggregate cap, operator pinning, and lease-backed concurrency slots.
"""

from ratelimiter_tpu.control.controller import (
    AdaptivePolicyController,
    ControlConfig,
)

__all__ = [
    "AdaptivePolicyController",
    "ControlConfig",
]
