"""Adaptive policy control plane (ROADMAP item 3, ARCHITECTURE §15).

Closes the loop from observation (the fleet telemetry plane's
``UsageSignals``) to actuation (``LimiterTable.set_policy`` row-wise
device updates): per-tenant AIMD limits, a hierarchical global
aggregate cap, operator pinning, and lease-backed concurrency slots.
``control/fleet.py`` makes the same loop fleet-true: epoch-fenced
controller leadership over the control RPC, cross-host signal
aggregation, and monotone-generation policy broadcast.
"""

from ratelimiter_tpu.control.controller import (
    AdaptivePolicyController,
    ControlConfig,
)
from ratelimiter_tpu.control.fleet import (
    ControllerElection,
    FleetControlPlane,
    NotLeader,
)

__all__ = [
    "AdaptivePolicyController",
    "ControlConfig",
    "ControllerElection",
    "FleetControlPlane",
    "NotLeader",
]
