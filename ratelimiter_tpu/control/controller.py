"""Adaptive per-tenant policy controller: AIMD + hierarchical global cap.

ROADMAP item 3 ("Multi-Objective Adaptive Rate Limiting ... Deep
Reinforcement Learning", PAPERS.md, sets the direction; this is the
AIMD/PID starting point the RL formulation can later replace).  Every
policy used to be a frozen constructor argument; this module closes the
loop from observation to actuation:

- **Observation**: the fleet telemetry plane's per-tenant
  :class:`~ratelimiter_tpu.observability.usage.UsageSignals`
  (``plane.all_signals(window_ms)``) — fleet-true under leases, within
  the documented staleness bound — plus the PR 2 circuit breaker's
  state as the global overload signal.
- **Decision**: per-tenant AIMD over a *fraction* of the tenant's
  operator-set ceiling.  While the tenant's denied+shed share of its
  observed load stays under ``target_excess``, the fraction rises
  additively (``increase_fraction`` per tick) toward the ceiling; an
  overload verdict — the tenant hammering far past its limit, sheds
  landing on it, or the breaker open — cuts it multiplicatively
  (``decrease_factor``), clamped to the operator floor.  Hierarchical
  enforcement adds a **global aggregate cap**: when the fleet's RAW
  observed load exceeds ``global_cap_per_s``, every tenant's effective
  rate is scaled by ``cap / fleet_observed``.  Scaling by observed
  load (not admitted rate) is deliberate: in a shed-heavy storm the
  admitted rate can sit UNDER the cap while arrivals are far above it,
  and an admitted-rate trigger would never engage — under-throttling
  exactly when the aggregate needs protecting.  The scale is
  floor-protected per tenant (``max(fraction * scale, floor)``), so a
  hammering fleet cannot squeeze a well-behaved tenant below its
  operator floor while AIMD reallocates the cut onto whoever is
  storming.
- **Actuation**: ``storage.set_policy(lid, config)`` — three scalar
  device row updates stamped with a monotonic policy generation
  (``LimiterTable.set_policy``); the window/algo shape never moves.
  Only CHANGED effective policies actuate, so a converged controller
  ticks for free.

The loop is single-threaded and tick-driven (the PR 9 orchestrator
idiom): ``tick()`` advances everything once — tests drive it with a
simulated clock for exact timelines — and ``start()`` runs it on a
cadence thread.  Operators freeze a lid out of the loop entirely with
:meth:`pin` (``POST /actuator/policies/<lid>/pin``); a pinned lid keeps
whatever effective policy it had and ignores both AIMD and the global
scale until unpinned.

Metrics: ``ratelimiter.control.adjustments`` (set_policy actuations),
``.pinned`` (currently pinned lids), ``.generation`` (the table's
policy generation), ``.global_scale`` (1.0 = cap disengaged).  Flight
events: ``policy.adjusted`` — coalesced per lid with a tally, the
lease ``revocation_storm`` idiom, so a converging AIMD reads as one
ring entry per lid per window, not one per tick — and
``control.global_cap_engaged``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, Optional

from ratelimiter_tpu.core.config import RateLimitConfig
from ratelimiter_tpu.utils.logging import get_logger

_log = get_logger("control.controller")

# Per-lid controller verdicts (status() / GET /actuator/policies).
STEADY = "STEADY"      # at ceiling, healthy
RAISING = "RAISING"    # additive recovery toward the ceiling
CUTTING = "CUTTING"    # multiplicative cut this tick
PINNED = "PINNED"      # operator froze the lid out of the loop
IDLE = "IDLE"          # no observable load in the window


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


@dataclasses.dataclass(frozen=True)
class ControlConfig:
    """Knobs, mirrored 1:1 by the ``ratelimiter.control.*`` props."""

    # Tick cadence (the start() thread; tests call tick() directly).
    interval_ms: float = 1000.0
    # Observation window handed to all_signals() — two ticks' worth by
    # default so one noisy bucket cannot flap a verdict.
    window_ms: int = 2000
    # Overload verdict: the tenant's (denied+shed)/observed share above
    # which its limit is cut multiplicatively.
    target_excess: float = 0.5
    # Additive raise per healthy tick, as a fraction of the ceiling.
    increase_fraction: float = 0.1
    # Multiplicative cut factor on an overload verdict.
    decrease_factor: float = 0.5
    # Default operator floor, as a fraction of the ceiling (per-lid
    # overrides via configure()).
    floor_fraction: float = 0.1
    # Hierarchical global cap on the fleet's aggregate load
    # (decisions/s); 0 disables.  Engages on RAW observed load — not
    # admitted rate, which a shed-heavy storm keeps under the cap
    # while arrivals are far above it.
    global_cap_per_s: float = 0.0
    # Telemetry staleness bound (ms); 0 disables.  When the plane's
    # ``staleness_ms`` exceeds it (a partitioned reporter, a dead
    # member link), the controller FREEZES raises — stale signals must
    # never justify giving a tenant more — while cuts stay allowed.
    staleness_bound_ms: float = 0.0
    # Tenants below this observed load get no verdict (their fraction
    # holds; raising an idle tenant would be guessing).
    min_load_per_s: float = 0.5
    # policy.adjusted events coalesce per lid within this window.
    event_coalesce_ms: float = 2000.0

    def validate(self) -> "ControlConfig":
        if not (0.0 < self.decrease_factor < 1.0):
            raise ValueError("decrease_factor must be in (0, 1)")
        if not (0.0 < self.increase_fraction <= 1.0):
            raise ValueError("increase_fraction must be in (0, 1]")
        if not (0.0 < self.floor_fraction <= 1.0):
            raise ValueError("floor_fraction must be in (0, 1]")
        if not (0.0 <= self.target_excess < 1.0):
            raise ValueError("target_excess must be in [0, 1)")
        if self.staleness_bound_ms < 0:
            raise ValueError("staleness_bound_ms must be >= 0")
        return self


class _LidState:
    """One controlled tenant: its ceiling (the registered policy), the
    operator floor, and the AIMD fraction between them."""

    __slots__ = ("algo", "ceiling", "floor_frac", "fraction", "pinned",
                 "applied", "verdict", "adjustments",
                 "last_event_ms", "coalesced")

    def __init__(self, algo: str, ceiling: RateLimitConfig,
                 floor_frac: float):
        self.algo = algo
        self.ceiling = ceiling
        self.floor_frac = floor_frac
        self.fraction = 1.0          # start at the provisioned ceiling
        self.pinned = False
        # (max_permits, refill_rate) last actuated; None = as registered.
        self.applied: Optional[tuple] = None
        self.verdict = STEADY
        self.adjustments = 0
        self.last_event_ms = 0
        self.coalesced = 0           # adjustments since the last event


class AdaptivePolicyController:
    """Tick-driven AIMD controller over a storage's policy table."""

    def __init__(self, storage, config: ControlConfig | None = None, *,
                 telemetry=None, breaker=None, clock_ms=None,
                 registry=None, recorder=None):
        self.storage = storage
        self.config = (config or ControlConfig()).validate()
        self._plane = (telemetry if telemetry is not None
                       else getattr(storage, "telemetry", None))
        if self._plane is None:
            raise ValueError(
                "the adaptive controller needs the fleet telemetry plane "
                "(storage built with observability=True) for its "
                "UsageSignals observations")
        self._breaker = breaker
        self._clock_ms = (clock_ms
                          or getattr(storage, "_clock_ms", None)
                          or _wall_ms)
        self._lock = threading.RLock()
        self._lids: Dict[int, _LidState] = {}
        self.ticks = 0
        self.adjustments_total = 0
        self.global_scale = 1.0
        self.global_cap_engagements = 0
        self._cap_event_ms = 0
        self.signals_stale_ticks = 0
        self._stale_event_ms = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        if recorder is not None:
            self._recorder = recorder
        else:
            from ratelimiter_tpu.observability import flight_recorder

            self._recorder = flight_recorder()
        if registry is not None:
            self._m_adjust = registry.counter(
                "ratelimiter.control.adjustments",
                "Live policy actuations (set_policy row updates) by the "
                "adaptive controller")
            self._m_pinned = registry.gauge(
                "ratelimiter.control.pinned",
                "Lids currently pinned out of the control loop by an "
                "operator")
            self._m_generation = registry.gauge(
                "ratelimiter.control.generation",
                "The policy table's monotonic generation (bumps on every "
                "live policy update)")
            self._m_scale = registry.gauge(
                "ratelimiter.control.global_scale",
                "Global-cap scale applied to every tenant's effective "
                "rate (1.0 = cap disengaged)")
            self._m_scale.set(1.0)
        else:
            self._m_adjust = self._m_pinned = None
            self._m_generation = self._m_scale = None

    # -- operator surface ------------------------------------------------------
    def configure(self, lid: int, *, floor: Optional[int] = None,
                  ceiling: Optional[RateLimitConfig] = None) -> None:
        """Set one lid's operator bounds: ``floor`` in permits (clamped
        to [1, ceiling]); ``ceiling`` replaces the registered policy as
        the AIMD upper bound (window immutable, like set_policy)."""
        with self._lock:
            st = self._ensure(int(lid))
            if st is None:
                raise KeyError(f"no limiter registered under lid={lid}")
            if ceiling is not None:
                ceiling.validate()
                if ceiling.window_ms != st.ceiling.window_ms:
                    raise ValueError("ceiling cannot change the window")
                st.ceiling = ceiling
            if floor is not None:
                floor = max(int(floor), 1)
                st.floor_frac = min(
                    max(floor / max(st.ceiling.max_permits, 1), 0.0), 1.0)

    def pin(self, lid: int, pinned: bool = True) -> Dict:
        """Freeze a lid out of the control loop (or release it).  The
        lid keeps its current effective policy while pinned."""
        with self._lock:
            st = self._ensure(int(lid))
            if st is None:
                raise KeyError(f"no limiter registered under lid={lid}")
            st.pinned = bool(pinned)
            if st.pinned:
                st.verdict = PINNED
            self._recorder.record("control.pinned" if pinned
                                  else "control.unpinned", lid=int(lid))
            if self._m_pinned is not None:
                self._m_pinned.set(float(sum(
                    1 for s in self._lids.values() if s.pinned)))
            return {"lid": int(lid), "pinned": st.pinned}

    def pinned_lids(self):
        with self._lock:
            return sorted(l for l, s in self._lids.items() if s.pinned)

    # -- the loop --------------------------------------------------------------
    def _ensure(self, lid: int) -> Optional[_LidState]:
        """Adopt a lid into the loop (its registered config becomes the
        ceiling).  Returns None for unregistered lids."""
        st = self._lids.get(lid)
        if st is not None:
            return st
        entry = getattr(self.storage, "_configs", {}).get(lid)
        if entry is None:
            return None
        algo, cfg = entry
        st = _LidState(algo, cfg, self.config.floor_fraction)
        self._lids[lid] = st
        return st

    def tick(self) -> None:
        """Advance the whole loop once: observe, decide, actuate.
        Single-threaded and clock-injected — drills and tests call it
        directly for deterministic timelines."""
        with self._lock:
            self.ticks += 1
            now = int(self._clock_ms())
            cfg = self.config
            for lid in list(getattr(self.storage, "_configs", {})):
                self._ensure(int(lid))
            signals = self._plane.all_signals(cfg.window_ms)
            breaker_open = False
            if self._breaker is not None:
                breaker_open = getattr(self._breaker, "state",
                                       "closed") != "closed"
            # -- staleness freeze -----------------------------------------
            # Stale observations must never justify RAISING a limit (a
            # partitioned reporter's last window could hide a storm);
            # cuts remain allowed — acting on overload evidence is safe
            # even if it is old.
            stale = False
            if cfg.staleness_bound_ms > 0:
                staleness = float(self._plane.staleness_ms())
                stale = staleness > cfg.staleness_bound_ms
                if stale:
                    self.signals_stale_ticks += 1
                    if now - self._stale_event_ms > cfg.event_coalesce_ms:
                        self._stale_event_ms = now
                        self._recorder.record(
                            "control.signals_stale",
                            staleness_ms=round(staleness, 1),
                            bound_ms=cfg.staleness_bound_ms)
            # -- hierarchical global cap ----------------------------------
            fleet_observed = sum(s.observed_load for s in signals.values())
            fleet_admitted = sum(s.goodput for s in signals.values())
            scale = 1.0
            if (cfg.global_cap_per_s > 0
                    and fleet_observed > cfg.global_cap_per_s):
                # Raw OBSERVED load is the trigger and the divisor: a
                # shed-heavy storm keeps the admitted rate under the
                # cap while arrivals are far above it, so admitted-rate
                # scaling would never engage (the PR 15 gap).
                scale = cfg.global_cap_per_s / fleet_observed
                self.global_cap_engagements += 1
                if now - self._cap_event_ms > cfg.event_coalesce_ms:
                    self._cap_event_ms = now
                    self._recorder.record(
                        "control.global_cap_engaged",
                        observed_per_s=round(fleet_observed, 1),
                        admitted_per_s=round(fleet_admitted, 1),
                        scale=round(scale, 4))
            if stale and scale > self.global_scale:
                # A relaxing cap is a raise too: hold the tighter scale
                # until the plane reports fresh signals.
                scale = self.global_scale
            self.global_scale = scale
            if self._m_scale is not None:
                self._m_scale.set(scale)
            # -- per-tenant AIMD ------------------------------------------
            for lid, st in self._lids.items():
                if st.pinned:
                    st.verdict = PINNED
                    continue
                s = signals.get(lid)
                if s is None or s.observed_load < cfg.min_load_per_s:
                    if not breaker_open:
                        st.verdict = IDLE
                        continue
                    excess = 0.0
                else:
                    excess = ((s.denied_rate + s.shed_rate)
                              / max(s.observed_load, 1e-9))
                if breaker_open or excess > cfg.target_excess:
                    st.fraction = max(st.floor_frac,
                                      st.fraction * cfg.decrease_factor)
                    st.verdict = CUTTING
                elif st.fraction < 1.0 and not stale:
                    st.fraction = min(1.0,
                                      st.fraction + cfg.increase_fraction)
                    st.verdict = RAISING
                else:
                    st.verdict = STEADY
                self._actuate(lid, st, scale, now)
            if self._m_generation is not None:
                table = getattr(self.storage, "table", None)
                if table is not None:
                    self._m_generation.set(float(table.generation))

    def _actuate(self, lid: int, st: _LidState, scale: float,
                 now: int) -> None:
        """Apply the lid's effective policy iff it changed."""
        # Floor-protected: the global scale must not squeeze a tenant
        # below its operator floor (AIMD reallocates the cut instead).
        eff = max(st.fraction * scale, st.floor_frac)
        ceiling = st.ceiling
        permits = max(1, round(ceiling.max_permits * eff))
        refill = round(ceiling.refill_rate * eff, 6)
        if ceiling.refill_rate > 0:
            # A token bucket must keep refilling (a zero rate would
            # freeze the bucket, not limit it).
            refill = max(refill, 1e-6)
        if st.applied is None:
            # Never actuated: the registered row IS the ceiling.
            if (permits, refill) == (ceiling.max_permits,
                                     round(ceiling.refill_rate, 6)):
                return
        elif (permits, refill) == st.applied:
            return
        new_cfg = dataclasses.replace(ceiling, max_permits=permits,
                                      refill_rate=refill)
        gen = self.storage.set_policy(lid, new_cfg)
        st.applied = (permits, refill)
        st.adjustments += 1
        st.coalesced += 1
        self.adjustments_total += 1
        if self._m_adjust is not None:
            self._m_adjust.increment()
        # policy.adjusted coalesces PER LID (the revocation_storm idiom:
        # a converging AIMD emits one tallied event per window, the ring
        # shows the episode, not every step).
        if now - st.last_event_ms > self.config.event_coalesce_ms:
            self._recorder.record(
                "policy.adjusted", lid=int(lid), verdict=st.verdict,
                max_permits=permits, fraction=round(st.fraction, 4),
                global_scale=round(scale, 4), generation=int(gen),
                n_coalesced=st.coalesced)
            st.last_event_ms = now
            st.coalesced = 0

    # -- introspection ---------------------------------------------------------
    def status(self) -> Dict:
        with self._lock:
            table = getattr(self.storage, "table", None)
            lids = {}
            for lid, st in sorted(self._lids.items()):
                eff = (st.fraction if st.pinned
                       else max(st.fraction * self.global_scale,
                                st.floor_frac))
                applied = st.applied or (st.ceiling.max_permits,
                                         round(st.ceiling.refill_rate, 6))
                lids[str(lid)] = {
                    "algo": st.algo,
                    "state": st.verdict,
                    "pinned": st.pinned,
                    "fraction": round(st.fraction, 4),
                    "effective_max_permits": applied[0],
                    "effective_refill_rate": applied[1],
                    "ceiling_max_permits": st.ceiling.max_permits,
                    "floor_max_permits": max(
                        1, round(st.ceiling.max_permits * st.floor_frac)),
                    "generation": (table.row_generation(lid)
                                   if table is not None else 0),
                    "adjustments": st.adjustments,
                    "effective_fraction": round(eff, 4),
                }
            return {
                "ticks": self.ticks,
                "generation": (table.generation if table is not None
                               else 0),
                "global_scale": round(self.global_scale, 4),
                "global_cap_per_s": self.config.global_cap_per_s,
                "global_cap_engagements": self.global_cap_engagements,
                "signals_stale_ticks": self.signals_stale_ticks,
                "adjustments": self.adjustments_total,
                "pinned": [l for l, s in sorted(self._lids.items())
                           if s.pinned],
                "lids": lids,
            }

    # -- cadence thread (the PR 9 orchestrator idiom) --------------------------
    def start(self) -> "AdaptivePolicyController":
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="policy-controller")
        self._thread.start()
        return self

    def _run(self) -> None:
        interval_s = max(self.config.interval_ms, 1.0) / 1000.0
        while not self._stop.wait(interval_s):
            try:
                self.tick()
            except Exception as exc:  # noqa: BLE001 — the loop must survive
                if type(exc).__name__ == "NotLeader":
                    # Fleet mode while not holding the cell: the
                    # actuation refusal is the CORRECT behaviour, and
                    # the election loop repairs leadership — not an
                    # error worth a stack trace per tick.
                    _log.debug("controller tick deferred: %s", exc)
                else:
                    _log.exception("controller tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def close(self) -> None:
        self.stop()
