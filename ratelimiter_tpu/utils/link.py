"""Host<->device link probe.

One implementation shared by the bench harness (bench.py:link_probe) and
``TpuBatchedStorage.probe_link`` so the link numbers a run logs and the
profile the storage elects chunk plans from are measured identically —
same probe sizes, same rep counts, same arithmetic.

The probe jits a trivial reduction so each fetch is a full round trip
(on the dev tunnel ``block_until_ready`` does not block; only fetches
prove completion — ROUND_NOTES).
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

PROBE_BYTES = 4 << 20  # 4 MiB upload probe


def measure_link(rtt_reps: int = 3, upload_reps: int = 2
                 ) -> Tuple[float, float]:
    """Measure (upload bytes/s, round-trip seconds) with a tiny-fetch
    RTT probe and a 4 MiB upload probe (each shape compiled untimed
    first).  ~0.5-1 s on a healthy link; callers gate how often."""
    import jax
    import jax.numpy as jnp

    csum = jax.jit(lambda v: v.sum())
    tiny = np.zeros(1024, dtype=np.int32)
    np.asarray(csum(jnp.asarray(tiny)))  # compile + settle
    t0 = time.perf_counter()
    for _ in range(rtt_reps):
        np.asarray(csum(jnp.asarray(tiny)))
    rtt_s = (time.perf_counter() - t0) / rtt_reps
    buf = np.random.default_rng(7).integers(
        0, 1 << 20, PROBE_BYTES // 4).astype(np.int32)
    np.asarray(csum(jnp.asarray(buf)))  # compile this shape untimed
    t0 = time.perf_counter()
    for _ in range(upload_reps):
        np.asarray(csum(jnp.asarray(buf)))
    up_s = max((time.perf_counter() - t0) / upload_reps - rtt_s, 1e-6)
    return PROBE_BYTES / up_s, rtt_s
