"""Host<->device link probe.

One implementation shared by the bench harness (bench.py:link_probe) and
``TpuBatchedStorage.probe_link`` so the link numbers a run logs and the
profile the storage elects chunk plans from are measured identically —
same probe sizes, same rep counts, same arithmetic.

The probe jits a trivial reduction so each fetch is a full round trip
(on the dev tunnel ``block_until_ready`` does not block; only fetches
prove completion — ROUND_NOTES).
"""

from __future__ import annotations

import time
from typing import Tuple

import numpy as np

PROBE_BYTES = 4 << 20  # 4 MiB upload probe


def measure_link(rtt_reps: int = 3, upload_reps: int = 2
                 ) -> Tuple[float, float, float]:
    """Measure (upload bytes/s, round-trip seconds, download bytes/s)
    with a tiny-fetch RTT probe, a 4 MiB upload probe, and a 4 MiB
    download probe (each shape compiled untimed first).  The two
    directions are probed SEPARATELY because the dev tunnel degrades
    them independently (r5 observed 62 MB/s up against 5.3 MB/s down
    in one window) and the words-vs-digest election trades upload
    bytes against download bytes.  ~1-1.5 s on a healthy link; callers
    gate how often.  (A repeated ``np.asarray`` on one jax Array is
    served from its host cache, so each download rep fetches a
    DISTINCT device array.)"""
    import jax
    import jax.numpy as jnp

    csum = jax.jit(lambda v: v.sum())
    tiny = np.zeros(1024, dtype=np.int32)
    np.asarray(csum(jnp.asarray(tiny)))  # compile + settle
    t0 = time.perf_counter()
    for _ in range(rtt_reps):
        np.asarray(csum(jnp.asarray(tiny)))
    rtt_s = (time.perf_counter() - t0) / rtt_reps
    buf = np.random.default_rng(7).integers(
        0, 1 << 20, PROBE_BYTES // 4).astype(np.int32)
    np.asarray(csum(jnp.asarray(buf)))  # compile this shape untimed
    t0 = time.perf_counter()
    for _ in range(upload_reps):
        np.asarray(csum(jnp.asarray(buf)))
    up_s = max((time.perf_counter() - t0) / upload_reps - rtt_s, 1e-6)
    # Download: materialize distinct 4 MiB arrays on device (seeded from
    # a scalar upload — no upload traffic in the timed window), fetch
    # each once.
    fill = jax.jit(lambda s: jnp.full(PROBE_BYTES // 4, s, jnp.int32))
    handles = [fill(np.int32(i)) for i in range(upload_reps + 1)]
    np.asarray(handles[0])  # compile + settle
    t0 = time.perf_counter()
    for h in handles[1:]:
        np.asarray(h)
    down_s = max((time.perf_counter() - t0) / upload_reps - rtt_s, 1e-6)
    return PROBE_BYTES / up_s, rtt_s, PROBE_BYTES / down_s
