"""Persistent XLA compilation cache setup (shared by service wiring and
bench.py).

jit compiles cost 40-90 s per batch shape on TPU; the persistent cache
brings repeats down to ~2 s across process restarts.  Best-effort: any
failure (read-only filesystem, unsupported backend) leaves compilation
working, just uncached.
"""

from __future__ import annotations

import os


def default_cache_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".cache", "ratelimiter_tpu", "jax")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        # 0.1 s (was 1.0): the staged micro steps compile in ~0.3-0.8 s
        # on CPU — under the old threshold they were re-compiled every
        # process boot, which is exactly the latency spike the warmup
        # and the local-SLO p99 gate exist to prevent.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        # The cache module latches "disabled" the first time a compile
        # consults it with no directory configured (_cache_initialized).
        # A caller that builds a storage BEFORE wiring (tests, embedded
        # use) would silently lose the cache for the whole process —
        # reset so this configuration takes effect from now on.
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
