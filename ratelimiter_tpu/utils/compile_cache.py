"""Persistent XLA compilation cache setup (shared by service wiring and
bench.py).

jit compiles cost 40-90 s per batch shape on TPU; the persistent cache
brings repeats down to ~2 s across process restarts.  Best-effort: any
failure (read-only filesystem, unsupported backend) leaves compilation
working, just uncached.
"""

from __future__ import annotations

import os


def default_cache_dir() -> str:
    return os.path.join(
        os.path.expanduser("~"), ".cache", "ratelimiter_tpu", "jax")


def enable_compile_cache(cache_dir: str | None = None) -> None:
    try:
        import jax

        jax.config.update("jax_compilation_cache_dir",
                          cache_dir or default_cache_dir())
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # noqa: BLE001 — cache is an optimization only
        pass
