"""Structured logging (SURVEY.md §5.5 parity).

The reference logs debug/trace throughout via Slf4j with a console
pattern configured in application.properties (lines 9-11: DEBUG for the
app package, a timestamped pattern).  This module is the analog: one
``ratelimiter_tpu`` logger hierarchy, level and pattern set from props
(``logging.level`` / ``logging.pattern``, env-overridable like every
other key).

Call sites use lazy %-formatting so a disabled level costs one enum
compare on the hot path.
"""

from __future__ import annotations

import logging

ROOT = "ratelimiter_tpu"

# The reference's console pattern (application.properties):
# %d{HH:mm:ss} - %msg%n with logger context; rendered in logging idiom.
DEFAULT_PATTERN = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"


def get_logger(name: str) -> logging.Logger:
    return logging.getLogger(f"{ROOT}.{name}")


def setup_logging(props=None) -> logging.Logger:
    """Configure the package logger from props; idempotent."""
    level_name = "INFO"
    pattern = DEFAULT_PATTERN
    if props is not None:
        level_name = (props.get("logging.level") or "INFO").upper()
        pattern = props.get("logging.pattern") or DEFAULT_PATTERN
    logger = logging.getLogger(ROOT)
    logger.setLevel(getattr(logging, level_name, logging.INFO))
    if not any(getattr(h, "_ratelimiter", False) for h in logger.handlers):
        handler = logging.StreamHandler()
        handler._ratelimiter = True  # type: ignore[attr-defined]
        logger.addHandler(handler)
    for handler in logger.handlers:
        if getattr(handler, "_ratelimiter", False):
            handler.setFormatter(logging.Formatter(pattern))
    logger.propagate = False
    return logger
