"""Tracing & profiling.

The reference's only observability beyond counters is TRACE-level logging of
the window math; SURVEY §5.1 lists tracing/profiling as an absent subsystem.
Here:

- ``DecisionTrace`` — a lock-protected ring buffer of per-dispatch records
  (wall time, algo, batch size, allowed count, dispatch latency).  Cheap
  enough to leave on in production; scraped at ``/actuator/trace``.
- ``device_profile`` — context manager around ``jax.profiler`` emitting a
  TensorBoard-loadable trace of the device steps (used by
  ``bench.py --profile`` / BENCH_PROFILE=dir).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, List, Optional


class DecisionTrace:
    """Fixed-capacity ring of per-batch dispatch records."""

    __slots__ = ("_records", "_capacity", "_next", "_total", "_lock")

    def __init__(self, capacity: int = 4096):
        self._capacity = int(capacity)
        self._records: List[Optional[dict]] = [None] * self._capacity
        self._next = 0
        self._total = 0
        self._lock = threading.Lock()

    def record(self, algo: str, batch: int, allowed: int, latency_us: float,
               **extra) -> None:
        """One dispatch record; ``extra`` enriches it (observability
        layer: ``path`` — micro/relay/flat/relay_sharded/... — ``shard``,
        and a sampled per-request ``stages_us`` breakdown)."""
        entry = {
            "t_ms": time.time_ns() // 1_000_000,
            "algo": algo,
            "batch": batch,
            "allowed": allowed,
            "latency_us": round(latency_us, 1),
        }
        if extra:
            entry.update(extra)
        with self._lock:
            self._records[self._next] = entry
            self._next = (self._next + 1) % self._capacity
            self._total += 1

    def snapshot(self, last: int = 100) -> Dict:
        with self._lock:
            ordered = [
                r for r in (
                    self._records[self._next:] + self._records[:self._next])
                if r is not None
            ]
        return {"total_dispatches": self._total, "recent": ordered[-last:]}


@contextlib.contextmanager
def device_profile(log_dir: Optional[str]):
    """Profile device execution into ``log_dir`` (no-op when None)."""
    if not log_dir:
        yield
        return
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
