"""Row scatter into the resident slot arrays.

The single most expensive op in the decision step: XLA's generic scatter
costs ~45 ns per index on the v5e (179 ms for 4M rows — bench/
profile_step.py), two orders of magnitude above the HBM-bandwidth floor
for the same traffic.  This module isolates the op behind one function so
the streaming steps can swap implementations:

- ``scatter_rows_sorted`` — batch is sorted by slot with at most one
  surviving write per slot (the segment-last mask).  The Pallas dense
  block-scatter (ops/pallas/block_scatter.py) exploits exactly that
  structure; XLA drop-mode scatter is the fallback.
"""

from __future__ import annotations

import jax.numpy as jnp


def _scatter(state, sorted_slots, write_mask, rows, presorted: bool):
    from ratelimiter_tpu.ops.pallas import block_scatter

    if block_scatter.enabled(state.shape, sorted_slots.shape[0]):
        fn = (block_scatter.scatter_rows_presorted if presorted
              else block_scatter.scatter_rows)
        return fn(state, sorted_slots, write_mask, rows)
    n = state.shape[0]
    widx = jnp.where(write_mask, sorted_slots, n)  # out-of-range -> dropped
    return state.at[widx].set(rows, mode="drop")


def scatter_rows_sorted(state, sorted_slots, write_mask, rows):
    """state[slot] <- rows[j] for each j with write_mask[j].

    ``sorted_slots`` is sorted ascending (padding < 0 first); among the
    masked entries each slot appears at most once.  Unmasked/padding lanes
    are dropped.
    """
    return _scatter(state, sorted_slots, write_mask, rows, presorted=False)


def scatter_rows_presorted(state, sorted_slots, write_mask, rows):
    """Like :func:`scatter_rows_sorted` for callers whose live updates
    are ALREADY sorted by slot with masked lanes at the tail (the
    host-sorted digest path): the Pallas dense sweep skips its
    compaction sort — no sort runtime, no sort compile cliff, so any
    lane count works.  XLA drop-mode scatter is the fallback (order
    is irrelevant to it)."""
    return _scatter(state, sorted_slots, write_mask, rows, presorted=True)
