"""Pallas TPU kernels (probe-gated, XLA fallbacks, decisions identical)."""


def settle_all() -> None:
    """Resolve every kernel's support probe eagerly.

    Engines call this at init, before any step kernel compiles: a probe
    firing lazily inside another program's lowering nests a remote
    compile some toolchains cannot serve, and the resulting failure
    would stick as a permanent silent fallback.  Each module's settle()
    honors its own kill switch, and both no-op off-TPU (the interpret
    overrides still probe lazily by design — interpret lowering nests
    fine).
    """
    import jax

    if jax.default_backend() != "tpu":
        return
    from ratelimiter_tpu.ops.pallas import block_scatter
    from ratelimiter_tpu.ops.pallas import solver

    block_scatter.settle()
    solver.settle()
