"""Pallas TPU kernels (probe-gated, XLA fallbacks, decisions identical).

Every kernel here is gated twice: a one-time correctness PROBE (tiny
differential against the XLA truth — any lowering failure or mismatch
means permanent fallback) and a one-time measured ELECTION
(ops/pallas/election.py — a supported kernel that measures slower than
the XLA path it replaces does not serve).  ``settle_all()`` resolves
both eagerly at engine init; ``election_report()`` exposes the verdicts
for BENCH_DETAIL and the perf-smoke consistency gate.
"""


def settle_all() -> None:
    """Resolve every kernel's support probe (and election) eagerly.

    Engines call this at init, before any step kernel compiles: a probe
    firing lazily inside another program's lowering nests a remote
    compile some toolchains cannot serve, and the resulting failure
    would stick as a permanent silent fallback.  Each module's settle()
    honors its own kill switch, and all no-op off-TPU (the interpret
    overrides still probe lazily by design — interpret lowering nests
    fine).
    """
    import jax

    if jax.default_backend() != "tpu":
        return
    from ratelimiter_tpu.ops.pallas import block_scatter
    from ratelimiter_tpu.ops.pallas import relay_step
    from ratelimiter_tpu.ops.pallas import solver

    block_scatter.settle()
    solver.settle()
    relay_step.settle()


def election_report() -> dict:
    """Per-path election verdicts + measurements resolved so far (see
    ops/pallas/election.py).  Paths that never probed (e.g. CPU runs)
    are simply absent."""
    from ratelimiter_tpu.ops.pallas import election

    return election.report()
