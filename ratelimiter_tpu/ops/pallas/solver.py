"""Pallas TPU kernel for the segmented threshold-recurrence solver.

The XLA implementation (ops/segments.py:solve_threshold_recurrence) runs the
sandwich iteration as a ``lax.while_loop`` whose per-iteration buffers round-
trip through HBM.  This kernel keeps the whole sorted batch resident in VMEM
and iterates in place: one launch, log-depth masked segmented scans on the
VPU, no HBM traffic between iterations.

Arithmetic: int32 with saturating adds.  Exactness argument:

- Sliding window (w == 1): all quantities are counts bounded by the batch
  size and max_permits; thresholds are clamped to SAT, and any count beyond
  SAT would reject anyway.
- Token bucket: the condition  W + req <= v1  has every term a multiple of
  2**TOKEN_FP_SHIFT (req = permits * 1000 * 2**s), so both sides can be
  right-shifted by s exactly (callers pass u' = (v1 - req) >> s and
  w' = req >> s = permits * 1000).  Within-segment sums can still overflow
  int32 for pathological hot segments, so the scan saturates at SAT
  (sized so 2*SAT fits int32 — the clamp runs after each add) while
  thresholds clip to SAT-1; a saturated prefix therefore always compares
  greater and correctly rejects.  min(a+b, SAT) is associative over
  non-negatives, so saturation commutes with the scan.

The kernel is gated: ``solve_threshold_recurrence_auto`` tries the Pallas
path when enabled (RATELIMITER_PALLAS=1) and the platform supports it,
falling back to the XLA implementation otherwise — decisions are identical
(differential-tested in tests/test_pallas_solver.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ratelimiter_tpu.ops import segments as _xla

# Saturation ceiling: 2*SAT must fit int32 so two adjacent saturated
# lanes can add without wrapping (the scan clamps AFTER the add), and
# thresholds are clipped to SAT-1 so a saturated prefix always rejects.
SAT = (1 << 30) - 1


def _ensure_stack() -> None:
    """Raise Python's recursion limit for kernel lowering.

    Mosaic's jaxpr lowering recurses per equation and pltpu.roll's
    tracing recurses with the shift amount, so the log-depth unroll
    needs ~n/2 frames at the largest shift — ~16K at the 32K-lane
    dispatch ceiling, far past the default 1000.  The raise is sticky
    (process-global): lowering continues inside jit internals after this
    frame returns, so a scoped save/restore cannot cover it.  CPython
    3.12 keeps Python-to-Python calls off the C stack, so the depth is
    safe on default 8 MB thread stacks.
    """
    import sys

    if sys.version_info < (3, 12):
        # Pre-3.12 CPython keeps Python calls on the C stack: a 100K
        # limit could convert a clean RecursionError (-> XLA fallback
        # via the probe) into a segfault.  Leave the default; the probe
        # will fail and the XLA solver serves instead.
        return
    if sys.getrecursionlimit() < 100000:
        sys.setrecursionlimit(100000)


def _solver_kernel(u_ref, w_ref, segfirst_ref, inc_ref, *, n: int):
    """Whole-batch solver in one VMEM block.

    u, w: i32[1, n]; segfirst: i32[1, n] — index of each element's segment
    head; inc (out): i32[1, n].
    """
    # Everything stays (1, n): Mosaic's TPU lowering handles 2D slices,
    # concats, and reductions, while rank-1 forms of the same ops hit
    # NotImplemented/recursion walls (found empirically on v5e).
    u = u_ref[...]
    w = w_ref[...]
    seg_first = segfirst_ref[...]
    idx = jax.lax.broadcasted_iota(jnp.int32, (1, n), 1)

    def seg_cumsum_excl(x):
        """Saturating segmented EXCLUSIVE scan.

        The exclusive sum is computed directly — shift x down one lane
        within its segment, then run the masked Hillis-Steele inclusive
        scan over the shifted values — so saturation clamps the
        exclusive prefix itself.  (Deriving it as inclusive-minus-own
        would UNDERestimate clamped prefixes by the element's own
        weight, admitting requests a saturated prefix must reject.)
        Values never leave the segment, so magnitudes stay
        segment-local.
        """
        import numpy as np

        from jax.experimental.pallas import tpu as pltpu

        # Circular roll (a supported Mosaic primitive; concatenate
        # recurses in lowering).  The wrap-around lanes land at
        # idx < d, where idx - d < 0 <= seg_first masks them off.
        # Literals must be explicit 32-bit under jax_enable_x64: a
        # weak python int turns the shift into an i64 scalar
        # (tpu.dynamic_rotate verification error) and an i64 `where`
        # arm sends Mosaic's convert-element-type lowering into
        # infinite recursion.
        prev_ok = (idx - 1) >= seg_first
        v = jnp.where(prev_ok, pltpu.roll(x, np.int32(1), 1), jnp.int32(0))
        d = 1
        while d < n:  # static log2(n) unroll
            shifted = pltpu.roll(v, np.int32(d), 1)
            ok = (idx - d) >= seg_first
            v = jnp.minimum(v + jnp.where(ok, shifted, jnp.int32(0)),
                            jnp.int32(SAT))
            d *= 2
        return v

    def step(x):
        s = seg_cumsum_excl(jnp.minimum(w * x, SAT))
        return (s <= u).astype(jnp.int32)

    def cond(carry):
        lo, hi, it = carry
        # Reduce through i32: Mosaic only converts 32-bit reductions to
        # scalars (a bool `any` trips a float64 path on TPU).
        diff = jnp.max(jnp.abs(lo - hi))
        return jnp.logical_and(diff > 0, it < n + 2)

    def body(carry):
        lo, hi, it = carry
        return step(hi), step(lo), it + 1

    lo0 = jnp.zeros((1, n), jnp.int32)
    hi0 = jnp.ones((1, n), jnp.int32)
    lo, _, _ = jax.lax.while_loop(cond, body, (lo0, hi0, jnp.int32(0)))
    inc_ref[...] = lo


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_solve(u32, w32, seg_first, interpret: bool = False):
    """Run the Pallas solver on i32 inputs shaped [n].

    Inputs are right-padded to a lane-aligned width (Mosaic mishandles
    tiny/unaligned rank-2 shapes): padded lanes carry u = -1 (never
    pass), and their seg_first is +inf-ish so the masked scan leaves
    them inert; padding sits at the tail, so it can never feed a real
    lane (the scan only looks backward).
    """
    from jax.experimental import pallas as pl

    _ensure_stack()
    n = u32.shape[0]
    n_pad = max(256, -(-n // 128) * 128)
    if n_pad != n:
        pad = n_pad - n
        u32 = jnp.concatenate([u32, jnp.full((pad,), -1, jnp.int32)])
        w32 = jnp.concatenate([w32, jnp.zeros((pad,), jnp.int32)])
        seg_first = jnp.concatenate(
            [seg_first, jnp.full((pad,), SAT, jnp.int32)])
    kernel = functools.partial(_solver_kernel, n=n_pad)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, n_pad), jnp.int32),
        interpret=interpret,
    )(u32.reshape(1, n_pad), w32.reshape(1, n_pad),
      seg_first.reshape(1, n_pad))
    return out[0, :n]


def seg_first_index(first: jnp.ndarray) -> jnp.ndarray:
    """Index of each element's segment head (i32), from the boolean
    first-occurrence mask."""
    n = first.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.associative_scan(jnp.maximum, jnp.where(first, idx, 0))


# ---------------------------------------------------------------------------
# Auto dispatcher
# ---------------------------------------------------------------------------

_PALLAS_FLAG = os.environ.get("RATELIMITER_PALLAS", "1") == "1"
# Interpret-mode override so the Pallas path can be exercised on CPU in tests.
_PALLAS_INTERPRET = os.environ.get("RATELIMITER_PALLAS_INTERPRET", "0") == "1"
# Single-launch lane ceiling: the log-depth unroll's temporaries grow with
# lane count and the TPU compiler falls over past 16K lanes (measured on
# v5e with the exclusive-scan kernel); larger batches take the XLA solver.
# The micro-batcher's buckets (<= max_batch 8192) sit comfortably under
# the ceiling — exactly the traffic the VMEM-resident iteration helps.
_PALLAS_MAX_LANES = 1 << 14
_pallas_ok: bool | None = None


def _pallas_supported() -> bool:
    global _pallas_ok
    if _pallas_ok is None:
        if not (_PALLAS_INTERPRET or jax.default_backend() == "tpu"):
            _pallas_ok = False
            return False
        try:
            test = jnp.asarray([5, 5, -1], dtype=jnp.int32)
            w = jnp.ones(3, dtype=jnp.int32)
            sf = jnp.zeros(3, dtype=jnp.int32)
            out = pallas_solve(test, w, sf, interpret=_PALLAS_INTERPRET)
            _pallas_ok = list(jax.device_get(out)) == [1, 1, 0]
        except Exception:  # noqa: BLE001 — any lowering failure => fallback
            _pallas_ok = False
    return _pallas_ok


# ---------------------------------------------------------------------------
# Measured micro-batch election (r6; generalized into
# ops/pallas/election.py in r7 — this module keeps only its measure
# function and delegates the verdict/caching/override machinery).
#
# BENCH_r05's A/B put the Pallas solver at x0.91 of the XLA path on the
# micro-batch traffic it exists to serve — a supported kernel is not
# necessarily a WINNING kernel, and which one wins varies by device
# generation and toolchain.  The auto dispatcher runs a one-time timed
# A/B at a representative micro-batch shape (duplicate segments,
# batcher-bucket lanes) and disables the Pallas path when XLA wins; the
# verdict is disk-cached per (platform, device kind, path) next to the
# compile cache.  RATELIMITER_PALLAS_ELECT=on|off|auto overrides (per
# path: RATELIMITER_PALLAS_ELECT_MICRO).  Interpret mode skips the
# election (it exists to exercise the kernel, not to win).


def _measure_micro_ab() -> dict:
    """Best-of-5 wall of one micro-batch solve, Pallas vs XLA, at the
    shape the kernel serves (8192 lanes, 4-deep segments)."""
    import time

    import numpy as np

    n = 8192
    rng = np.random.default_rng(17)
    seg = np.sort(rng.integers(0, n // 4, n))
    first = np.ones(n, dtype=bool)
    first[1:] = seg[1:] != seg[:-1]
    u = jnp.asarray(rng.integers(0, 100, n).astype(np.int64))
    w = jnp.asarray(rng.integers(1, 5, n).astype(np.int64))
    first_j = jnp.asarray(first)

    def run_pallas(u, w, first):
        sf = seg_first_index(first)
        u32 = jnp.clip(u, -1, SAT - 1).astype(jnp.int32)
        w32 = jnp.clip(w, 0, SAT).astype(jnp.int32)
        return pallas_solve(u32, w32, sf,
                            interpret=_PALLAS_INTERPRET).astype(jnp.int64)

    def run_xla(u, w, first):
        return _xla.solve_threshold_recurrence(u, w, first)

    def best_of(fn):
        f = jax.jit(fn)
        jax.block_until_ready(f(u, w, first_j))  # compile + settle
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(f(u, w, first_j))
            best = min(best, time.perf_counter() - t0)
        return best

    return {"pallas_s": best_of(run_pallas), "xla_s": best_of(run_xla),
            "lanes": n}


def _micro_election() -> bool:
    """True when the Pallas solver should serve micro-batches on this
    device (measured; cached in-process and on disk by the shared
    per-path election — ops/pallas/election.py, path ``micro``)."""
    from ratelimiter_tpu.ops.pallas import election

    return election.measured_election("micro", _measure_micro_ab,
                                      interpret=_PALLAS_INTERPRET)


def settle() -> bool:
    """Resolve the support probe (and the micro-batch election) eagerly
    — engine init calls this before any step kernel compiles; a probe
    firing lazily inside another program's lowering would nest remote
    compiles.  Respects the RATELIMITER_PALLAS kill switch: disabled
    means no Pallas compile at all.  Returns whether the Pallas solver
    will actually SERVE (supported AND elected)."""
    if not _PALLAS_FLAG:
        return False
    if not _pallas_supported():
        return False
    return _micro_election()


def solve_threshold_recurrence_auto(u, w, first, shift: int = 0):
    """Drop-in for segments.solve_threshold_recurrence with optional Pallas.

    Inputs are int64 (engine convention).  ``shift`` right-shifts u and w
    into the int32 domain; exact when every weight is a multiple of
    2**shift (token bucket: shift=TOKEN_FP_SHIFT since req_fp =
    permits * 1000 * 2**shift — the arithmetic shift floors u, and
    W <= u  <=>  W>>s <= floor(u/2**s) for W a multiple of 2**s).
    Sliding window uses shift=0.
    """
    if (_PALLAS_FLAG and u.shape[0] <= _PALLAS_MAX_LANES
            and _pallas_supported() and _micro_election()):
        u_s = jnp.right_shift(u, shift) if shift else u
        w_s = jnp.right_shift(w, shift) if shift else w
        # Thresholds clip BELOW the saturation ceiling so a saturated
        # prefix sum (== SAT) compares greater and correctly rejects.
        u32 = jnp.clip(u_s, -1, SAT - 1).astype(jnp.int32)
        w32 = jnp.clip(w_s, 0, SAT).astype(jnp.int32)
        sf = seg_first_index(first)
        out = pallas_solve(u32, w32, sf, interpret=_PALLAS_INTERPRET)
        return out.astype(jnp.int64)
    return _xla.solve_threshold_recurrence(u, w, first)
