"""Pallas TPU kernel for the segmented threshold-recurrence solver.

The XLA implementation (ops/segments.py:solve_threshold_recurrence) runs the
sandwich iteration as a ``lax.while_loop`` whose per-iteration buffers round-
trip through HBM.  This kernel keeps the whole sorted batch resident in VMEM
and iterates in place: one launch, log-depth masked segmented scans on the
VPU, no HBM traffic between iterations.

Arithmetic: int32 with saturating adds.  Exactness argument:

- Sliding window (w == 1): all quantities are counts bounded by the batch
  size and max_permits; thresholds are clamped to SAT, and any count beyond
  SAT would reject anyway.
- Token bucket: the condition  W + req <= v1  has every term a multiple of
  2**TOKEN_FP_SHIFT (req = permits * 1000 * 2**s), so both sides can be
  right-shifted by s exactly (callers pass u' = (v1 - req) >> s and
  w' = req >> s = permits * 1000).  Within-segment sums can still overflow
  int32 for pathological hot segments, so the scan saturates at SAT; since
  SAT > any representable u', a saturated prefix correctly rejects.
  min(a+b, SAT) is associative over non-negatives, so saturation commutes
  with the scan.

The kernel is gated: ``solve_threshold_recurrence_auto`` tries the Pallas
path when enabled (RATELIMITER_PALLAS=1) and the platform supports it,
falling back to the XLA implementation otherwise — decisions are identical
(differential-tested in tests/test_pallas_solver.py).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp

from ratelimiter_tpu.ops import segments as _xla

SAT = 1 << 30  # saturation ceiling (python int): above any legal threshold


def _solver_kernel(u_ref, w_ref, segfirst_ref, inc_ref, *, n: int):
    """Whole-batch solver in one VMEM block.

    u, w: i32[1, n]; segfirst: i32[1, n] — index of each element's segment
    head; inc (out): i32[1, n].
    """
    u = u_ref[0, :]
    w = w_ref[0, :]
    seg_first = segfirst_ref[0, :]
    idx = jax.lax.broadcasted_iota(jnp.int32, (n,), 0)

    def seg_cumsum_excl(x):
        """Saturating segmented inclusive scan minus x (exclusive).

        Masked Hillis-Steele: after step k, v[i] holds the (saturated) sum of
        x over [max(seg_first[i], i - 2^k + 1), i]; values never leave the
        segment, so magnitudes stay segment-local.
        """
        v = x
        d = 1
        while d < n:  # static log2(n) unroll
            shifted = jnp.concatenate([jnp.zeros((d,), jnp.int32), v[:-d]])
            ok = (idx - d) >= seg_first
            v = jnp.minimum(v + jnp.where(ok, shifted, 0), SAT)
            d *= 2
        return v - x

    def step(x):
        s = seg_cumsum_excl(jnp.minimum(w * x, SAT))
        return (s <= u).astype(jnp.int32)

    def cond(carry):
        lo, hi, it = carry
        return jnp.logical_and(jnp.any(lo != hi), it < n + 2)

    def body(carry):
        lo, hi, it = carry
        return step(hi), step(lo), it + 1

    lo0 = jnp.zeros((n,), jnp.int32)
    hi0 = jnp.ones((n,), jnp.int32)
    lo, _, _ = jax.lax.while_loop(cond, body, (lo0, hi0, jnp.int32(0)))
    inc_ref[0, :] = lo


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_solve(u32, w32, seg_first, interpret: bool = False):
    """Run the Pallas solver on i32 inputs shaped [n]."""
    from jax.experimental import pallas as pl

    n = u32.shape[0]
    kernel = functools.partial(_solver_kernel, n=n)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((1, n), jnp.int32),
        interpret=interpret,
    )(u32.reshape(1, n), w32.reshape(1, n), seg_first.reshape(1, n))
    return out[0]


def seg_first_index(first: jnp.ndarray) -> jnp.ndarray:
    """Index of each element's segment head (i32), from the boolean
    first-occurrence mask."""
    n = first.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    return jax.lax.associative_scan(jnp.maximum, jnp.where(first, idx, 0))


# ---------------------------------------------------------------------------
# Auto dispatcher
# ---------------------------------------------------------------------------

_PALLAS_FLAG = os.environ.get("RATELIMITER_PALLAS", "0") == "1"
# Interpret-mode override so the Pallas path can be exercised on CPU in tests.
_PALLAS_INTERPRET = os.environ.get("RATELIMITER_PALLAS_INTERPRET", "0") == "1"
_pallas_ok: bool | None = None


def _pallas_supported() -> bool:
    global _pallas_ok
    if _pallas_ok is None:
        try:
            test = jnp.asarray([5, 5, -1], dtype=jnp.int32)
            w = jnp.ones(3, dtype=jnp.int32)
            sf = jnp.zeros(3, dtype=jnp.int32)
            out = pallas_solve(test, w, sf, interpret=_PALLAS_INTERPRET)
            _pallas_ok = list(jax.device_get(out)) == [1, 1, 0]
        except Exception:  # noqa: BLE001 — any lowering failure => fallback
            _pallas_ok = False
    return _pallas_ok


def solve_threshold_recurrence_auto(u, w, first, shift: int = 0):
    """Drop-in for segments.solve_threshold_recurrence with optional Pallas.

    Inputs are int64 (engine convention).  ``shift`` right-shifts u and w
    into the int32 domain; exact when every weight is a multiple of
    2**shift (token bucket: shift=TOKEN_FP_SHIFT since req_fp =
    permits * 1000 * 2**shift — the arithmetic shift floors u, and
    W <= u  <=>  W>>s <= floor(u/2**s) for W a multiple of 2**s).
    Sliding window uses shift=0.
    """
    if _PALLAS_FLAG and _pallas_supported():
        u_s = jnp.right_shift(u, shift) if shift else u
        w_s = jnp.right_shift(w, shift) if shift else w
        u32 = jnp.clip(u_s, -1, SAT).astype(jnp.int32)
        w32 = jnp.clip(w_s, 0, SAT).astype(jnp.int32)
        sf = seg_first_index(first)
        out = pallas_solve(u32, w32, sf, interpret=_PALLAS_INTERPRET)
        return out.astype(jnp.int64)
    return _xla.solve_threshold_recurrence(u, w, first)
