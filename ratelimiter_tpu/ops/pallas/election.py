"""Measured per-path Pallas elections (VERDICT r5 #7, generalized r6->r7).

A supported kernel is not necessarily a WINNING kernel: BENCH_r05's A/B
put the Pallas micro-batch solver at x0.91 of the XLA path on the very
traffic it exists to serve, and which backend wins varies by device
generation and toolchain.  PR 3 gave the solver a one-time timed A/B
(`solver.py:_micro_election`); this module is that machinery extracted
so EVERY Pallas-capable path elects the same way:

- ``micro``        — the micro-batch sandwich solver (ops/pallas/solver.py)
- ``block_scatter``— the dense presorted digest sweep (block_scatter.py)
- ``relay_fused``  — the fused relay-step kernel (relay_step.py)

Each path registers a measure function returning ``{"pallas_s",
"xla_s", ...shape keys...}``; the verdict (Pallas serves iff
``pallas_s <= margin * xla_s``) is cached in-process and on disk per
(platform, device kind, path) next to the compile cache, like
engine/device_rates.py — so one process pays the A/B and every later
process reads the verdict.  ``report()`` returns every resolved
verdict with its measurements, which bench.py and bench/device_only.py
record into BENCH_DETAIL so no path can silently run a measured-slower
backend (bench/perf_smoke.py asserts record/verdict consistency in CI).

Overrides: ``RATELIMITER_PALLAS_ELECT=auto|on|off`` applies to every
path; ``RATELIMITER_PALLAS_ELECT_<PATH>`` (upper-cased path name) wins
over the global for that path.  ``on`` = always use Pallas when the
support probe passes (the pre-r6 behavior); ``off`` = never; ``auto`` =
measure.  Interpret mode skips the election (it exists to exercise the
kernels on CPU, not to win) — callers pass ``interpret=True`` and get
an elected-True verdict tagged ``source: interpret``.
"""

from __future__ import annotations

import json
import os
import time
from typing import Callable, Dict, Optional

_ELECT_ENV = "RATELIMITER_PALLAS_ELECT"
# Pallas keeps a path unless XLA clearly wins: the margin absorbs timer
# noise so a dead-even A/B doesn't flap between processes.
DEFAULT_MARGIN = 1.05

# path -> {"elected": bool, "source": str, ...measurements...}
_verdicts: Dict[str, Dict] = {}


def _cache_path(path_name: str) -> Optional[str]:
    try:
        import jax

        base = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001
        base = None
    if not base:
        from ratelimiter_tpu.utils.compile_cache import default_cache_dir

        base = default_cache_dir()
    try:
        import jax

        dev = jax.devices()[0]
        kind = getattr(dev, "device_kind", dev.platform)
    except Exception:  # noqa: BLE001
        return None
    safe = "".join(ch if ch.isalnum() else "_" for ch in kind)[:40]
    return os.path.join(
        base, f"pallas_elect_{dev.platform}_{safe}_{path_name}.json")


def _policy(path_name: str) -> str:
    per_path = os.environ.get(
        f"{_ELECT_ENV}_{path_name.upper()}", "").lower()
    if per_path:
        return per_path
    return os.environ.get(_ELECT_ENV, "auto").lower()


def measured_election(
    path_name: str,
    measure: Callable[[], Dict],
    *,
    margin: float = DEFAULT_MARGIN,
    interpret: bool = False,
) -> bool:
    """True when the Pallas implementation of ``path_name`` should serve
    on this device.  ``measure`` runs at most once per (device, path)
    across processes; a measurement failure keeps Pallas (the support
    probe already proved it computes correctly — refusing to elect on a
    timing error would silently discard a working kernel)."""
    hit = _verdicts.get(path_name)
    if hit is not None:
        return bool(hit["elected"])
    try:
        return _resolve_verdict(path_name, measure, margin, interpret)
    finally:
        _note_verdict(path_name)


def _note_verdict(path_name: str) -> None:
    """Every freshly-resolved election verdict lands in the flight
    recorder — a losing kernel silently reverting to XLA is exactly the
    kind of transition an operator reconstructs timelines from."""
    v = _verdicts.get(path_name)
    if v is None:
        return
    try:
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record(
            "pallas.election", path=path_name,
            elected=bool(v.get("elected")), source=str(v.get("source")))
    except Exception:  # noqa: BLE001 — observability must not break elections
        pass


def _resolve_verdict(
    path_name: str,
    measure: Callable[[], Dict],
    margin: float,
    interpret: bool,
) -> bool:
    policy = _policy(path_name)
    if policy in ("on", "always", "1"):
        _verdicts[path_name] = {"elected": True, "source": "env_on"}
        return True
    if policy in ("off", "never", "0"):
        _verdicts[path_name] = {"elected": False, "source": "env_off"}
        return False
    if interpret:
        # Interpret mode exists to exercise the kernel; timing it against
        # compiled XLA on CPU would always reject it.
        _verdicts[path_name] = {"elected": True, "source": "interpret"}
        return True
    disk = _cache_path(path_name)
    if disk and os.path.exists(disk):
        try:
            with open(disk, "r", encoding="utf-8") as fh:
                data = json.load(fh)
            _verdicts[path_name] = dict(data, source="disk_cache")
            return bool(data["elected"])
        except Exception:  # noqa: BLE001 — corrupt cache: re-measure
            pass
    try:
        ab = dict(measure())
        elected = ab["pallas_s"] <= margin * ab["xla_s"]
    except Exception as exc:  # noqa: BLE001 — measurement failed: keep Pallas
        _verdicts[path_name] = {"elected": True, "source": "measure_error",
                                "error": str(exc)[:200]}
        return True
    rec = dict(ab, elected=bool(elected), margin=margin,
               measured_at_ms=int(time.time() * 1000))
    _verdicts[path_name] = dict(rec, source="measured")
    if disk:
        try:
            os.makedirs(os.path.dirname(disk), exist_ok=True)
            tmp = disk + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(rec, fh)
            os.replace(tmp, disk)
        except Exception:  # noqa: BLE001 — disk cache is best-effort
            pass
    return bool(elected)


def report() -> Dict[str, Dict]:
    """Every verdict this process has resolved (for BENCH_DETAIL and the
    perf-smoke consistency gate).  Copies, so callers can't poison the
    cache."""
    return {k: dict(v) for k, v in _verdicts.items()}


def reset_for_tests() -> None:
    """Drop the in-process verdict cache (tests flip env overrides)."""
    _verdicts.clear()
