"""Pallas TPU dense block-scatter for sorted-unique row updates.

XLA's generic scatter on TPU costs ~45 ns/index (~179 ms to write 4M rows
of a 1M-slot table — bench/profile_step.py), far above the HBM-bandwidth
floor for the same bytes.  But the streaming step's scatter has structure
XLA cannot exploit: the batch is sorted by slot and carries at most one
surviving write per slot (the segment-last row of each sorted duplicate
run).  That makes the scatter expressible as a DENSE sweep:

    for each aligned block of T consecutive state rows:
        the updates touching it sit in a contiguous window of the
        (compacted, slot-sorted) update array, at most T long
        -> load block + window into VMEM, select per row, write back

Pipeline:
1. Compact: one payload-carrying ``lax.sort`` moves masked-out lanes to
   the tail (key = slot for live updates, S sentinel otherwise), leaving
   live updates sorted by slot and unique.
2. Window map: ``searchsorted`` of the T-aligned block boundaries over the
   compacted keys, divided down to block granularity — per state block i a
   scalar sigma[i] such that update-blocks [sigma[i], sigma[i]+1] cover
   every update for block i (<= T updates, any exact window start spans at
   most two aligned T-blocks).
3. One ``pallas_call`` over the S/T state blocks: the update windows are
   pulled through VMEM by BlockSpec index_maps reading sigma (scalar
   prefetch — DMA double-buffering comes free from the grid pipeline);
   per row the matching update (if any) is selected by compare-and-sum
   over the window, which is exact because slots are unique.

HBM traffic: read S + 2B rows, write S rows — bandwidth-bound instead of
per-index-bound.  The state output aliases the state input (in-place in
HBM, composing with the caller's donated buffers).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

T = 256          # state rows per block; S must divide by this
_CHUNK = 128     # window columns folded per VPU select-sum pass

_FLAG = os.environ.get("RATELIMITER_BLOCK_SCATTER", "1") == "1"
_INTERPRET = os.environ.get("RATELIMITER_BLOCK_SCATTER_INTERPRET", "0") == "1"
_probe_ok: bool | None = None


def _kernel(sigma_ref, state_ref, upd_a_ref, upd_b_ref, out_ref, *, lanes):
    del sigma_ref, lanes  # sigma is consumed by the index_maps
    block = state_ref[...]                       # (T, lanes)
    win = jnp.concatenate([upd_a_ref[...], upd_b_ref[...]], axis=0)
    w_slot = win[:, 0]                           # (2T,) compacted slot keys
    w_rows = win[:, 1:]                          # (2T, lanes)
    t_slot = T * pl.program_id(0) + jax.lax.broadcasted_iota(
        jnp.int32, (T,), 0)

    acc = jnp.zeros(block.shape, dtype=jnp.int32)
    anym = jnp.zeros((T,), dtype=jnp.bool_)
    for c in range(0, 2 * T, _CHUNK):
        eq = w_slot[None, c:c + _CHUNK] == t_slot[:, None]   # (T, CHUNK)
        anym = anym | eq.any(axis=1)
        # Unique slots => at most one hit per row: select-sum is exact.
        acc = acc + jnp.sum(
            eq[:, :, None].astype(jnp.int32) * w_rows[None, c:c + _CHUNK, :],
            axis=1, dtype=jnp.int32)
    out_ref[...] = jnp.where(anym[:, None], acc, block)


try:  # import guarded so CPU-only environments can still load the module
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu
except Exception:  # noqa: BLE001
    pl = None
    pltpu = None


@functools.partial(jax.jit, static_argnames=("interpret",))
def _block_scatter(state, upd, sigma, interpret: bool = False):
    """state (S, L) i32; upd (B, 1+L) i32 lane0=compacted slot key;
    sigma (S/T,) i32 aligned window starts (units of T)."""
    s_rows, lanes = state.shape
    grid = s_rows // T
    kernel = functools.partial(_kernel, lanes=lanes)
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((T, lanes), lambda i, sig: (i, 0)),
            pl.BlockSpec((T, 1 + lanes), lambda i, sig: (sig[i], 0)),
            pl.BlockSpec((T, 1 + lanes), lambda i, sig: (sig[i] + 1, 0)),
        ],
        out_specs=pl.BlockSpec((T, lanes), lambda i, sig: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct(state.shape, state.dtype),
        input_output_aliases={1: 0},  # state buffer updated in place
        interpret=interpret,
    )(sigma, state, upd, upd)


def scatter_rows(state, sorted_slots, write_mask, rows,
                 interpret: bool | None = None):
    """Drop-in for the XLA drop-mode scatter over sorted-unique writes.

    state i32[S, L]; sorted_slots i32[B] ascending (padding < 0 first);
    write_mask bool[B] with at most one True per slot; rows i32[B, L].
    """
    if interpret is None:
        interpret = _INTERPRET
    s_rows, lanes = state.shape
    n = sorted_slots.shape[0]
    key = jnp.where(write_mask, sorted_slots, jnp.int32(s_rows))
    ops = jax.lax.sort(
        (key,) + tuple(rows[:, j] for j in range(lanes)), num_keys=1)
    upd = jnp.stack(ops, axis=1)                 # (B, 1+L), live-first
    bounds = jnp.arange(s_rows // T, dtype=jnp.int32) * T
    starts = jnp.searchsorted(ops[0], bounds).astype(jnp.int32)
    sigma = jnp.clip(starts // T, 0, n // T - 2)
    return _block_scatter(state, upd, sigma, interpret=interpret)


def supported(state_shape, batch: int) -> bool:
    """Static geometry gate: aligned table, window-coverable batch."""
    s_rows = state_shape[0]
    return (pl is not None and s_rows % T == 0 and s_rows // T >= 1
            and batch >= 2 * T and batch % T == 0)


def _probe() -> bool:
    """One-time self-check on this platform: tiny scatter vs XLA truth."""
    global _probe_ok
    if _probe_ok is None:
        try:
            rng = np.random.default_rng(7)
            s = jnp.asarray(rng.integers(0, 1 << 30, (2 * T, 3), np.int32))
            slots = np.sort(rng.choice(2 * T, size=2 * T, replace=True))
            mask = np.r_[np.diff(slots) != 0, True]
            rows = rng.integers(0, 1 << 30, (2 * T, 3), np.int32)
            got = np.asarray(scatter_rows(
                s, jnp.asarray(slots.astype(np.int32)), jnp.asarray(mask),
                jnp.asarray(rows), interpret=_INTERPRET))
            want = np.asarray(s).copy()
            want[slots[mask]] = rows[mask]
            _probe_ok = bool((got == want).all())
        except Exception:  # noqa: BLE001 — any lowering failure => fallback
            _probe_ok = False
    return _probe_ok


def enabled(state_shape, batch: int) -> bool:
    if not _FLAG or not supported(state_shape, batch):
        return False
    if not (_INTERPRET or jax.default_backend() == "tpu"):
        return False
    return _probe()
