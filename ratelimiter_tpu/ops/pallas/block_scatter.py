"""Pallas TPU dense block-scatter for sorted-unique row updates.

XLA's generic scatter on TPU costs ~45 ns/index, far above the
HBM-bandwidth floor for the same bytes.  But the sorted step's scatter
has structure XLA cannot exploit: the batch is sorted by slot and
carries at most one surviving write per slot (the segment-last row of
each sorted duplicate run).  That makes the scatter expressible as a
DENSE sweep:

    for each aligned block of T consecutive state rows:
        the updates touching it sit in a contiguous window of the
        (compacted, slot-sorted) update array, at most T long
        -> load block + window into VMEM, select per row, write back

Pipeline:
1. Compact: one payload-carrying ``lax.sort`` moves masked-out lanes to
   the tail (key = slot for live updates, S sentinel otherwise), leaving
   live updates sorted by slot and unique; the update array is then
   TRANSPOSED (XLA-side) so the kernel reads (row-vector slots,
   lane-major rows) — rank-2 friendly shapes for Mosaic.
2. Window map: ``searchsorted`` of the T-aligned block boundaries over
   the compacted keys, divided down to block granularity — per state
   block i a scalar sigma[i] such that update-blocks [sigma[i],
   sigma[i]+1] cover every update for block i (<= T updates; any exact
   window start spans at most two aligned T-blocks).
3. One ``pallas_call`` over the S/T state blocks: per window the kernel
   builds the (T, T) match matrix t_slot == w_slot and SELECTS each
   row's matching update by two exact f32 matmuls over the update's
   16-bit halves (at most one match per row, so every dot-product has
   at most one nonzero term — exact in f32 regardless of magnitude).
   Slots are unique and the two windows are disjoint, so summing the
   per-window selections composes them.

HBM traffic: read S + 2B rows, write S rows — bandwidth-bound instead
of per-index-bound.  The state output aliases the state input (in-place
in HBM, composing with the caller's donated buffers).

Mosaic survival rules baked in (learned on v5e, see also
ops/pallas/solver.py): rank-2 everything, no 1-D slices/gathers,
explicit 32-bit literals under jax_enable_x64.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6 exports the context manager at top level
    enable_x64 = jax.enable_x64
except AttributeError:  # older jax: experimental API, same semantics
    from jax.experimental import enable_x64

T = 256          # state rows per block; S must divide by this

_FLAG = os.environ.get("RATELIMITER_BLOCK_SCATTER", "1") == "1"
_INTERPRET = os.environ.get("RATELIMITER_BLOCK_SCATTER_INTERPRET", "0") == "1"
_probe_ok: bool | None = None


def _select_window(eq_f, rows_ref):
    """Per-target-row selected update values for one window.

    eq_f: f32[T, T] 0/1 match matrix (at most one 1 per row).
    rows_ref: i32[lanes, T] window rows, lane-major.
    Returns (vals u32[T, lanes] — zeros where unmatched, hits f32-exact
    via 16-bit halves; match f32[T, 1] row match counts).
    """
    rows = rows_ref[...]
    # 16-bit halves in SIGNED i32 arithmetic (Mosaic crashes on
    # uint32 casts/bitcasts): both halves land in [0, 65535], exact in
    # f32; the left-shift recombine wraps into the sign bit, which is
    # exactly the original bit pattern.
    lo = (rows & jnp.int32(0xFFFF)).astype(jnp.float32)
    hi = ((rows >> jnp.int32(16)) & jnp.int32(0xFFFF)).astype(jnp.float32)
    dn = (((1,), (1,)), ((), ()))  # contract window axis of both
    # HIGHEST precision: the TPU's default bf16 matmul passes would
    # round the 16-bit halves; the 3-pass f32 mode keeps them exact.
    lo_s = jax.lax.dot_general(eq_f, lo, dn,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)
    hi_s = jax.lax.dot_general(eq_f, hi, dn,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)
    match = jnp.sum(eq_f, axis=1, keepdims=True)
    vals = ((hi_s.astype(jnp.int32) << jnp.int32(16))
            | lo_s.astype(jnp.int32))
    return vals, match


def _kernel(sigma_ref, state_ref, sl_a_ref, sl_b_ref, rw_a_ref, rw_b_ref,
            out_ref, *, lanes):
    del lanes  # shapes carry it
    from jax.experimental import pallas as pl

    block = state_ref[...]                       # (T, lanes)
    t_slot = (jnp.int32(T) * pl.program_id(0)
              + jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0))
    eq_a = (sl_a_ref[...] == t_slot).astype(jnp.float32)   # (T, T)
    eq_b = (sl_b_ref[...] == t_slot).astype(jnp.float32)
    va, ma = _select_window(eq_a, rw_a_ref)
    vb, mb = _select_window(eq_b, rw_b_ref)
    # Windows are disjoint and slots unique: at most one nonzero term.
    vals = va | vb
    anym = (ma + mb) > 0.0
    out_ref[...] = jnp.where(anym, vals, block)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _block_scatter(state, upd_slots, upd_rows_t, sigma,
                   interpret: bool = False):
    """state (S, L) i32; upd_slots (1, B) i32 compacted sorted keys;
    upd_rows_t (L, B) i32 lane-major rows; sigma (S/T,) i32 aligned
    window starts (units of T)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_rows, lanes = state.shape
    grid = s_rows // T
    kernel = functools.partial(_kernel, lanes=lanes)
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((T, lanes), lambda i, sig: (i, 0)),
            pl.BlockSpec((1, T), lambda i, sig: (0, sig[i])),
            pl.BlockSpec((1, T), lambda i, sig: (0, sig[i] + 1)),
            pl.BlockSpec((lanes, T), lambda i, sig: (0, sig[i])),
            pl.BlockSpec((lanes, T), lambda i, sig: (0, sig[i] + 1)),
        ],
        out_specs=pl.BlockSpec((T, lanes), lambda i, sig: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=jax.ShapeDtypeStruct(state.shape, state.dtype),
        input_output_aliases={1: 0},  # state buffer updated in place
        interpret=interpret,
    )(sigma, state, upd_slots, upd_slots, upd_rows_t, upd_rows_t)


def scatter_rows(state, sorted_slots, write_mask, rows,
                 interpret: bool | None = None):
    """Drop-in for the XLA drop-mode scatter over sorted-unique writes.

    state i32[S, L]; sorted_slots i32[B] ascending (padding < 0 first);
    write_mask bool[B] with at most one True per slot; rows i32[B, L].
    """
    if interpret is None:
        interpret = _INTERPRET
    s_rows, lanes = state.shape
    n = sorted_slots.shape[0]
    # Trace with 64-bit disabled: every value here is explicit int32, but
    # under jax_enable_x64 the grid/BlockSpec index plumbing emits i64
    # index arithmetic that crashes the TPU compiler outright (any
    # grid-ful pallas_call does, even a block copy — found on v5e).
    with enable_x64(False):
        key = jnp.where(write_mask, sorted_slots, jnp.int32(s_rows))
        ops = jax.lax.sort(
            (key,) + tuple(rows[:, j] for j in range(lanes)), num_keys=1)
        upd_rows_t = jnp.stack(ops[1:], axis=0)  # (L, B), lane-major
        return _windowed_call(state, ops[0], upd_rows_t, interpret)


def _windowed_call(state, key_sorted, upd_rows_t, interpret):
    """Shared tail of both entry points: block-aligned window map over
    the sorted key lane, then the pallas_call."""
    s_rows, _ = state.shape
    n = key_sorted.shape[0]
    bounds = jnp.arange(s_rows // T, dtype=jnp.int32) * T
    starts = jnp.searchsorted(key_sorted, bounds).astype(jnp.int32)
    sigma = jnp.clip(starts // T, 0, n // T - 2)
    return _block_scatter(state, key_sorted.reshape(1, n), upd_rows_t,
                          sigma, interpret=interpret)


def scatter_rows_presorted(state, sorted_slots, write_mask, rows,
                           interpret: bool | None = None):
    """:func:`scatter_rows` minus the compaction sort, for callers whose
    live updates already arrive sorted by slot with every masked-out
    lane at the TAIL (the host-sorted digest path — the C index sorts
    uniques before dispatch).  Skipping the ``lax.sort`` removes both
    its runtime and its super-linear XLA:TPU compile cliff, so this
    path has no practical lane-count ceiling."""
    if interpret is None:
        interpret = _INTERPRET
    s_rows, lanes = state.shape
    with enable_x64(False):
        # Masked lanes are at the tail, so mapping them to the sentinel
        # (s_rows) preserves ascending order.
        key = jnp.where(write_mask, sorted_slots, jnp.int32(s_rows))
        return _windowed_call(state, key, rows.T, interpret)


def align_slots(n: int) -> int:
    """Smallest multiple of the block size T at or above ``n`` — the
    num_slots alignment that lets the dense sweeps engage (supported()
    requires state rows %% T == 0).  Benchmarks and deployments that
    want the presorted digest path should size their tables with
    this."""
    return -(-int(n) // T) * T


def supported(state_shape, batch: int) -> bool:
    """Static geometry gate: aligned table, window-coverable batch."""
    try:
        from jax.experimental import pallas as pl  # noqa: F401
    except Exception:  # noqa: BLE001
        return False
    s_rows = state_shape[0]
    return (s_rows % T == 0 and s_rows // T >= 1
            and batch >= 2 * T and batch % T == 0)


def _probe() -> bool:
    """One-time self-check on this platform: tiny scatter vs XLA truth."""
    global _probe_ok
    if _probe_ok is None:
        try:
            rng = np.random.default_rng(7)
            s = jnp.asarray(rng.integers(0, 1 << 30, (2 * T, 3), np.int32))
            slots = np.sort(rng.choice(2 * T, size=2 * T, replace=True))
            mask = np.r_[np.diff(slots) != 0, True]
            rows = rng.integers(-(1 << 30), 1 << 30, (2 * T, 3), np.int32)
            got = np.asarray(scatter_rows(
                s, jnp.asarray(slots.astype(np.int32)), jnp.asarray(mask),
                jnp.asarray(rows), interpret=_INTERPRET))
            want = np.asarray(s).copy()
            want[slots[mask]] = rows[mask]
            _probe_ok = bool((got == want).all())
        except Exception:  # noqa: BLE001 — any lowering failure => fallback
            _probe_ok = False
    return _probe_ok


def _measure_ab() -> dict:
    """Timed A/B of the dense sweep vs XLA's drop-mode scatter at a
    representative sorted-unique digest shape (chained inside one jit,
    one fetched checksum — the device_rates.py method)."""
    import time

    s_rows, b, k_steps = 1 << 17, 1 << 15, 8
    rng = np.random.default_rng(3)
    slots = np.sort(rng.choice(s_rows, size=b, replace=False)
                    ).astype(np.int32)
    mask = np.ones(b, dtype=bool)
    slots_j, mask_j = jnp.asarray(slots), jnp.asarray(mask)
    rows = jnp.asarray(rng.integers(-(1 << 30), 1 << 30, (b, 4), np.int32))

    def xla_scatter(state, rows):
        widx = jnp.where(mask_j, slots_j, jnp.int32(s_rows))
        return state.at[widx].set(rows, mode="drop")

    def pallas_scatter(state, rows):
        return scatter_rows_presorted(state, slots_j, mask_j, rows,
                                      interpret=_INTERPRET)

    def best_of(fn):
        import functools as ft

        @ft.partial(jax.jit, donate_argnums=0)
        def chain(state, rows):
            def body(i, st):
                return fn(st, rows + i.astype(jnp.int32))

            st = jax.lax.fori_loop(0, k_steps, body, state)
            return st, jnp.sum(st[:8].astype(jnp.int64))

        st, acc = chain(jnp.zeros((s_rows, 4), jnp.int32), rows)
        int(np.asarray(acc))  # compile + settle
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            st, acc = chain(st, rows)
            int(np.asarray(acc))
            best = min(best, time.perf_counter() - t0)
        return best / k_steps

    return {"pallas_s": best_of(pallas_scatter),
            "xla_s": best_of(xla_scatter),
            "updates": b, "state_rows": s_rows}


def _elected() -> bool:
    """Measured per-path election (ops/pallas/election.py): the sweep
    only serves where it beats XLA's per-index scatter on THIS device."""
    from ratelimiter_tpu.ops.pallas import election

    return election.measured_election("block_scatter", _measure_ab,
                                      interpret=_INTERPRET)


def settle() -> bool:
    """Resolve the support probe (and the measured election) eagerly
    (engine init calls this before any step kernel compiles — a probe
    firing lazily inside another program's lowering would nest remote
    compiles).  Respects the RATELIMITER_BLOCK_SCATTER kill switch:
    disabled means no Pallas compile at all."""
    if not _FLAG:
        return False
    if not (_INTERPRET or jax.default_backend() == "tpu"):
        return False
    return _probe() and _elected()


def enabled(state_shape, batch: int) -> bool:
    if not _FLAG or not supported(state_shape, batch):
        return False
    if not (_INTERPRET or jax.default_backend() == "tpu"):
        return False
    return _probe() and _elected()
