"""Fused Pallas TPU relay-step kernel: gather -> update -> scatter in ONE pass.

The digest relay step (ops/relay.py:tb_relay_counts / sw_relay_counts)
is the streaming hot path's dominant device dispatch, and as composed
XLA it crosses HBM three times per chunk: a row gather of the touched
slots, the elementwise decision math, and a scatter of the new rows
(the dense presorted sweep of ops/pallas/block_scatter.py at best).
This kernel does the whole step in one memory-resident pass over the
state array:

    for each aligned block of T consecutive state rows (one grid step):
        the updates touching it sit in a contiguous window of the
        slot-SORTED unique lane, at most T long (slots are unique)
        -> load block + two T-wide windows into VMEM
        -> decode words, match rows to lanes ((T, T) compare)
        -> select each row's segment count by one exact f32 matmul
        -> run the decision math on the rows IN REGISTER
        -> write the block back in place; matmul-select the per-lane
           allowed counts into the window-shaped count outputs

HBM traffic: read S rows + 2 windows, write S rows + counts — the
gather and the scatter are the same pass, so the step's floor is one
read + one write of the state instead of gather + sweep-read + write.

Window map: identical to block_scatter.py — ``searchsorted`` of the
T-aligned block bounds over the sorted uword lane gives a scalar
sigma[i] per state block such that update windows [sigma[i],
sigma[i]+1] cover every lane whose slot lands in block i.  sigma is
non-decreasing, so the two count outputs (window-a hits and window-b
hits) revisit their blocks only consecutively — a first-visit select
accumulates multi-step hits and an XLA-side visited mask zeroes blocks
no grid step wrote.  Every lane matches in exactly one (step, window)
role, so the two outputs sum to the per-unique allowed counts.

64-bit arithmetic: Mosaic has no i64, so the fixed-point token-bucket
refill and the sliding-window bucket math (the EXACT semantics of
semantics/oracle.py, via ops/token_bucket.py / ops/sliding_window.py)
run as two-lane i32 pairs: add/sub with manual carries, 16-bit-limb
multiplies, and two division strategies — ``u // TOKEN_FP_ONE``
reduces to a constant shift plus an i32 divide-by-1000 (done as an f32
reciprocal estimate with exact integer correction, valid because the
quotient only matters when it is below the segment count < 2^21), and
the sliding window's ``(prev * (win - rem)) // win`` runs a 31-step
vectorized binary search on the quotient (exact by construction; the
VPU cost is noise next to the HBM sweep).  Preconditions the engine
already maintains: counters non-negative, max_permits <= 2^31 - 1
(config validation), rank_bits <= 21 (num_slots >= 2T implies it).

Scope (the "geometry allows" gate): the classic counts wire format,
slot-sorted uniques, scalar tenant id — exactly the headline digest
dispatch.  Multi-tenant lanes (the ``_resident`` variant) would need a
per-row policy gather the window structure cannot express without
per-lid limb matmuls, and the split format's two lane sets are sorted
per set, not merged — both fall back to the composed-XLA step, elected
per path like everything else (ops/pallas/election.py).

Mosaic survival rules (see block_scatter.py, learned on v5e): rank-2
everything, no 1-D slices/gathers/concats, explicit 32-bit literals,
trace under enable_x64(False).
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

try:  # jax >= 0.6 exports the context manager at top level
    enable_x64 = jax.enable_x64
except AttributeError:  # older jax: experimental API, same semantics
    from jax.experimental import enable_x64

T = 256          # state rows per block; num_slots must divide by this

_FLAG = os.environ.get("RATELIMITER_RELAY_FUSED", "1") == "1"
_INTERPRET = os.environ.get(
    "RATELIMITER_RELAY_FUSED_INTERPRET", "0") == "1"
_probe_ok: bool | None = None
# Fallback observability (PR 4 silent-degrade fix): a probe failure on
# real hardware means the fused kernel silently stops serving — record
# why, warn ONCE, and surface it via fallback_info() so /actuator/health
# and the ratelimiter.pallas.fused_fallback gauge can report it.
_fallback_reason: str | None = None
_warned = False


def _note_fallback(reason: str) -> None:
    global _fallback_reason, _warned
    _fallback_reason = reason
    try:
        from ratelimiter_tpu.observability import flight_recorder

        flight_recorder().record("pallas.fused_fallback", reason=reason)
    except Exception:  # noqa: BLE001 — observability must not break serving
        pass
    if not _warned:
        _warned = True
        from ratelimiter_tpu.utils.logging import get_logger

        get_logger("pallas.relay_step").warning(
            "fused Pallas relay step not serving (%s); decisions fall "
            "back to the composed XLA step — see "
            "ratelimiter.pallas.fused_fallback and "
            "pallas.relay_fused_live in /actuator/health", reason)


def fallback_info() -> dict:
    """Live/fallback status of the fused relay step for health payloads
    and metrics (reads only already-settled state — never triggers a
    probe or compile).

    ``relay_fused_live`` — the kernel will serve eligible dispatches;
    ``probe_failed`` — the differential probe failed on this hardware
    (the silent-degrade trap: supported platform, losing kernel);
    ``reason`` — why the kernel is not live, when it is not.
    """
    import jax

    platform_ok = _INTERPRET or jax.default_backend() == "tpu"
    elected = None
    if _probe_ok:
        from ratelimiter_tpu.ops.pallas import election

        verdict = election.report().get("relay_fused")
        elected = None if verdict is None else bool(verdict["elected"])
    live = bool(_FLAG and platform_ok and _probe_ok and elected)
    reason = None
    if not live:
        if not _FLAG:
            reason = "disabled (RATELIMITER_RELAY_FUSED=0)"
        elif _probe_ok is False:
            # The trap this exists for: supported platform, losing
            # kernel — outranks every other explanation.
            reason = _fallback_reason or "probe failed"
        elif not platform_ok:
            reason = f"platform {jax.default_backend()} (TPU-only kernel)"
        elif _probe_ok is None:
            reason = "not probed yet"
        elif elected is None:
            reason = "not elected yet"
        else:
            reason = "election lost (XLA measured faster)"
    return {"relay_fused_live": live,
            "probe_failed": _probe_ok is False,
            "reason": reason}

_SIGN = -2147483648   # 0x80000000 as i32
_M16 = 0xFFFF
_FP_ONE_I32 = 1048576000    # 1000 << 20 == core.config.TOKEN_FP_ONE


# ---------------------------------------------------------------------------
# i64-as-i32-pair arithmetic (hi, lo), lo unsigned.  All helpers are
# elementwise over rank-2 arrays and broadcast scalars freely.
# ---------------------------------------------------------------------------

def _i32(v):
    return jnp.int32(v)


def _lshr(x, k: int):
    """Logical right shift by a static k in [1, 31]."""
    return (x >> _i32(k)) & _i32((1 << (32 - k)) - 1)


def _ult(a, b):
    """Unsigned a < b on i32 bit patterns."""
    return (a ^ _i32(_SIGN)) < (b ^ _i32(_SIGN))


def _add64(ah, al, bh, bl):
    lo = al + bl
    hi = ah + bh + _ult(lo, bl).astype(jnp.int32)
    return hi, lo


def _sub64(ah, al, bh, bl):
    lo = al - bl
    hi = ah - bh - _ult(al, bl).astype(jnp.int32)
    return hi, lo


def _lt64(ah, al, bh, bl):
    return (ah < bh) | ((ah == bh) & _ult(al, bl))


def _ge64(ah, al, bh, bl):
    return ~_lt64(ah, al, bh, bl)


def _eq64(ah, al, bh, bl):
    return (ah == bh) & (al == bl)


def _sel64(cond, a, b):
    return jnp.where(cond, a[0], b[0]), jnp.where(cond, a[1], b[1])


def _min64(a, b):
    return _sel64(_lt64(a[0], a[1], b[0], b[1]), a, b)


def _mulu32(a, b):
    """Unsigned 32x32 -> 64 as (hi, lo), via 16-bit limbs (i32 products
    of 16-bit limbs are exact; wraps only discard bits above 2^32)."""
    m16 = _i32(_M16)
    a0, a1 = a & m16, _lshr(a, 16)
    b0, b1 = b & m16, _lshr(b, 16)
    p00 = a0 * b0
    p01 = a0 * b1
    p10 = a1 * b0
    p11 = a1 * b1
    mid = _lshr(p00, 16) + (p01 & m16) + (p10 & m16)   # < 3 * 2^16
    lo = (mid << _i32(16)) | (p00 & m16)
    hi = p11 + _lshr(p01, 16) + _lshr(p10, 16) + _lshr(mid, 16)
    return hi, lo


def _mul64(ah, al, bh, bl):
    """Low 64 bits of the 64x64 product (exact mod 2^64 — callers bound
    true products below 2^63)."""
    hi, lo = _mulu32(al, bl)
    return hi + al * bh + ah * bl, lo


def _shr64(ah, al, k: int):
    """Arithmetic 64-bit right shift by static k in [1, 31]."""
    return ah >> _i32(k), _lshr(al, k) | (ah << _i32(32 - k))


def _shl64_of_u32(x, k: int):
    """(0, x) << k for non-negative x, static k in [1, 31]."""
    return _lshr(x, 32 - k), x << _i32(k)


def _sx(x):
    """Sign-extend i32 -> pair (matches XLA's .astype(int64) on lanes)."""
    return x >> _i32(31), x


def _div1000(n):
    """Exact n // 1000 for i32 0 <= n < 2^31: f32 reciprocal estimate
    (abs error < 0.5), then integer correction by +-1."""
    q = jnp.floor(n.astype(jnp.float32)
                  * jnp.float32(0.001)).astype(jnp.int32)
    q = jnp.where((q + _i32(1)) * _i32(1000) <= n, q + _i32(1), q)
    q = jnp.where(q * _i32(1000) > n, q - _i32(1), q)
    return q


def _div64_by_u32(ph, pl, d):
    """floor(p / d) for a non-negative 64-bit pair p whose quotient fits
    31 bits, d a positive i32 scalar: binary search on the quotient —
    exact with no magic-number proof obligations; 31 static rounds of
    limb-multiply + compare on the VPU."""
    q = jnp.zeros_like(pl)
    for k in range(30, -1, -1):
        cand = q | _i32(1 << k)
        ch, cl = _mulu32(cand, d)
        ok = _ge64(ph, pl, ch, cl)
        q = jnp.where(ok, cand, q)
    return q


# ---------------------------------------------------------------------------
# Kernel
# ---------------------------------------------------------------------------

def _f32_dot(a, b, contract_a: int, contract_b: int):
    """Exact f32 matmul (values < 2^24, at most one nonzero term per
    output element — same argument as block_scatter._select_window)."""
    dn = (((contract_a,), (contract_b,)), ((), ()))
    return jax.lax.dot_general(a, b, dn,
                               precision=jax.lax.Precision.HIGHEST,
                               preferred_element_type=jnp.float32)


def _decode_window(uw, rank_bits: int):
    """(1, T) i32 uword bit patterns -> (slot, count) i32 (1, T).
    Padding (0xFFFFFFFF) decodes to the max slot-field value, which is
    >= num_slots for every legal layout — it can never match a row."""
    slot = _lshr(uw, rank_bits + 1)
    count = _lshr(uw, 1) & _i32((1 << rank_bits) - 1)
    return slot, count


def _par64(params_ref, j: int):
    """j-th logical i64 param as an (hi, lo) scalar pair."""
    return params_ref[2 * j + 1], params_ref[2 * j]


def _tb_row_update(block, cnt_row, params_ref):
    """Token-bucket decision math on T state rows at once (exact i64
    semantics of ops/relay.py:_tb_counts_core via pair arithmetic).
    Returns (new column list [tok_lo, tok_hi, last_lo, last_hi],
    n_allowed i32 (T, 1))."""
    tok = (block[:, 1:2], block[:, 0:1])    # (hi, lo)
    last = (block[:, 3:4], block[:, 2:3])
    pre_ok = params_ref[0] != _i32(0)
    now = _par64(params_ref, 1)
    now1 = _par64(params_ref, 2)
    cap = _par64(params_ref, 3)
    rate = _par64(params_ref, 4)
    ecap = _par64(params_ref, 5)
    ttl2 = _par64(params_ref, 6)

    dl = _add64(last[0], last[1], ttl2[0], ttl2[1])
    expired = (((last[0] == _i32(0)) & (last[1] == _i32(0)))
               | _ge64(now[0], now[1], dl[0], dl[1]))
    v0 = _sel64(expired, cap, tok)
    last_e = _sel64(expired, now, last)
    el = _sub64(now[0], now[1], last_e[0], last_e[1])
    el = _sel64(_lt64(el[0], el[1], _i32(0), _i32(0)),
                (_i32(0), _i32(0)), el)
    el = _sel64(_lt64(ecap[0], ecap[1], el[0], el[1]), ecap, el)
    refill = _mul64(el[0], el[1], rate[0], rate[1])
    v1 = _min64(cap, _add64(v0[0], v0[1], refill[0], refill[1]))

    u = _sub64(v1[0], v1[1], _i32(0), _i32(_FP_ONE_I32))
    u_ok = _ge64(u[0], u[1], _i32(0), _i32(0)) & pre_ok
    u2h, u2l = _shr64(u[0], u[1], 20)         # u // 2^20 (u >= 0 branch)
    c1000 = (cnt_row - _i32(1)) * _i32(1000)  # < 2^31 (rank_bits <= 21)
    # avail >= count  <=>  u2 >= (count-1)*1000; below that u2 fits i32.
    avail_ge = _ge64(u2h, u2l, c1000 >> _i32(31), c1000)
    avail_small = _div1000(u2l) + _i32(1)
    avail = jnp.where(u_ok,
                      jnp.where(avail_ge, cnt_row, avail_small), _i32(0))
    n_alw = jnp.minimum(avail, cnt_row)
    any_inc = n_alw > _i32(0)
    cons = _shl64_of_u32(n_alw * _i32(1000), 20)
    tok_new = _sel64(any_inc,
                     _sub64(v1[0], v1[1], cons[0], cons[1]), tok)
    last_new = _sel64(any_inc, now1, last)
    return [tok_new[1], tok_new[0], last_new[1], last_new[0]], n_alw


def _sw_row_update(block, cnt_row, params_ref):
    """Sliding-window decision math on T rows (exact semantics of
    ops/relay.py:_sw_counts_core).  Returns (new column list [ws_lo,
    ws_hi, curr, prev, cdl_off, pdl_off], tot i32 (T, 1))."""
    win = params_ref[0]          # i32 scalars (validated <= 2^30)
    maxp = params_ref[2]
    wmr = params_ref[4]          # win - now % win
    now = _par64(params_ref, 3)
    cws = _par64(params_ref, 4)
    cwsmw = _par64(params_ref, 5)   # curr_ws - win
    npw = _par64(params_ref, 6)     # now + win
    ws = (block[:, 1:2], block[:, 0:1])
    curr = block[:, 2:3]
    prev = block[:, 3:4]
    cdl = _add64(ws[0], ws[1], _i32(0), block[:, 4:5])
    pdl = _add64(ws[0], ws[1], _i32(0), block[:, 5:6])

    same = _eq64(ws[0], ws[1], cws[0], cws[1])
    next1 = _eq64(ws[0], ws[1], cwsmw[0], cwsmw[1])
    curr_alive = _lt64(now[0], now[1], cdl[0], cdl[1])
    prev_alive = _lt64(now[0], now[1], pdl[0], pdl[1])
    curr_e = jnp.where(same, curr, _i32(0))
    prev_e = jnp.where(same, jnp.where(prev_alive, prev, _i32(0)),
                       jnp.where(next1 & curr_alive, curr, _i32(0)))
    pdle = _sel64(same, pdl, _sel64(next1, cdl, (_i32(0), _i32(0))))

    bp = _mulu32(prev_e, wmr)
    base = _div64_by_u32(bp[0], bp[1], win)
    npass = _sub64(*_sub64(_i32(0), maxp, *_sx(base)), *_sx(curr_e))
    npass_pos = ~_lt64(npass[0], npass[1], _i32(0), _i32(0))
    n_pass = jnp.where(npass_pos, npass[1], _i32(0))  # <= maxp: lo exact
    tot = jnp.minimum(cnt_row, n_pass)
    any_inc = tot > _i32(0)
    curr_new = curr_e + tot
    cdl_new = _sel64(any_inc, npw, _sel64(same, cdl, (_i32(0), _i32(0))))

    def off_of(dl):
        d = _sub64(dl[0], dl[1], cws[0], cws[1])
        return jnp.where(_lt64(d[0], d[1], _i32(0), _i32(0)),
                         _i32(0), d[1])   # alive offsets < 2^31: lo exact

    return [jnp.broadcast_to(cws[1], curr.shape),
            jnp.broadcast_to(cws[0], curr.shape),
            curr_new, prev_e, off_of(cdl_new), off_of(pdle)], tot


def _kernel(sigma_ref, params_ref, state_ref, uwa_ref, uwb_ref,
            out_state_ref, cnt_a_ref, cnt_b_ref, *, algo: str, lanes: int,
            rank_bits: int):
    from jax.experimental import pallas as pl

    i = pl.program_id(0)
    block = state_ref[...]                            # (T, lanes)
    t_slot = (_i32(T) * i
              + jax.lax.broadcasted_iota(jnp.int32, (T, 1), 0))
    slot_a, count_a = _decode_window(uwa_ref[...], rank_bits)
    slot_b, count_b = _decode_window(uwb_ref[...], rank_bits)
    eq_a = (slot_a == t_slot).astype(jnp.float32)     # (T, T): [row, lane]
    eq_b = (slot_b == t_slot).astype(jnp.float32)
    # Per-row segment count + matched flag: one exact f32 select each
    # (slots unique => at most one matching lane per row across BOTH
    # windows, and counts < 2^21 are f32-exact).
    cnt_row = (_f32_dot(eq_a, count_a.astype(jnp.float32), 1, 1)
               + _f32_dot(eq_b, count_b.astype(jnp.float32), 1, 1)
               ).astype(jnp.int32)                    # (T, 1)
    ones = jnp.ones((T, 1), jnp.float32)
    ma = _f32_dot(eq_a, ones, 1, 0)   # ma[t] = lanes of window a at row t
    mb = _f32_dot(eq_b, ones, 1, 0)
    matched = (ma + mb) > jnp.float32(0.0)            # (T, 1)

    if algo == "tb":
        cols, n_alw = _tb_row_update(block, cnt_row, params_ref)
    else:
        cols, n_alw = _sw_row_update(block, cnt_row, params_ref)

    lane_idx = jax.lax.broadcasted_iota(jnp.int32, (T, lanes), 1)
    new_block = block
    for j, col in enumerate(cols):
        new_block = jnp.where(lane_idx == _i32(j), col, new_block)
    out_state_ref[...] = jnp.where(matched, new_block, block)

    # Per-lane counts back in window space: n_alw[t] selected into each
    # window's matching lane ((T,)x(T,1) contraction over rows -> (T,1)
    # per window block).  Consecutive revisits of the same output block
    # accumulate via a first-visit select; blocks never visited are
    # zeroed by the caller's visited mask.
    n_alw_f = jnp.where(matched, n_alw, _i32(0)).astype(jnp.float32)
    out_a = _f32_dot(eq_a, n_alw_f, 0, 0).astype(jnp.int32)   # (T, 1)
    out_b = _f32_dot(eq_b, n_alw_f, 0, 0).astype(jnp.int32)
    mw_a = _f32_dot(eq_a, ones, 0, 0)                         # (T, 1)
    mw_b = _f32_dot(eq_b, ones, 0, 0)
    first = jnp.logical_or(
        i == _i32(0),
        sigma_ref[i] != sigma_ref[jnp.maximum(i - _i32(1), _i32(0))])
    prev_a = jnp.where(first, _i32(0), cnt_a_ref[...])
    prev_b = jnp.where(first, _i32(0), cnt_b_ref[...])
    cnt_a_ref[...] = jnp.where(mw_a > jnp.float32(0.0), out_a, prev_a)
    cnt_b_ref[...] = jnp.where(mw_b > jnp.float32(0.0), out_b, prev_b)


def _call_kernel(algo, state, uwords_i32, sigma, params, rank_bits: int,
                 interpret: bool):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    s_rows, lanes = state.shape
    u = uwords_i32.shape[1]
    kernel = functools.partial(_kernel, algo=algo, lanes=lanes,
                               rank_bits=rank_bits)
    spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_rows // T,),
        in_specs=[
            pl.BlockSpec((T, lanes), lambda i, sig, par: (i, 0)),
            pl.BlockSpec((1, T), lambda i, sig, par: (0, sig[i])),
            pl.BlockSpec((1, T), lambda i, sig, par: (0, sig[i] + 1)),
        ],
        out_specs=[
            pl.BlockSpec((T, lanes), lambda i, sig, par: (i, 0)),
            pl.BlockSpec((T, 1), lambda i, sig, par: (sig[i], 0)),
            pl.BlockSpec((T, 1), lambda i, sig, par: (sig[i] + 1, 0)),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=spec,
        out_shape=[jax.ShapeDtypeStruct(state.shape, state.dtype),
                   jax.ShapeDtypeStruct((u, 1), jnp.int32),
                   jax.ShapeDtypeStruct((u, 1), jnp.int32)],
        input_output_aliases={2: 0},   # state updated in place in HBM
        interpret=interpret,
    )(sigma, params, state, uwords_i32, uwords_i32)


# ---------------------------------------------------------------------------
# Traced entry points (the engine jits these with donate_argnums=0)
# ---------------------------------------------------------------------------

def _pairs_i32(vec64):
    """i64[k] -> i32[2k] as [lo0, hi0, lo1, hi1, ...] (little-endian
    bitcast — computed BEFORE the x64-off scope so the i64 math is
    real)."""
    return jax.lax.bitcast_convert_type(vec64, jnp.int32).reshape(-1)


def _tb_params(table, lid, now):
    cap = table.cap_fp[lid]
    rate = table.rate_fp[lid]
    maxp = table.max_permits[lid]
    ttl2 = table.ttl2_ms[lid]
    vec = jnp.stack([
        (maxp >= 1).astype(jnp.int64),       # 0: pre_ok
        now.astype(jnp.int64),               # 1
        jnp.maximum(now, 1).astype(jnp.int64),   # 2: last_refill write
        cap, rate,                           # 3, 4
        cap // jnp.maximum(rate, 1) + 1,     # 5: elapsed clamp
        ttl2,                                # 6
    ])
    return _pairs_i32(vec)


def _sw_params(table, lid, now):
    maxp = table.max_permits[lid]
    win = table.window_ms[lid]
    now64 = now.astype(jnp.int64)
    rem = now64 % win
    cws = now64 - rem
    vec = jnp.stack([
        win,                                 # 0 (lo slot: i32 scalar)
        maxp,                                # 1? -> see _sw_row_update
        win - rem,                           # 2: wmr
        now64,                               # 3
        cws,                                 # 4
        cws - win,                           # 5
        now64 + win,                         # 6
    ])
    return _pairs_i32(vec)


def _fused_counts(algo, packed, table, uwords, lid, now, *, rank_bits: int,
                  out_dtype=jnp.uint8, interpret: bool = False):
    """Fused replacement for relay.tb_relay_counts / sw_relay_counts with
    ``slots_sorted=True`` and a scalar ``lid`` — bit-identical decisions
    and state (tests/test_pallas_relay.py drives both).  uwords uint32[U]
    slot-ascending with 0xFFFFFFFF padding at the tail; U and the state
    rows must satisfy :func:`supported`."""
    params = (_tb_params if algo == "tb" else _sw_params)(
        table, lid, jnp.asarray(now))
    s_rows, _ = packed.shape
    u = uwords.shape[0]
    with enable_x64(False):
        # Every scalar below is explicitly 32-bit: a weak python-int
        # literal traced in this scope can still materialize as i64 at
        # lowering time (the same trap block_scatter.py documents).
        uw = uwords.reshape(1, u)
        bounds = (jnp.arange(s_rows // T, dtype=jnp.uint32)
                  * jnp.uint32(T << (rank_bits + 1)))
        starts = jnp.searchsorted(uwords, bounds).astype(jnp.int32)
        sigma = jnp.clip(starts // jnp.int32(T), jnp.int32(0),
                         jnp.int32(u // T - 2))
        new_state, cnt_a, cnt_b = _call_kernel(
            algo, packed, jax.lax.bitcast_convert_type(uw, jnp.int32),
            sigma, params, rank_bits, interpret)
        n_w = u // T
        va = jnp.zeros((n_w,), jnp.int32).at[sigma].set(jnp.int32(1))
        vb = jnp.zeros((n_w,), jnp.int32).at[sigma + jnp.int32(1)].set(
            jnp.int32(1))
        cnt = (cnt_a.reshape(n_w, T) * va[:, None]
               + cnt_b.reshape(n_w, T) * vb[:, None]).reshape(u)
        lim = int(jnp.iinfo(out_dtype).max)
        counts = jnp.clip(cnt, jnp.int32(0),
                          jnp.int32(lim)).astype(out_dtype)
    return new_state, counts


def tb_relay_counts_fused(packed, table, uwords, lid, now, *,
                          rank_bits: int, out_dtype=jnp.uint8,
                          interpret: bool = False):
    return _fused_counts("tb", packed, table, uwords, lid, now,
                         rank_bits=rank_bits, out_dtype=out_dtype,
                         interpret=interpret)


def sw_relay_counts_fused(packed, table, uwords, lid, now, *,
                          rank_bits: int, out_dtype=jnp.uint8,
                          interpret: bool = False):
    return _fused_counts("sw", packed, table, uwords, lid, now,
                         rank_bits=rank_bits, out_dtype=out_dtype,
                         interpret=interpret)


# ---------------------------------------------------------------------------
# Gating: static geometry + one-time correctness probe + measured election
# ---------------------------------------------------------------------------

def supported(state_shape, batch: int, rank_bits: int) -> bool:
    """Static geometry gate: T-aligned table, window-coverable sorted
    lane, counts that stay f32/i32-exact (rank_bits <= 21 — implied by
    the >= 2T slot floor for every engine-derived layout, checked anyway
    for hand-built callers)."""
    try:
        from jax.experimental import pallas as pl  # noqa: F401
    except Exception:  # noqa: BLE001
        return False
    s_rows = state_shape[0]
    return (s_rows % T == 0 and s_rows // T >= 1
            and batch >= 2 * T and batch % T == 0
            and 1 <= rank_bits <= 21)


def interpret_mode() -> bool:
    return _INTERPRET


def _probe() -> bool:
    """One-time differential self-check on this platform: a couple of
    populated steps, fused vs composed XLA, both algorithms, exact."""
    global _probe_ok
    if _probe_ok is not None:
        return _probe_ok
    try:
        from ratelimiter_tpu.core.config import RateLimitConfig
        from ratelimiter_tpu.engine.state import LimiterTable
        from ratelimiter_tpu.ops import relay
        from ratelimiter_tpu.ops.sliding_window import make_sw_packed
        from ratelimiter_tpu.ops.token_bucket import make_tb_packed

        rng = np.random.default_rng(13)
        s_rows, u = 2 * T, 2 * T
        rb = 31 - int(s_rows).bit_length()
        table = LimiterTable()
        lid = jnp.int32(table.register(RateLimitConfig(
            max_permits=9, window_ms=1000, refill_rate=4.0)))
        tarr = table.device_arrays
        slots = np.sort(rng.choice(s_rows, size=u - 17,
                                   replace=False)).astype(np.uint32)
        counts = rng.integers(1, 6, u - 17).astype(np.uint32)
        uw = np.full(u, 0xFFFFFFFF, dtype=np.uint32)
        uw[:u - 17] = (slots << np.uint32(rb + 1)) | (counts << np.uint32(1))
        uw_j = jnp.asarray(uw)
        for algo, make in (("tb", make_tb_packed), ("sw", make_sw_packed)):
            ref_fn = (relay.tb_relay_counts if algo == "tb"
                      else relay.sw_relay_counts)
            fused_fn = (tb_relay_counts_fused if algo == "tb"
                        else sw_relay_counts_fused)
            st_ref = make(s_rows)
            # Populate with two composed steps so the probe sees live
            # windows/refills, then compare the third step exactly.
            for now in (1_000_003, 1_000_400):
                st_ref, _ = ref_fn(st_ref, tarr, uw_j, lid,
                                   jnp.int64(now), rank_bits=rb,
                                   slots_sorted=False)
            st_fused = jnp.array(st_ref)  # independent buffer
            now = jnp.int64(1_001_251)
            want_st, want_c = ref_fn(st_ref, tarr, uw_j, lid, now,
                                     rank_bits=rb, slots_sorted=False)
            got_st, got_c = jax.jit(functools.partial(
                fused_fn, rank_bits=rb, interpret=_INTERPRET))(
                    st_fused, tarr, uw_j, lid, now)
            if not (np.array_equal(np.asarray(want_st), np.asarray(got_st))
                    and np.array_equal(np.asarray(want_c),
                                       np.asarray(got_c))):
                _probe_ok = False
                _note_fallback(f"probe mismatch ({algo}): fused output "
                               "diverged from the composed XLA step")
                return False
        _probe_ok = True
    except Exception as exc:  # noqa: BLE001 — any lowering failure => fallback
        _probe_ok = False
        _note_fallback(f"probe error: {type(exc).__name__}: "
                       f"{str(exc)[:160]}")
    return _probe_ok


def _measure_ab() -> dict:
    """Chained-step A/B at a representative digest shape (the same
    chain-K-fetch-one-checksum method as engine/device_rates.py)."""
    import time

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.engine.state import LimiterTable
    from ratelimiter_tpu.ops import relay
    from ratelimiter_tpu.ops.pallas import block_scatter
    from ratelimiter_tpu.ops.token_bucket import make_tb_packed

    s_rows, lanes_u, k_steps = 1 << 18, 1 << 16, 8
    rb = 31 - int(s_rows).bit_length()
    table = LimiterTable()
    lid = jnp.int32(table.register(RateLimitConfig(
        max_permits=100, window_ms=60_000, refill_rate=50.0)))
    tarr = table.device_arrays
    base = np.arange(lanes_u, dtype=np.uint32) * (s_rows // lanes_u)
    uw = jnp.asarray((base << np.uint32(rb + 1)) | np.uint32(1 << 1))
    srt_ok = block_scatter.enabled((s_rows, 4), lanes_u)

    def chain(step):
        @functools.partial(jax.jit, donate_argnums=0)
        def run(packed, now0):
            def body(i, carry):
                packed, acc = carry
                packed, c = step(packed, now0 + i)
                return packed, acc + jnp.sum(c.astype(jnp.int64))

            return jax.lax.fori_loop(0, k_steps, body,
                                     (packed, jnp.int64(0)))

        return run

    def xla_step(packed, now):
        return relay.tb_relay_counts(packed, tarr, uw, lid, now,
                                     rank_bits=rb, slots_sorted=srt_ok)

    def fused_step(packed, now):
        return tb_relay_counts_fused(packed, tarr, uw, lid, now,
                                     rank_bits=rb, interpret=_INTERPRET)

    def best_of(step):
        fn = chain(step)
        packed, acc = fn(make_tb_packed(s_rows), jnp.int64(1_000_000))
        int(np.asarray(acc))  # compile + settle
        best = float("inf")
        for rep in range(3):
            t0 = time.perf_counter()
            packed, acc = fn(packed, jnp.int64(2_000_000 + rep))
            int(np.asarray(acc))
            best = min(best, time.perf_counter() - t0)
        return best / (k_steps * lanes_u)

    return {"pallas_s": best_of(fused_step), "xla_s": best_of(xla_step),
            "uniques": lanes_u, "state_rows": s_rows,
            "xla_sorted_sweep": bool(srt_ok)}


def _elected() -> bool:
    from ratelimiter_tpu.ops.pallas import election

    return election.measured_election("relay_fused", _measure_ab,
                                      interpret=_INTERPRET)


def settle() -> bool:
    """Resolve the support probe + election eagerly (engine init calls
    this before any step kernel compiles).  Respects the
    RATELIMITER_RELAY_FUSED kill switch: disabled means no Pallas
    compile at all.  Returns whether the fused step will actually SERVE
    (supported AND elected)."""
    if not _FLAG:
        return False
    if not (_INTERPRET or jax.default_backend() == "tpu"):
        return False
    if not _probe():
        return False
    return _elected()


def enabled(state_shape, batch: int, rank_bits: int) -> bool:
    """Full per-dispatch gate: flag, platform, geometry, probe, election."""
    if not _FLAG or not supported(state_shape, batch, rank_bits):
        return False
    if not (_INTERPRET or jax.default_backend() == "tpu"):
        return False
    return _probe() and _elected()
