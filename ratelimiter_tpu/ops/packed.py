"""Transfer-minimal step variants: fused outputs and packed-bit scan steps.

The decision kernels are transfer-bound, not compute-bound: on a tunneled
TPU a device->host fetch costs ~100 ms of fixed latency regardless of size,
so the four separate output arrays of ``sw_step``/``tb_step`` cost four
round trips per micro-batch.  Two remedies, both pure wrappers around the
exact same decision math (differential-tested in tests/test_packed.py):

1. **Fused outputs** (``sw_step_fused`` / ``tb_step_fused``): all per-request
   outputs stacked into ONE ``i64[3, B]`` array — one fetch instead of four.
   Used by the engine's dict-returning acquire API.

2. **Scan-of-batches with bit-packed decisions** (``sw_scan_bits`` /
   ``tb_scan_bits``): K consecutive micro-batches executed in one dispatch
   via ``lax.scan`` (sequential semantics *across* sub-batches, exactly like
   K successive flushes), returning only the allow/deny decisions packed to
   1 bit each — ``uint8[K, B/8]``.  One dispatch + one ~K*B/8-byte fetch per
   K*B decisions.  This is the hyperscale hot path: the host learns
   allow/deny (all `tryAcquire` returns — RateLimiter.java:16-26) and
   nothing else; counts/remaining stay device-resident and are served by
   the peek kernels on demand.

Within each wrapper the underlying step is the single source of truth —
these functions contain no decision logic of their own.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ratelimiter_tpu.ops.sliding_window import sw_step_p
from ratelimiter_tpu.ops.token_bucket import tb_step_p

# -- fused full-output steps (one i64[3, B] fetch) ---------------------------
# All wrappers operate on the engine's packed-resident state form
# (i32[S, 6] sliding window, i32[S, 4] token bucket — see the ops modules).


def sw_step_fused(state, table, slots, limiter_ids, permits, now):
    """Row 0: allowed | mutated<<1;  row 1: observed;  row 2: cache_value."""
    state, out = sw_step_p(state, table, slots, limiter_ids, permits, now)
    flags = out.allowed.astype(jnp.int64) | (out.mutated.astype(jnp.int64) << 1)
    return state, jnp.stack([flags, out.observed, out.cache_value])


def tb_step_fused(state, table, slots, limiter_ids, permits, now):
    """Row 0: allowed;  row 1: observed;  row 2: remaining."""
    state, out = tb_step_p(state, table, slots, limiter_ids, permits, now)
    return state, jnp.stack(
        [out.allowed.astype(jnp.int64), out.observed, out.remaining])


def decode_sw_fused(arr):
    """numpy i64[3, B] -> dict matching DeviceEngine.sw_acquire's contract."""
    flags = arr[0]
    return {
        "allowed": (flags & 1).astype(bool),
        "mutated": (flags & 2).astype(bool),
        "observed": arr[1],
        "cache_value": arr[2],
    }


def decode_tb_fused(arr):
    return {
        "allowed": (arr[0] & 1).astype(bool),
        "observed": arr[1],
        "remaining": arr[2],
    }


# -- K-batch scan steps with bit-packed decisions ----------------------------
#
# Shapes: slots i32[K, B]; permits i32[K, B] (or None => all-ones); lids
# either a 0-d i32 (uniform tenant, materialized on device — saves a K*B
# transfer) or i32[K, B]; now i64[K] (non-decreasing batch stamps).
# Returns (new_state, uint8[K, ceil(B/8)]).


def _scan(step, state, table, slots, lids, permits, now):
    uniform_lid = lids.ndim == 0
    unit_permits = permits is None

    def body(st, xs):
        s = xs[0]
        i = 1
        if uniform_lid:
            l = lids  # 0-d: steps take the zero-table-gather scalar path
        else:
            l = xs[i]
            i += 1
        if unit_permits:
            p = jnp.ones(s.shape, dtype=jnp.int64)
        else:
            p = xs[i].astype(jnp.int64)
            i += 1
        t = xs[-1]
        st, out = step(st, table, s, l, p, t)
        return st, jnp.packbits(out.allowed)

    xs = (slots,)
    if not uniform_lid:
        xs += (lids,)
    if not unit_permits:
        xs += (permits,)
    xs += (now,)
    return jax.lax.scan(body, state, xs)


def sw_scan_bits(state, table, slots, lids, permits, now):
    return _scan(sw_step_p, state, table, slots, lids, permits, now)


def tb_scan_bits(state, table, slots, lids, permits, now):
    return _scan(tb_step_p, state, table, slots, lids, permits, now)
