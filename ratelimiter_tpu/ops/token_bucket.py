"""Batched token-bucket decision step (device side).

One invocation is the vectorized equivalent of N executions of the
reference's atomic Lua script (TokenBucketRateLimiter.java:38-68): lazy init
on absent/expired buckets, exact fixed-point refill, sequential-semantics
consume within duplicate-slot segments, and write-back (tokens, last_refill,
TTL=2x window) only for slots where at least one request was allowed — a
fully-denied slot keeps its prior state bit-for-bit, like the Lua deny
branch that performs no writes.

Decision math is the exact fixed-point model of
``semantics/oracle.py:TokenBucketOracle``; requests above bucket capacity
are rejected without touching state (TokenBucketRateLimiter.java:110-116).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

import jax

from ratelimiter_tpu.core.config import TOKEN_FP_ONE, TOKEN_FP_SHIFT
from ratelimiter_tpu.engine.state import TBState, TableArrays
from ratelimiter_tpu.ops.pallas.solver import solve_threshold_recurrence_auto
from ratelimiter_tpu.ops.scatter import scatter_rows_sorted
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.sorting import sort_batch, unsort


# -- packed resident form -----------------------------------------------------
# (tokens_fp, last_refill) live as FOUR i32 lanes [tok_lo, tok_hi, last_lo,
# last_hi]: int64 gathers/scatters lower ~3x slower than int32 on TPU, and
# one row op replaces two flat ones.  Pure bitcast — bit-exact round trip.


def _tb_encode(tokens, last):
    return jnp.concatenate(
        [jax.lax.bitcast_convert_type(tokens, jnp.int32),
         jax.lax.bitcast_convert_type(last, jnp.int32)], axis=-1)


def _tb_decode(rows):
    tokens = jax.lax.bitcast_convert_type(rows[..., 0:2], jnp.int64)
    last = jax.lax.bitcast_convert_type(rows[..., 2:4], jnp.int64)
    return tokens, last


def tb_pack_state(state: TBState) -> jnp.ndarray:
    return _tb_encode(state.tokens_fp, state.last_refill)


def tb_unpack_state(packed: jnp.ndarray) -> TBState:
    return TBState(*_tb_decode(packed))


def make_tb_packed(num_slots: int) -> jnp.ndarray:
    return jnp.zeros((num_slots, 4), dtype=jnp.int32)


class TBOut(NamedTuple):
    allowed: jnp.ndarray    # bool[B]
    observed: jnp.ndarray   # i64[B] — whole tokens available pre-consume
    remaining: jnp.ndarray  # i64[B] — whole tokens after the operation


def _refilled(state_rows, cap, rate, ttl2, now):
    """Lazy-init + exact fixed-point refill (oracle: _refilled).

    Expiry is ``now >= last_refill + ttl2`` — identical to the stored-deadline
    model (deadline was always written as last_refill + ttl2), with
    ``last_refill == 0`` as the absent-key sentinel (fresh slot => expired =>
    lazy init to full capacity, like a missing Redis key).
    """
    tokens, last = state_rows
    expired = (last == 0) | (now >= last + ttl2)
    v0 = jnp.where(expired, cap, tokens)
    last_e = jnp.where(expired, now, last)
    elapsed = jnp.clip(now - last_e, 0, cap // jnp.maximum(rate, 1) + 1)
    return jnp.minimum(cap, v0 + elapsed * rate)


def tb_step_p(
    packed: jnp.ndarray,       # i32[S, 4] — resident packed state
    table: TableArrays,
    slots: jnp.ndarray,        # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray,  # i32[B] or 0-d (uniform tenant)
    permits: jnp.ndarray,      # i64[B]
    now: jnp.ndarray,          # i64 scalar
):
    """Returns (new_packed, TBOut) — jit with donate_argnums=0.

    ``limiter_ids`` may be a 0-d scalar (uniform-tenant batch): the policy
    row is then read once instead of gathered per request — the common hot
    path pays zero table gathers.
    """
    if jnp.ndim(limiter_ids) == 0:
        inv, s, (p,) = sort_batch(slots, permits)
        lid = limiter_ids
    else:
        inv, s, (lid, p) = sort_batch(slots, limiter_ids, permits)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.cap_fp.shape[0] - 1)

    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    maxp = table.max_permits[lidc]
    ttl2 = table.ttl2_ms[lidc]

    rows = _tb_decode(packed[sc])  # one 4-lane i32 row gather
    v1 = _refilled(rows, cap, rate, ttl2, now)

    req = p * TOKEN_FP_ONE
    # Client-side reject above capacity; padding never passes.
    pre_ok = valid & (p <= maxp)
    # inc[j] = [ W[j] + req[j] <= v1 ],  W = fp tokens consumed by prior
    # requests in the segment (all share `now`, so no intra-batch refill —
    # matching the oracle at equal timestamps).
    u = jnp.where(pre_ok, v1 - req, -1)
    first = first_occurrence(s)
    # Exact i32 shift for the optional Pallas path: req is a multiple of
    # 2**TOKEN_FP_SHIFT (see solver docstring).
    inc = solve_threshold_recurrence_auto(u, req, first, shift=TOKEN_FP_SHIFT)
    W = segmented_cumsum_exclusive(req * inc, first)

    v_j = v1 - W                         # fp tokens seen by request j
    allowed = inc == 1
    after = v_j - req * inc              # Lua returns tokens post-op either way

    # Per-segment write-back only where something was allowed.
    lastm = last_occurrence(s) & valid
    tot_w = segment_totals(req * inc, first)
    tot_inc = segment_totals(inc, first)
    any_inc = tot_inc > 0
    tokens_new = jnp.where(any_inc, v1 - tot_w, rows[0])
    # Clamp to >= 1 so a write at epoch instant 0 cannot alias the
    # absent-key sentinel (last_refill == 0); costs at most 1 ms of refill
    # skew for clocks that start exactly at 0.
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])

    # Sorted batch, one surviving write per slot: the shared scatter takes
    # the Pallas dense block-scatter when the geometry allows.
    packed_new = scatter_rows_sorted(
        packed, s, lastm, _tb_encode(tokens_new, last_new))

    out = TBOut(
        allowed=unsort(allowed & valid, inv),
        observed=unsort(v_j // TOKEN_FP_ONE, inv),
        remaining=unsort(after // TOKEN_FP_ONE, inv),
    )
    return packed_new, out


def tb_step(
    state: TBState,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    permits: jnp.ndarray,
    now: jnp.ndarray,
):
    """Tuple-state compatibility wrapper around :func:`tb_step_p` (sharded
    shard_map path and driver entry; the engine runs the packed form)."""
    packed, out = tb_step_p(tb_pack_state(state), table, slots, limiter_ids,
                            permits, now)
    return tb_unpack_state(packed), out


def tb_peek_p(
    packed: jnp.ndarray,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    now: jnp.ndarray,
) -> jnp.ndarray:
    """Read-only refilled whole-token count (the fixed availablePermits —
    quirk Q3 in the reference always crashed here)."""
    sc = jnp.clip(slots, 0, packed.shape[0] - 1)
    lidc = jnp.clip(limiter_ids, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    ttl2 = table.ttl2_ms[lidc]
    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)
    return v1 // TOKEN_FP_ONE


def tb_peek(state: TBState, table, slots, limiter_ids, now) -> jnp.ndarray:
    return tb_peek_p(tb_pack_state(state), table, slots, limiter_ids, now)


def tb_reset_p(packed: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """Zero the given slots (delete bucket, TokenBucketRateLimiter.java:154-158)."""
    n = packed.shape[0]
    widx = jnp.where(slots >= 0, slots, n)
    z = jnp.zeros((slots.shape[0], packed.shape[1]), dtype=jnp.int32)
    return packed.at[widx].set(z, mode="drop")


def tb_reset(state: TBState, slots: jnp.ndarray) -> TBState:
    return tb_unpack_state(tb_reset_p(tb_pack_state(state), slots))
