"""Batched token-bucket decision step (device side).

One invocation is the vectorized equivalent of N executions of the
reference's atomic Lua script (TokenBucketRateLimiter.java:38-68): lazy init
on absent/expired buckets, exact fixed-point refill, sequential-semantics
consume within duplicate-slot segments, and write-back (tokens, last_refill,
TTL=2x window) only for slots where at least one request was allowed — a
fully-denied slot keeps its prior state bit-for-bit, like the Lua deny
branch that performs no writes.

Decision math is the exact fixed-point model of
``semantics/oracle.py:TokenBucketOracle``; requests above bucket capacity
are rejected without touching state (TokenBucketRateLimiter.java:110-116).
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ratelimiter_tpu.core.config import TOKEN_FP_ONE, TOKEN_FP_SHIFT
from ratelimiter_tpu.engine.state import TBState, TableArrays
from ratelimiter_tpu.ops.pallas.solver import solve_threshold_recurrence_auto
from ratelimiter_tpu.ops.rows import (
    gather_rows,
    pack_fields,
    scatter_rows,
    unpack_fields,
)
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.sorting import sort_batch, unsort


class TBOut(NamedTuple):
    allowed: jnp.ndarray    # bool[B]
    observed: jnp.ndarray   # i64[B] — whole tokens available pre-consume
    remaining: jnp.ndarray  # i64[B] — whole tokens after the operation


def _refilled(state_rows, cap, rate, now):
    """Lazy-init + exact fixed-point refill (oracle: _refilled)."""
    tokens, last, dl = state_rows
    expired = now >= dl  # zero state reads as expired -> fresh full bucket
    v0 = jnp.where(expired, cap, tokens)
    last_e = jnp.where(expired, now, last)
    elapsed = jnp.clip(now - last_e, 0, cap // jnp.maximum(rate, 1) + 1)
    return jnp.minimum(cap, v0 + elapsed * rate)


def tb_step(
    state: TBState,
    table: TableArrays,
    slots: jnp.ndarray,        # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray,  # i32[B]
    permits: jnp.ndarray,      # i64[B]
    now: jnp.ndarray,          # i64 scalar
):
    """Returns (new_state, TBOut) — jit with donate_argnums=0.

    ``limiter_ids`` may be a 0-d scalar (uniform-tenant batch): the policy
    row is then read once instead of gathered per request — the common hot
    path pays zero table gathers.
    """
    if jnp.ndim(limiter_ids) == 0:
        inv, s, (p,) = sort_batch(slots, permits)
        lid = limiter_ids
    else:
        inv, s, (lid, p) = sort_batch(slots, limiter_ids, permits)
    valid = s >= 0
    sc = jnp.clip(s, 0, state.tokens_fp.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.cap_fp.shape[0] - 1)

    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    maxp = table.max_permits[lidc]
    ttl2 = table.ttl2_ms[lidc]

    packed = pack_fields(state.tokens_fp, state.last_refill, state.deadline)
    rows = gather_rows(packed, sc, 3)
    v1 = _refilled(rows, cap, rate, now)

    req = p * TOKEN_FP_ONE
    # Client-side reject above capacity; padding never passes.
    pre_ok = valid & (p <= maxp)
    # inc[j] = [ W[j] + req[j] <= v1 ],  W = fp tokens consumed by prior
    # requests in the segment (all share `now`, so no intra-batch refill —
    # matching the oracle at equal timestamps).
    u = jnp.where(pre_ok, v1 - req, -1)
    first = first_occurrence(s)
    # Exact i32 shift for the optional Pallas path: req is a multiple of
    # 2**TOKEN_FP_SHIFT (see solver docstring).
    inc = solve_threshold_recurrence_auto(u, req, first, shift=TOKEN_FP_SHIFT)
    W = segmented_cumsum_exclusive(req * inc, first)

    v_j = v1 - W                         # fp tokens seen by request j
    allowed = inc == 1
    after = v_j - req * inc              # Lua returns tokens post-op either way

    # Per-segment write-back only where something was allowed.
    lastm = last_occurrence(s) & valid
    tot_w = segment_totals(req * inc, first)
    tot_inc = segment_totals(inc, first)
    any_inc = tot_inc > 0
    tokens_new = jnp.where(any_inc, v1 - tot_w, rows[0])
    last_new = jnp.where(any_inc, now, rows[1])
    dl_new = jnp.where(any_inc, now + ttl2, rows[2])

    n_slots = state.tokens_fp.shape[0]
    widx = jnp.where(lastm, sc, n_slots)
    packed_new = scatter_rows(packed, widx, tokens_new, last_new, dl_new)
    new_state = TBState(*unpack_fields(packed_new, 3))

    out = TBOut(
        allowed=unsort(allowed & valid, inv),
        observed=unsort(v_j // TOKEN_FP_ONE, inv),
        remaining=unsort(after // TOKEN_FP_ONE, inv),
    )
    return new_state, out


def tb_peek(
    state: TBState,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    now: jnp.ndarray,
) -> jnp.ndarray:
    """Read-only refilled whole-token count (the fixed availablePermits —
    quirk Q3 in the reference always crashed here)."""
    sc = jnp.clip(slots, 0, state.tokens_fp.shape[0] - 1)
    lidc = jnp.clip(limiter_ids, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    rows = (state.tokens_fp[sc], state.last_refill[sc], state.deadline[sc])
    v1 = _refilled(rows, cap, rate, now)
    return v1 // TOKEN_FP_ONE


def tb_reset(state: TBState, slots: jnp.ndarray) -> TBState:
    """Zero the given slots (delete bucket, TokenBucketRateLimiter.java:154-158)."""
    n = state.tokens_fp.shape[0]
    widx = jnp.where(slots >= 0, slots, n)
    z = jnp.zeros_like(slots, dtype=jnp.int64)
    return TBState(
        tokens_fp=state.tokens_fp.at[widx].set(z, mode="drop"),
        last_refill=state.last_refill.at[widx].set(z, mode="drop"),
        deadline=state.deadline.at[widx].set(z, mode="drop"),
    )
