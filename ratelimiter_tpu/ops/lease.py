"""Batched lease RESERVE / CREDIT steps (device side).

Token leases (leases/) push enforcement to the client: the server
reserves a bounded per-key permit budget in ONE atomic device pass —
gather slot rows -> roll/refill to ``now`` -> greedy segmented grant ->
scatter updated rows — and the client burns the budget locally at memory
speed.  These two steps are the device half of that contract:

- **RESERVE** charges up to ``requested`` permits per key against the
  live counters.  Sliding window: grant ``min(requested, max_permits -
  weighted_estimate)`` and charge the current-window bucket with the
  usual PEXPIRE refresh.  Token bucket: grant ``min(requested,
  refilled_whole_tokens)`` and consume them with the allow-branch
  write-back.  The grant is therefore bounded by the remaining-window
  budget / current tokens — the lease over-admission bound falls out by
  construction.
- **CREDIT** returns unused permits at renewal/release.  Sliding
  window: the decrement applies only while the charged window
  (``grant_ws``) is still current (a rolled window already ages the
  charge out as previous-window weight) and never refreshes the TTL.
  Token bucket: refill-then-add up to capacity; a bucket already at
  capacity stays bit-untouched.

Decision math is the exact integer semantics specified by
``semantics/oracle.py:{SlidingWindowOracle,TokenBucketOracle}.reserve/
credit`` — differential tests drive both on identical streams
(tests/test_leases.py).

Duplicate slots within a batch are granted greedily in sorted order via
the closed form ``grant_j = clip(avail - cumsum_excl(req)_j, 0, req_j)``
(prior requests are fully served until the budget runs out, then
partially, then not at all — exactly the sequential semantics).

The ``host_*_rows`` mirrors restate the same arithmetic over host numpy
rows for engines that reserve via a read-rows -> update -> write-rows
round trip (the sharded mesh engine); callers there pass unique slots
per call (the lease manager reserves one key at a time).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import TOKEN_FP_ONE
from ratelimiter_tpu.engine.state import TableArrays
from ratelimiter_tpu.ops.scatter import scatter_rows_sorted
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.sliding_window import _rolled, _sw_decode, _sw_encode
from ratelimiter_tpu.ops.sorting import sort_batch, unsort
from ratelimiter_tpu.ops.token_bucket import _refilled, _tb_decode, _tb_encode


# -- device steps -------------------------------------------------------------

def sw_reserve_p(
    packed: jnp.ndarray,       # i32[S, 6] — resident packed state
    table: TableArrays,
    slots: jnp.ndarray,        # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray,  # i32[B]
    requested: jnp.ndarray,    # i64[B]; padding 0
    now: jnp.ndarray,          # i64 scalar
):
    """Returns ``(new_packed, granted i64[B], window_start i64[B])`` —
    jit with donate_argnums=0."""
    inv, s, (lid, req) = sort_batch(slots, limiter_ids, requested)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    rem = now % win
    base = (prev_e * (win - rem)) // win
    avail = jnp.maximum(maxp - base - curr_e, 0)

    req = jnp.where(valid, jnp.maximum(req, 0), 0)
    first = first_occurrence(s)
    pre = segmented_cumsum_exclusive(req, first)
    grant = jnp.clip(avail - pre, 0, req)
    tot = segment_totals(grant, first)

    lastm = last_occurrence(s) & valid
    any_g = tot > 0
    curr_new = curr_e + tot
    samew = rows[0] == curr_ws
    # PEXPIRE refresh exactly where an increment would apply it.
    cdl_new = jnp.where(any_g, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e)
    packed_new = scatter_rows_sorted(packed, s, lastm, new_rows)
    return packed_new, unsort(grant, inv), unsort(curr_ws_b, inv)


def sw_credit_p(
    packed: jnp.ndarray,
    table: TableArrays,
    slots: jnp.ndarray,        # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray,  # i32[B]
    credit: jnp.ndarray,       # i64[B]; padding 0
    grant_ws: jnp.ndarray,     # i64[B] — window the charge landed in
    now: jnp.ndarray,
):
    """Returns ``(new_packed, credited i64[B])`` — jit donate_argnums=0."""
    inv, s, (lid, cr, gws) = sort_batch(slots, limiter_ids, credit, grant_ws)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.max_permits.shape[0] - 1)
    win = table.window_ms[lidc]

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    ok = valid & (gws == curr_ws)
    cr = jnp.where(ok, jnp.maximum(cr, 0), 0)
    first = first_occurrence(s)
    pre = segmented_cumsum_exclusive(cr, first)
    credited = jnp.clip(curr_e - pre, 0, cr)
    tot = segment_totals(credited, first)

    # A nonzero credit implies the row is in the charged (current)
    # window — a rolled row reads curr_e == 0 and credits nothing — so
    # written rows always have samew and keep their existing deadline
    # (a credit is not an increment: no TTL refresh).
    lastm = last_occurrence(s) & valid & (tot > 0)
    curr_new = curr_e - tot
    samew = rows[0] == curr_ws
    cdl_keep = jnp.where(samew, rows[2], 0)
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_keep, prev_e, prev_dl_e)
    packed_new = scatter_rows_sorted(packed, s, lastm, new_rows)
    return packed_new, unsort(credited, inv)


def tb_reserve_p(
    packed: jnp.ndarray,       # i32[S, 4]
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    requested: jnp.ndarray,
    now: jnp.ndarray,
):
    """Returns ``(new_packed, granted i64[B], zeros i64[B])`` (the third
    output keeps the reserve surface uniform with the sliding window)."""
    inv, s, (lid, req) = sort_batch(slots, limiter_ids, requested)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    ttl2 = table.ttl2_ms[lidc]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)
    avail = v1 // TOKEN_FP_ONE

    req = jnp.where(valid, jnp.maximum(req, 0), 0)
    first = first_occurrence(s)
    pre = segmented_cumsum_exclusive(req, first)
    grant = jnp.clip(avail - pre, 0, req)
    tot = segment_totals(grant, first)

    lastm = last_occurrence(s) & valid
    any_g = tot > 0
    # Write-back only where something was granted (deny keeps prior
    # state bit-for-bit, like the Lua deny branch / tb_step_p).
    tokens_new = jnp.where(any_g, v1 - tot * TOKEN_FP_ONE, rows[0])
    last_new = jnp.where(any_g, jnp.maximum(now, 1), rows[1])
    packed_new = scatter_rows_sorted(
        packed, s, lastm, _tb_encode(tokens_new, last_new))
    return packed_new, unsort(grant, inv), unsort(
        jnp.zeros_like(grant), inv)


def tb_credit_p(
    packed: jnp.ndarray,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    credit: jnp.ndarray,
    grant_ws: jnp.ndarray,     # ignored (uniform surface)
    now: jnp.ndarray,
):
    """Returns ``(new_packed, credited i64[B])``."""
    del grant_ws
    inv, s, (lid, cr) = sort_batch(slots, limiter_ids, credit)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    ttl2 = table.ttl2_ms[lidc]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)
    gap = jnp.maximum(cap - v1, 0)

    cr_fp = jnp.where(valid, jnp.maximum(cr, 0), 0) * TOKEN_FP_ONE
    first = first_occurrence(s)
    pre = segmented_cumsum_exclusive(cr_fp, first)
    absorbed = jnp.clip(gap - pre, 0, cr_fp)
    tot = segment_totals(absorbed, first)

    # Write-back only where something was absorbed: a bucket already at
    # capacity stays bit-untouched (oracle credit parity).
    lastm = last_occurrence(s) & valid & (tot > 0)
    tokens_new = v1 + tot
    last_new = jnp.broadcast_to(jnp.maximum(now, 1), sc.shape)
    packed_new = scatter_rows_sorted(
        packed, s, lastm, _tb_encode(tokens_new, last_new))
    return packed_new, unsort(absorbed // TOKEN_FP_ONE, inv)


# -- host mirrors (read-rows -> update -> write-rows engines) -----------------
# Exact per-lane restatement of the device arithmetic over decoded host
# rows.  Lanes are independent: callers pass UNIQUE slots per call (the
# lease manager reserves/credits one key at a time).

def _np_pair_i64(rows: np.ndarray, lo: int) -> np.ndarray:
    """Two little-endian i32 lanes -> i64 (bitcast, like the device)."""
    return np.ascontiguousarray(
        rows[:, lo:lo + 2].astype(np.int32)).view(np.int64).ravel()


def _np_i64_pair(vals: np.ndarray) -> np.ndarray:
    """i64[n] -> i32[n, 2] (inverse bitcast)."""
    return np.ascontiguousarray(
        vals.astype(np.int64)).view(np.int32).reshape(-1, 2)


def _sw_host_roll(row, win: int, now: int):
    """Host restatement of sliding_window._rolled for ONE decoded row."""
    ws0, curr0, cdl0, prev0, pdl0 = row
    curr_ws = now - now % win
    if ws0 == curr_ws:
        curr = curr0
        prev = prev0 if now < pdl0 else 0
        prev_dl = pdl0
    elif ws0 == curr_ws - win:
        curr = 0
        prev = curr0 if now < cdl0 else 0
        prev_dl = cdl0
    else:
        curr, prev, prev_dl = 0, 0, 0
    return curr_ws, curr, prev, prev_dl


def _sw_decode_host(rows: np.ndarray):
    ws = _np_pair_i64(rows, 0)
    curr = rows[:, 2].astype(np.int64)
    prev = rows[:, 3].astype(np.int64)
    cdl = ws + rows[:, 4]
    pdl = ws + rows[:, 5]
    return ws, curr, cdl, prev, pdl


def _sw_encode_host(ws, curr, cdl, prev, pdl) -> np.ndarray:
    n = len(ws)
    out = np.empty((n, 6), dtype=np.int32)
    out[:, 0:2] = _np_i64_pair(np.asarray(ws, dtype=np.int64))
    out[:, 2] = np.asarray(curr, dtype=np.int64)
    out[:, 3] = np.asarray(prev, dtype=np.int64)
    out[:, 4] = np.maximum(np.asarray(cdl, dtype=np.int64) - ws, 0)
    out[:, 5] = np.maximum(np.asarray(pdl, dtype=np.int64) - ws, 0)
    return out


def host_reserve_rows(algo: str, rows: np.ndarray, lids, requested,
                      policies, now: int):
    """Reserve over host rows.  ``policies`` maps lid -> (max_permits,
    window_ms, cap_fp, rate_fp, ttl2_ms) (LimiterTable.host_policy).
    Returns ``(granted i64[n], ws i64[n], new_rows, changed bool[n])``."""
    n = len(rows)
    granted = np.zeros(n, dtype=np.int64)
    ws_out = np.zeros(n, dtype=np.int64)
    changed = np.zeros(n, dtype=bool)
    new_rows = np.array(rows, dtype=np.int32, copy=True)
    now = int(now)
    if algo == "sw":
        dec = _sw_decode_host(rows)
        for i in range(n):
            maxp, win, _, _, _ = policies(int(lids[i]))
            row = (int(dec[0][i]), int(dec[1][i]), int(dec[2][i]),
                   int(dec[3][i]), int(dec[4][i]))
            curr_ws, curr, prev, prev_dl = _sw_host_roll(row, win, now)
            base = (prev * (win - now % win)) // win
            g = max(0, min(int(requested[i]), maxp - base - curr))
            cdl = (now + win) if g > 0 else (
                row[2] if row[0] == curr_ws else 0)
            new_rows[i] = _sw_encode_host(
                np.array([curr_ws]), np.array([curr + g]), np.array([cdl]),
                np.array([prev]), np.array([prev_dl]))[0]
            granted[i] = g
            ws_out[i] = curr_ws
            changed[i] = True  # rolled rewrite, like the device scatter
        return granted, ws_out, new_rows, changed
    for i in range(n):
        maxp, win, cap, rate, ttl2 = policies(int(lids[i]))
        tokens = int(_np_pair_i64(rows[i:i + 1], 0)[0])
        last = int(_np_pair_i64(rows[i:i + 1], 2)[0])
        if last == 0 or now >= last + ttl2:
            tokens, last = cap, now
        elapsed = min(max(now - last, 0), cap // max(rate, 1) + 1)
        v1 = min(cap, tokens + elapsed * rate)
        g = max(0, min(int(requested[i]), v1 // TOKEN_FP_ONE))
        granted[i] = g
        if g > 0:
            new_rows[i, 0:2] = _np_i64_pair(
                np.array([v1 - g * TOKEN_FP_ONE]))[0]
            new_rows[i, 2:4] = _np_i64_pair(np.array([max(now, 1)]))[0]
            changed[i] = True
    return granted, ws_out, new_rows, changed


def host_credit_rows(algo: str, rows: np.ndarray, lids, credit, grant_ws,
                     policies, now: int):
    """Credit over host rows; returns ``(credited, new_rows, changed)``."""
    n = len(rows)
    credited = np.zeros(n, dtype=np.int64)
    changed = np.zeros(n, dtype=bool)
    new_rows = np.array(rows, dtype=np.int32, copy=True)
    now = int(now)
    if algo == "sw":
        dec = _sw_decode_host(rows)
        for i in range(n):
            _, win, _, _, _ = policies(int(lids[i]))
            row = (int(dec[0][i]), int(dec[1][i]), int(dec[2][i]),
                   int(dec[3][i]), int(dec[4][i]))
            curr_ws, curr, prev, prev_dl = _sw_host_roll(row, win, now)
            if curr_ws != int(grant_ws[i]) or curr <= 0:
                continue
            c = min(max(int(credit[i]), 0), curr)
            if c <= 0:
                continue
            # curr > 0 implies the row is already in the current window,
            # so the existing deadline is kept (no TTL refresh).
            new_rows[i] = _sw_encode_host(
                np.array([curr_ws]), np.array([curr - c]),
                np.array([row[2]]), np.array([prev]),
                np.array([prev_dl]))[0]
            credited[i] = c
            changed[i] = True
        return credited, new_rows, changed
    for i in range(n):
        _, _, cap, rate, ttl2 = policies(int(lids[i]))
        tokens = int(_np_pair_i64(rows[i:i + 1], 0)[0])
        last = int(_np_pair_i64(rows[i:i + 1], 2)[0])
        if last == 0 or now >= last + ttl2:
            tokens, last = cap, now
        elapsed = min(max(now - last, 0), cap // max(rate, 1) + 1)
        v1 = min(cap, tokens + elapsed * rate)
        absorbed = min(max(int(credit[i]), 0) * TOKEN_FP_ONE, cap - v1)
        if absorbed <= 0:
            continue
        new_rows[i, 0:2] = _np_i64_pair(np.array([v1 + absorbed]))[0]
        new_rows[i, 2:4] = _np_i64_pair(np.array([max(now, 1)]))[0]
        credited[i] = absorbed // TOKEN_FP_ONE
        changed[i] = True
    return credited, new_rows, changed


# Module-level jitted singletons (one compile per (algo, bucket) across
# every engine in the process — the engine/engine.py _MICRO_STEPS rule).
RESERVE_STEPS = {
    "sw": jax.jit(sw_reserve_p, donate_argnums=0),
    "tb": jax.jit(tb_reserve_p, donate_argnums=0),
}
CREDIT_STEPS = {
    "sw": jax.jit(sw_credit_p, donate_argnums=0),
    "tb": jax.jit(tb_credit_p, donate_argnums=0),
}
