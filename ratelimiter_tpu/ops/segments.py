"""Segmented-batch primitives for the device decision kernels.

A micro-batch of ``B`` requests is sorted (stably) by slot id; requests for
the same slot form a contiguous *segment* that must observe sequential
semantics: request ``j`` in a segment sees the effects of requests ``i < j``
(the device-side equivalent of Redis executing one Lua call at a time —
SURVEY.md §7 "Atomicity").

Both algorithms reduce to the same self-referential recurrence

    inc[j] = 1  iff  S[j] <= u[j],     S[j] = sum_{i<j in segment} w[i]*inc[i]

(sliding window: w == 1, u = max - base - permits - c0; token bucket:
w = requested_fp, u = refilled_tokens - requested_fp).  ``S`` depends on
``inc`` which depends on ``S`` — a sequential scan in disguise.  Instead of
scanning (O(B) dependent steps — hopeless on a vector machine), we solve the
recurrence by *monotone sandwich iteration*:

  F(x)[j] = (segcumsum_excl(w*x)[j] <= u[j])  is antitone in x
  (more increments before j  ->  harder for j to pass).

The sequential solution is the unique fixpoint of F (uniqueness: induction on
the first differing index).  Iterate lo <- F(hi), hi <- F(lo) from
lo = zeros, hi = ones: antitonicity keeps lo <= fixpoint <= hi invariant, and
each double-step extends the longest agreed prefix of every segment by at
least one element, so the loop terminates in at most max-segment-length
steps — in practice 2-4 iterations for real traffic (uniform permits
converge on the second pass).  Each iteration is two vectorized cumsums:
O(log B) depth on the VPU, no sequential dependency.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _cumsum(x: jnp.ndarray) -> jnp.ndarray:
    """Log-depth inclusive cumulative sum.

    Explicit ``associative_scan`` instead of ``jnp.cumsum``: XLA's TPU
    lowering of cumulative ops over int64 can fall back to an O(n^2)
    reduce-window that overflows scoped VMEM at realistic batch sizes; the
    associative scan is log-depth elementwise adds, which tile cleanly on
    the VPU.
    """
    return jax.lax.associative_scan(jnp.add, x)


def _cummax(x: jnp.ndarray) -> jnp.ndarray:
    """Log-depth inclusive cumulative maximum (see _cumsum for why)."""
    return jax.lax.associative_scan(jnp.maximum, x)


def first_occurrence(sorted_slots: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask marking the first element of each segment.

    ``sorted_slots`` must be sorted; padding slots (<0) sort first and form
    their own segment.
    """
    prev = jnp.concatenate([sorted_slots[:1] - 1, sorted_slots[:-1]])
    return sorted_slots != prev


def segmented_cumsum_exclusive(x: jnp.ndarray, first: jnp.ndarray) -> jnp.ndarray:
    """Exclusive cumulative sum of non-negative ``x`` within each segment.

    Uses the running-total trick: with x >= 0 the global cumsum is
    non-decreasing, so the segment base (global exclusive cumsum at the
    segment's first element) can be propagated with a running maximum.
    """
    cs = _cumsum(x)
    excl = cs - x
    seg_base = _cummax(jnp.where(first, excl, 0))
    return excl - seg_base


def solve_threshold_recurrence(
    u: jnp.ndarray, w: jnp.ndarray, first: jnp.ndarray
) -> jnp.ndarray:
    """Solve inc[j] = (segcumsum_excl(w*inc)[j] <= u[j]) by sandwich iteration.

    Args:
      u: int64 per-request thresholds; requests that must never pass
         (padding, pre-rejected) should carry a negative value below any
         reachable sum (e.g. -1 works since sums are >= 0... use < 0).
      w: int64 non-negative weights (1 for counting, requested_fp for tokens).
      first: segment-first mask over the sorted batch.

    Returns int64 0/1 vector ``inc`` — the unique sequential solution.

    Fast path: a batch whose live slots are all distinct (every segment has
    length 1 — the common case for uniform key traffic) has the closed form
    inc = (0 <= u); the iteration is skipped via lax.cond.  Padding slots
    all share one segment but carry u < 0, which the closed form also
    rejects, so only duplicates among *live* requests force iteration.
    """
    u = u.astype(jnp.int64)
    w = w.astype(jnp.int64)
    zeros = jnp.zeros_like(u)
    ones = jnp.ones_like(u)

    def F(x):
        s = segmented_cumsum_exclusive(w * x, first)
        return (s <= u).astype(jnp.int64)

    def solve(_):
        def cond(carry):
            lo, hi, it = carry
            return jnp.logical_and(jnp.any(lo != hi), it < u.shape[0] + 2)

        def body(carry):
            lo, hi, it = carry
            return F(hi), F(lo), it + 1

        lo, _, _ = jax.lax.while_loop(cond, body, (zeros, ones, jnp.int64(0)))
        return lo

    def closed_form(_):
        return (u >= 0).astype(jnp.int64)

    # A duplicate exists iff some non-first element passes the threshold
    # check at S=0 or not — structural only: any live (u >= 0) element that
    # is not a segment head implies a multi-element live segment.
    has_dup = jnp.any(jnp.logical_and(~first, u >= 0))
    return jax.lax.cond(has_dup, solve, closed_form, operand=None)


def segment_totals(x: jnp.ndarray, first: jnp.ndarray) -> jnp.ndarray:
    """Inclusive within-segment running sum — at a segment's LAST element this
    is the segment total (used for the single per-slot state write)."""
    return segmented_cumsum_exclusive(x, first) + x


def last_occurrence(sorted_slots: jnp.ndarray) -> jnp.ndarray:
    nxt = jnp.concatenate([sorted_slots[1:], sorted_slots[-1:] + 1])
    return sorted_slots != nxt
