"""Flat mega-batch decision steps — the streaming hot path, rebuilt.

The scan-of-batches path (ops/packed.py) runs K sequential sub-batches per
dispatch for sequential semantics across sub-batches.  But every sub-batch
in one dispatch shares a single timestamp, and at equal timestamps K
sequential sub-batches are decision-identical to ONE flat sorted batch of
K*B requests: a key's requests still form one contiguous segment in arrival
order (stable sort), refill/window-roll at the shared `now` happens once
per slot either way, and a sub-batch that consumed from a slot leaves
exactly the state the flat segment prefix would (tests/test_flat.py drives
both paths on identical streams to prove it).

Flattening unlocks three structural wins over the scan path, each measured
on the tunneled v5e (bench/profile_step.py, B=4M, S=1M):

1. **Payload-carrying sorts** (lax.sort multi-operand, ~17 ms) replace
   argsort + separate 1-lane permutation gathers (~21 ms + 40 ms each for
   the forward and inverse permutes).  The unsort of the decision bits is
   itself a 2-operand sort keyed by the forward order.

2. **Closed-form segment solve** for uniform-permit streams (the
   ``permits=None`` default): within a segment every request carries the
   same weight w and threshold u (one slot == one (limiter, key), so
   policy, refilled balance, and permits are segment-constant), which
   collapses the threshold recurrence

       inc[j] = [ sum_{i<j in seg} w*inc[i] <= u ]

   to ``inc[j] = rank_j * w <= u`` — prior passes before a passing rank
   are exactly ``rank_j``.  No sandwich iteration, no segmented cumsums;
   one log-depth cummax (segment head index) plus elementwise math.
   Weighted per-request permits fall back to the sandwich solver.

3. **One gather / one scatter** of K*B rows instead of K each (same index
   count, but the scatter — 179 ms per 4M rows vs 29 ms for the gather —
   is then replaceable wholesale by the Pallas block-scatter).

Decision math references: semantics/oracle.py (the executable spec);
reference behaviors SlidingWindowRateLimiter.java:86-131 (weighted
two-window estimate, Q1/Q2 quirks) and TokenBucketRateLimiter.java:38-68
(Lua refill/consume, write-only-on-allow).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import TOKEN_FP_ONE
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.pallas.solver import solve_threshold_recurrence_auto
from ratelimiter_tpu.ops.sliding_window import _rolled, _sw_decode, _sw_encode
from ratelimiter_tpu.ops.token_bucket import _refilled, _tb_decode, _tb_encode
from ratelimiter_tpu.ops.scatter import scatter_rows_sorted


def _sort_by_slot(slots, *payloads):
    """Stable multi-operand sort by slot id; payloads ride along (no
    separate permutation gathers).  Returns (sorted_slots, order, sorted
    payloads...); ``order`` is the forward permutation for unsorting."""
    iota = jnp.arange(slots.shape[0], dtype=jnp.int32)
    out = jax.lax.sort((slots, iota) + payloads, num_keys=1, is_stable=True)
    return out[0], out[1], out[2:]


def _unsort_bits(order, allowed):
    """Arrival-order decision bitmask from sorted-order decisions: one
    2-operand sort keyed by the forward order (a permutation), then
    packbits.  Cheaper than a 1-lane inverse-permutation gather."""
    _, back = jax.lax.sort((order, allowed.astype(jnp.uint8)), num_keys=1)
    return jnp.packbits(back)


def _seg_rank(s, first):
    """Rank of each request within its segment (0-based arrival order)."""
    idx = jnp.arange(s.shape[0], dtype=jnp.int32)
    head = jax.lax.associative_scan(jnp.maximum, jnp.where(first, idx, 0))
    return (idx - head).astype(jnp.int64)


def _solve_uniform(u, w, rank, first, permits_none: bool):
    """inc for the recurrence; closed form when weights are segment-uniform
    (permits is None), sandwich solver otherwise.  Returns i64 0/1."""
    if permits_none:
        return (rank * w <= u).astype(jnp.int64)
    return solve_threshold_recurrence_auto(u, w, first)


# ---------------------------------------------------------------------------
# Token bucket
# ---------------------------------------------------------------------------

def tb_flat_bits(packed, table, slots, lids, permits, now):
    """One flat sorted mega-batch of token-bucket decisions.

    slots i32[B] (< 0 = padding/force-deny); lids 0-d i32 or i32[B];
    permits None (unit) or i32[B]; now i64 scalar.  Returns
    (new_packed, uint8[B/8] arrival-order allow bits).  Decisions are
    identical to tb_step_p over the same batch (and to K sequential
    sub-batches at the same `now` — module docstring).
    """
    scalar_lid = jnp.ndim(lids) == 0
    payloads = ()
    if not scalar_lid:
        payloads += (lids,)
    if permits is not None:
        payloads += (permits,)
    s, order, payloads = _sort_by_slot(slots, *payloads)
    payloads = list(payloads)
    lid = lids if scalar_lid else payloads.pop(0)
    p = None if permits is None else payloads.pop(0).astype(jnp.int64)

    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = lid if scalar_lid else jnp.clip(lid, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    maxp = table.max_permits[lidc]
    ttl2 = table.ttl2_ms[lidc]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)

    req = TOKEN_FP_ONE if permits is None else p * TOKEN_FP_ONE
    pre_ok = valid & ((1 if permits is None else p) <= maxp)
    u = jnp.where(pre_ok, v1 - req, jnp.int64(-1))
    first = first_occurrence(s)
    rank = _seg_rank(s, first)
    inc = _solve_uniform(u, req if permits is not None else
                         jnp.int64(TOKEN_FP_ONE), rank, first,
                         permits is None)
    allowed = (inc == 1) & valid

    lastm = last_occurrence(s) & valid
    if permits is None:
        # Segment totals in closed form: the first max(0, u//w + 1) ranks
        # pass, clamped to the segment length (= rank+1 at its last row).
        n_alw = jnp.where(u >= 0,
                          jnp.minimum(rank + 1, u // TOKEN_FP_ONE + 1),
                          jnp.int64(0))
        tot_w = n_alw * TOKEN_FP_ONE
        any_inc = n_alw > 0
    else:
        tot_w = segment_totals(req * inc, first)
        any_inc = segment_totals(inc, first) > 0
    tokens_new = jnp.where(any_inc, v1 - tot_w, rows[0])
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])

    packed_new = scatter_rows_sorted(
        packed, s, lastm, _tb_encode(tokens_new, last_new))
    return packed_new, _unsort_bits(order, allowed)


# ---------------------------------------------------------------------------
# Sliding window
# ---------------------------------------------------------------------------

def sw_flat_bits(packed, table, slots, lids, permits, now):
    """Flat sliding-window counterpart of :func:`tb_flat_bits` (same
    contract; decision math mirrors ops/sliding_window.py:sw_step_p
    including the Q1/Q2 increment-by-1 and post-increment-check quirks)."""
    scalar_lid = jnp.ndim(lids) == 0
    payloads = ()
    if not scalar_lid:
        payloads += (lids,)
    if permits is not None:
        payloads += (permits,)
    s, order, payloads = _sort_by_slot(slots, *payloads)
    payloads = list(payloads)
    lid = lids if scalar_lid else payloads.pop(0)
    p = (jnp.int64(1) if permits is None
         else payloads.pop(0).astype(jnp.int64))

    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = lid if scalar_lid else jnp.clip(
        lid, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    rem = now % win
    base = (prev_e * (win - rem)) // win

    u = jnp.where(valid, maxp - base - curr_e - p, jnp.int64(-1))
    first = first_occurrence(s)
    rank = _seg_rank(s, first)
    inc = _solve_uniform(u, jnp.ones_like(u), rank, first, permits is None)

    if permits is None:
        n_pass = jnp.maximum(u + 1, 0)          # segment-uniform
        S = jnp.minimum(rank, n_pass)           # prior incs at this rank
        tot = jnp.minimum(rank + 1, n_pass)     # segment total at its last
    else:
        S = segmented_cumsum_exclusive(inc, first)
        tot = segment_totals(inc, first)
    c_j = curr_e + S
    allowed = (inc == 1) & (c_j + 1 <= maxp) & valid

    lastm = last_occurrence(s) & valid
    any_inc = tot > 0
    curr_new = curr_e + tot
    samew = rows[0] == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e)

    packed_new = scatter_rows_sorted(packed, s, lastm, new_rows)
    return packed_new, _unsort_bits(order, allowed)
