"""Batch sort/unsort helpers.

Stable argsort by slot id groups duplicate keys into contiguous segments
while preserving arrival order within each segment — the order the
sequential semantics are defined over.

Unsorting uses the inverse permutation with a *gather*: on TPU a scatter
(`zeros.at[order].set(x)`) costs ~3x a gather of the same width, and the
inverse permutation is one extra argsort, which the sort unit does far
cheaper than the scatter unit.  The inverse is computed once per step and
shared by every output.
"""

from __future__ import annotations

import jax.numpy as jnp


def sort_batch(slots: jnp.ndarray, *others: jnp.ndarray):
    """Stable-sort the batch by slot id.

    Returns (inv, sorted_slots, tuple_of_sorted_others) where ``inv`` is the
    inverse permutation (pass to :func:`unsort`).
    """
    order = jnp.argsort(slots, stable=True)
    inv = jnp.argsort(order)  # permutation inverse: order[inv[i]] == i
    return inv, slots[order], tuple(o[order] for o in others)


def unsort(x: jnp.ndarray, inv: jnp.ndarray) -> jnp.ndarray:
    """Invert the sort permutation (gather back to arrival order)."""
    return x[inv]
