"""Batch sort/unsort helpers.

Stable argsort by slot id groups duplicate keys into contiguous segments
while preserving arrival order within each segment — the order the
sequential semantics are defined over.
"""

from __future__ import annotations

import jax.numpy as jnp


def sort_batch(slots: jnp.ndarray, *others: jnp.ndarray):
    """Stable-sort the batch by slot id.

    Returns (order, sorted_slots, tuple_of_sorted_others).
    """
    order = jnp.argsort(slots, stable=True)
    return order, slots[order], tuple(o[order] for o in others)


def unsort(x: jnp.ndarray, order: jnp.ndarray) -> jnp.ndarray:
    """Invert the sort permutation (scatter back to arrival order)."""
    return jnp.zeros_like(x).at[order].set(x)
