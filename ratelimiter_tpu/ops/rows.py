"""Bitcast row-packing for slot-state access.

TPU XLA lowers an int64 gather/scatter to roughly 3x the cost of an int32
one, and pays per array: N separate field arrays mean N gathers + N
scatters per decision step.  These helpers view a set of i64[S] field
arrays as ONE i32[S, 2F] row matrix (pure bitcast + reshape — dense, ~free
at HBM bandwidth) so each step does a single row gather and a single row
scatter regardless of field count.  Values are exactly preserved: the
int64 <-> 2x int32 round trip is a bit-level identity.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def pack_fields(*fields: jnp.ndarray) -> jnp.ndarray:
    """i64[S] x F  ->  i32[S, 2F] (bitcast view, concatenated)."""
    cols = [jax.lax.bitcast_convert_type(f, jnp.int32) for f in fields]  # [S,2]
    return jnp.concatenate(cols, axis=1)


def unpack_fields(packed: jnp.ndarray, n_fields: int):
    """i32[S, 2F] -> tuple of F i64[S] arrays."""
    s = packed.shape[0]
    return tuple(
        jax.lax.bitcast_convert_type(
            packed[:, 2 * i:2 * i + 2].reshape(s, 2), jnp.int64)
        for i in range(n_fields)
    )


def gather_rows(packed: jnp.ndarray, idx: jnp.ndarray, n_fields: int):
    """One i32 row gather; returns F i64[B] field vectors."""
    rows = packed[idx]  # i32[B, 2F]
    b = rows.shape[0]
    return tuple(
        jax.lax.bitcast_convert_type(
            rows[:, 2 * i:2 * i + 2].reshape(b, 2), jnp.int64)
        for i in range(n_fields)
    )


def scatter_rows(packed: jnp.ndarray, idx: jnp.ndarray, *fields: jnp.ndarray):
    """One i32 row scatter of F i64[B] field vectors at ``idx``.

    Out-of-range idx rows are dropped (the padding discipline: callers pass
    an index >= S for lanes that must not write).
    """
    cols = [jax.lax.bitcast_convert_type(f, jnp.int32) for f in fields]  # [B,2]
    rows = jnp.concatenate(cols, axis=1)
    return packed.at[idx].set(rows, mode="drop")
