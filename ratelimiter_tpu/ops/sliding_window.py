"""Batched sliding-window decision step (device side).

One invocation decides a whole micro-batch against the slot-array state:

    gather slot rows -> roll windows forward to `now` -> weighted estimate ->
    segmented sequential-semantics solve -> scatter updated rows

This replaces the reference's per-request chain of 2 Redis GETs + pipelined
INCR/PEXPIRE (SlidingWindowRateLimiter.java:158-180, 114-116;
RedisRateLimitStorage.java:38-49) with one device dispatch for thousands of
decisions.  Decision math is the exact integer semantics specified in
``semantics/oracle.py`` — differential tests drive both on identical streams.

All requests in a batch share one timestamp ``now`` (captured at flush time
by the micro-batcher).  The reference stamps each call individually inside a
<1 ms window; with the batcher's sub-millisecond flush deadline the shared
stamp is the same fidelity at the algorithms' ms granularity, and it is what
makes duplicate-slot segments closed under the threshold recurrence.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

import jax

from ratelimiter_tpu.engine.state import SWState, TableArrays
from ratelimiter_tpu.ops.pallas.solver import solve_threshold_recurrence_auto
from ratelimiter_tpu.ops.scatter import scatter_rows_sorted
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.sorting import sort_batch, unsort


# -- compact row codec --------------------------------------------------------
# The five i64 fields travel through the gather/scatter hot path as SIX i32
# lanes: [ws_lo, ws_hi, curr, prev, cdl_off, pdl_off].  Counts fit i32 by
# construction (counter <= max_permits <= 2^31-1, Java-int parity with the
# reference), and the PEXPIRE deadlines are stored as offsets from the row's
# own win_start (alive offsets < 2*window < 2^31 given the validated
# window_ms bound).  A dead deadline (0) encodes as offset 0, which decodes
# to win_start — in every comparison (`now < deadline` with now >= win_start)
# that value is equally dead, so decisions are unchanged.


def _sw_encode(ws, curr, cdl, prev, pdl):
    """5 x i64[...] -> i32[..., 6] (dense, ~free at HBM bandwidth)."""
    ws32 = jax.lax.bitcast_convert_type(ws, jnp.int32)  # [..., 2]
    cols = [
        ws32,
        curr.astype(jnp.int32)[..., None],
        prev.astype(jnp.int32)[..., None],
        jnp.maximum(cdl - ws, 0).astype(jnp.int32)[..., None],
        jnp.maximum(pdl - ws, 0).astype(jnp.int32)[..., None],
    ]
    return jnp.concatenate(cols, axis=-1)


def _sw_decode(rows):
    """i32[..., 6] -> (ws, curr, cdl, prev, pdl) as i64[...]."""
    ws = jax.lax.bitcast_convert_type(rows[..., 0:2], jnp.int64)
    curr = rows[..., 2].astype(jnp.int64)
    prev = rows[..., 3].astype(jnp.int64)
    cdl = ws + rows[..., 4]
    pdl = ws + rows[..., 5]
    return ws, curr, cdl, prev, pdl


class SWOut(NamedTuple):
    allowed: jnp.ndarray     # bool[B]
    mutated: jnp.ndarray     # bool[B] — whether this request incremented
    observed: jnp.ndarray    # i64[B] — weighted estimate seen by the request
    cache_value: jnp.ndarray # i64[B] — value the host cache should store
                             # (raw counter on increment, estimate on reject —
                             #  mirroring SlidingWindowRateLimiter.java:106-121)


def _rolled(state_rows, win, now):
    """Advance gathered rows to `now`'s window, applying PEXPIRE deadlines."""
    ws0, curr, cdl, prev, pdl = state_rows
    curr_ws = now - now % win
    same = ws0 == curr_ws
    next1 = ws0 == curr_ws - win
    curr_e = jnp.where(same, curr, 0)
    prev_alive = now < pdl
    curr_alive = now < cdl
    prev_e = jnp.where(
        same,
        jnp.where(prev_alive, prev, 0),
        jnp.where(next1 & curr_alive, curr, 0),
    )
    prev_dl_e = jnp.where(same, pdl, jnp.where(next1, cdl, 0))
    return curr_ws, curr_e, prev_e, prev_dl_e


def sw_pack_state(state: SWState) -> jnp.ndarray:
    """SWState (5 x i64[S]) -> resident packed form i32[S, 6]."""
    return _sw_encode(state.win_start, state.curr, state.curr_dl,
                      state.prev, state.prev_dl)


def sw_unpack_state(packed: jnp.ndarray) -> SWState:
    return SWState(*_sw_decode(packed))


def make_sw_packed(num_slots: int) -> jnp.ndarray:
    return jnp.zeros((num_slots, 6), dtype=jnp.int32)


def sw_step_p(
    packed: jnp.ndarray,      # i32[S, 6] — resident packed state
    table: TableArrays,
    slots: jnp.ndarray,       # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray, # i32[B] or 0-d (uniform tenant)
    permits: jnp.ndarray,     # i64[B]
    now: jnp.ndarray,         # i64 scalar
):
    """Returns (new_packed, SWOut) — jit with donate_argnums=0.

    ``limiter_ids`` may be a 0-d scalar (uniform-tenant batch): the policy
    row is read once instead of gathered per request.
    """
    if jnp.ndim(limiter_ids) == 0:
        inv, s, (p,) = sort_batch(slots, permits)
        lid = limiter_ids
    else:
        inv, s, (lid, p) = sort_batch(slots, limiter_ids, permits)
    valid = s >= 0
    sc = jnp.clip(s, 0, packed.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.max_permits.shape[0] - 1)

    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]

    rows = _sw_decode(packed[sc])  # one 6-lane i32 row gather
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)

    # Weighted estimate base: exact integer floor of prev * (1 - rem/win)
    # (spec: semantics/oracle.py:current_count).
    rem = now % win
    base = (prev_e * (win - rem)) // win

    # inc[j] = [ base + curr_e + S[j] + p[j] <= maxp ],  S = prior increments.
    u = jnp.where(valid, maxp - base - curr_e - p, -1)
    first = first_occurrence(s)
    inc = solve_threshold_recurrence_auto(u, jnp.ones_like(u), first)
    S = segmented_cumsum_exclusive(inc, first)

    c_j = curr_e + S                     # raw curr counter seen by request j
    observed = base + c_j                # weighted estimate at request j
    allowed = (inc == 1) & (c_j + 1 <= maxp)
    # Host-cache value parity: raw new counter when incremented, estimate on
    # pre-check rejection (SlidingWindowRateLimiter.java:106-108, 119-121).
    cache_value = jnp.where(inc == 1, c_j + 1, observed)

    # One state write per segment, at its last element.
    lastm = last_occurrence(s) & valid
    tot = segment_totals(inc, first)
    any_inc = tot > 0
    curr_new = curr_e + tot
    ws0 = rows[0]
    samew = ws0 == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))

    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e)
    # Sorted batch, one surviving write per slot: the shared scatter takes
    # the Pallas dense block-scatter when the geometry allows.
    packed_new = scatter_rows_sorted(packed, s, lastm, new_rows)

    out = SWOut(
        allowed=unsort(allowed & valid, inv),
        mutated=unsort((inc == 1) & valid, inv),
        observed=unsort(observed, inv),
        cache_value=unsort(cache_value, inv),
    )
    return packed_new, out


def sw_step(
    state: SWState,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    permits: jnp.ndarray,
    now: jnp.ndarray,
):
    """Tuple-state compatibility wrapper around :func:`sw_step_p` (used by
    the sharded shard_map path and the driver entry; the engine runs the
    packed-resident form directly)."""
    packed, out = sw_step_p(sw_pack_state(state), table, slots, limiter_ids,
                            permits, now)
    return sw_unpack_state(packed), out


def sw_peek_p(
    packed: jnp.ndarray,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    now: jnp.ndarray,
) -> jnp.ndarray:
    """Read-only availablePermits: max(0, maxPermits - estimate)
    (SlidingWindowRateLimiter.java:134-137). No sort needed — no mutation."""
    sc = jnp.clip(slots, 0, packed.shape[0] - 1)
    lidc = jnp.clip(limiter_ids, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]
    rows = _sw_decode(packed[sc])
    _, curr_e, prev_e, _ = _rolled(rows, win, now)
    rem = now % win
    est = curr_e + (prev_e * (win - rem)) // win
    return jnp.maximum(0, maxp - est)


def sw_peek(state: SWState, table, slots, limiter_ids, now) -> jnp.ndarray:
    return sw_peek_p(sw_pack_state(state), table, slots, limiter_ids, now)


def sw_reset_p(packed: jnp.ndarray, slots: jnp.ndarray) -> jnp.ndarray:
    """Zero the given slots (delete curr+prev buckets,
    SlidingWindowRateLimiter.java:140-153). Negative slots are dropped."""
    n = packed.shape[0]
    widx = jnp.where(slots >= 0, slots, n)
    z = jnp.zeros((slots.shape[0], packed.shape[1]), dtype=jnp.int32)
    return packed.at[widx].set(z, mode="drop")


def sw_reset(state: SWState, slots: jnp.ndarray) -> SWState:
    return sw_unpack_state(sw_reset_p(sw_pack_state(state), slots))
