"""Batched sliding-window decision step (device side).

One invocation decides a whole micro-batch against the slot-array state:

    gather slot rows -> roll windows forward to `now` -> weighted estimate ->
    segmented sequential-semantics solve -> scatter updated rows

This replaces the reference's per-request chain of 2 Redis GETs + pipelined
INCR/PEXPIRE (SlidingWindowRateLimiter.java:158-180, 114-116;
RedisRateLimitStorage.java:38-49) with one device dispatch for thousands of
decisions.  Decision math is the exact integer semantics specified in
``semantics/oracle.py`` — differential tests drive both on identical streams.

All requests in a batch share one timestamp ``now`` (captured at flush time
by the micro-batcher).  The reference stamps each call individually inside a
<1 ms window; with the batcher's sub-millisecond flush deadline the shared
stamp is the same fidelity at the algorithms' ms granularity, and it is what
makes duplicate-slot segments closed under the threshold recurrence.
"""

from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ratelimiter_tpu.engine.state import SWState, TableArrays
from ratelimiter_tpu.ops.pallas.solver import solve_threshold_recurrence_auto
from ratelimiter_tpu.ops.rows import (
    gather_rows,
    pack_fields,
    scatter_rows,
    unpack_fields,
)
from ratelimiter_tpu.ops.segments import (
    first_occurrence,
    last_occurrence,
    segment_totals,
    segmented_cumsum_exclusive,
)
from ratelimiter_tpu.ops.sorting import sort_batch, unsort


class SWOut(NamedTuple):
    allowed: jnp.ndarray     # bool[B]
    mutated: jnp.ndarray     # bool[B] — whether this request incremented
    observed: jnp.ndarray    # i64[B] — weighted estimate seen by the request
    cache_value: jnp.ndarray # i64[B] — value the host cache should store
                             # (raw counter on increment, estimate on reject —
                             #  mirroring SlidingWindowRateLimiter.java:106-121)


def _rolled(state_rows, win, now):
    """Advance gathered rows to `now`'s window, applying PEXPIRE deadlines."""
    ws0, curr, cdl, prev, pdl = state_rows
    curr_ws = now - now % win
    same = ws0 == curr_ws
    next1 = ws0 == curr_ws - win
    curr_e = jnp.where(same, curr, 0)
    prev_alive = now < pdl
    curr_alive = now < cdl
    prev_e = jnp.where(
        same,
        jnp.where(prev_alive, prev, 0),
        jnp.where(next1 & curr_alive, curr, 0),
    )
    prev_dl_e = jnp.where(same, pdl, jnp.where(next1, cdl, 0))
    return curr_ws, curr_e, prev_e, prev_dl_e


def sw_step(
    state: SWState,
    table: TableArrays,
    slots: jnp.ndarray,       # i32[B]; < 0 = padding
    limiter_ids: jnp.ndarray, # i32[B]
    permits: jnp.ndarray,     # i64[B]
    now: jnp.ndarray,         # i64 scalar
):
    """Returns (new_state, SWOut) — jit with donate_argnums=0.

    ``limiter_ids`` may be a 0-d scalar (uniform-tenant batch): the policy
    row is read once instead of gathered per request.
    """
    if jnp.ndim(limiter_ids) == 0:
        inv, s, (p,) = sort_batch(slots, permits)
        lid = limiter_ids
    else:
        inv, s, (lid, p) = sort_batch(slots, limiter_ids, permits)
    valid = s >= 0
    sc = jnp.clip(s, 0, state.win_start.shape[0] - 1)
    lidc = jnp.clip(lid, 0, table.max_permits.shape[0] - 1)

    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]

    packed = pack_fields(state.win_start, state.curr, state.curr_dl,
                         state.prev, state.prev_dl)
    rows = gather_rows(packed, sc, 5)
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)

    # Weighted estimate base: exact integer floor of prev * (1 - rem/win)
    # (spec: semantics/oracle.py:current_count).
    rem = now % win
    base = (prev_e * (win - rem)) // win

    # inc[j] = [ base + curr_e + S[j] + p[j] <= maxp ],  S = prior increments.
    u = jnp.where(valid, maxp - base - curr_e - p, -1)
    first = first_occurrence(s)
    inc = solve_threshold_recurrence_auto(u, jnp.ones_like(u), first)
    S = segmented_cumsum_exclusive(inc, first)

    c_j = curr_e + S                     # raw curr counter seen by request j
    observed = base + c_j                # weighted estimate at request j
    allowed = (inc == 1) & (c_j + 1 <= maxp)
    # Host-cache value parity: raw new counter when incremented, estimate on
    # pre-check rejection (SlidingWindowRateLimiter.java:106-108, 119-121).
    cache_value = jnp.where(inc == 1, c_j + 1, observed)

    # One state write per segment, at its last element.
    lastm = last_occurrence(s) & valid
    tot = segment_totals(inc, first)
    any_inc = tot > 0
    curr_new = curr_e + tot
    ws0 = rows[0]
    samew = ws0 == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))

    n_slots = state.win_start.shape[0]
    widx = jnp.where(lastm, sc, n_slots)  # out-of-range -> dropped
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    packed_new = scatter_rows(packed, widx, curr_ws_b, curr_new, cdl_new,
                              prev_e, prev_dl_e)
    new_state = SWState(*unpack_fields(packed_new, 5))

    out = SWOut(
        allowed=unsort(allowed & valid, inv),
        mutated=unsort((inc == 1) & valid, inv),
        observed=unsort(observed, inv),
        cache_value=unsort(cache_value, inv),
    )
    return new_state, out


def sw_peek(
    state: SWState,
    table: TableArrays,
    slots: jnp.ndarray,
    limiter_ids: jnp.ndarray,
    now: jnp.ndarray,
) -> jnp.ndarray:
    """Read-only availablePermits: max(0, maxPermits - estimate)
    (SlidingWindowRateLimiter.java:134-137). No sort needed — no mutation."""
    sc = jnp.clip(slots, 0, state.win_start.shape[0] - 1)
    lidc = jnp.clip(limiter_ids, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]
    rows = (state.win_start[sc], state.curr[sc], state.curr_dl[sc],
            state.prev[sc], state.prev_dl[sc])
    _, curr_e, prev_e, _ = _rolled(rows, win, now)
    rem = now % win
    est = curr_e + (prev_e * (win - rem)) // win
    return jnp.maximum(0, maxp - est)


def sw_reset(state: SWState, slots: jnp.ndarray) -> SWState:
    """Zero the given slots (delete curr+prev buckets,
    SlidingWindowRateLimiter.java:140-153). Negative slots are dropped."""
    n = state.win_start.shape[0]
    widx = jnp.where(slots >= 0, slots, n)
    z = jnp.zeros_like(slots, dtype=jnp.int64)
    return SWState(
        win_start=state.win_start.at[widx].set(z, mode="drop"),
        curr=state.curr.at[widx].set(z, mode="drop"),
        curr_dl=state.curr_dl.at[widx].set(z, mode="drop"),
        prev=state.prev.at[widx].set(z, mode="drop"),
        prev_dl=state.prev_dl.at[widx].set(z, mode="drop"),
    )
