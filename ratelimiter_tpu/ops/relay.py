"""Relay decision steps — the unit-permit streaming hot path.

The host slot index already walks every request of a batch in arrival
order to assign slots, so it can ALSO hand the device each request's
within-batch duplicate rank and each unique slot's segment count for
free (native/slot_index.cpp:assign_batch_uniques — O(1) extra work per
request, epoch-tagged per-slot scratch; :func:`rebuild_words` turns
that digest output into the per-request word stream when needed).  With unit permits the whole threshold
recurrence of the sorted step (ops/flat.py) has a closed form in that
rank: within a segment every request carries the same weight and
threshold, so request j passes iff ``rank_j < avail`` and the slot's
single write needs only the segment length (= rank + 1 at the last
occurrence).  That deletes the device-side sort, segment scans, and
unsort entirely:

    decode word -> gather row -> elementwise math -> masked scatter
                                                  -> packbits

which is the entire step.  On XLA:TPU this matters twice over: the
sort/associative-scan ops the sorted step leans on compile
super-linearly in lane count (minutes at 2M lanes) and run far above the
bandwidth floor, while gather/scatter/elementwise compile in ~1 s at any
size and run near memory speed (bench/profile_compile.py,
bench/profile_ops.py).

Everything about a request travels in ONE uint32 word:

    bit 0                   last-occurrence flag
    bits 1 .. rank_bits     duplicate rank, clamped to 2^rank_bits - 1
                            (the clamp value is a sentinel: the layout
                            guarantees 2^rank_bits - 2 >= every
                            registered limiter's max_permits, and no
                            request with rank above max_permits can ever
                            be allowed, so "clamped" decides as deny)
    bits rank_bits+1 .. 31  slot id; the all-ones padding word decodes
                            to a slot field >= num_slots => invalid lane

so the host->device traffic is 4 bytes/request — the same as the sorted
step's bare slot lane, with the rank riding in bits the slot never uses.

Rank clamping is exact, not approximate: ``avail <= max_permits``
always (token bucket: refilled tokens <= capacity; sliding window:
remaining budget <= max_permits), so any rank at or past the clamp
ceiling compares >= avail and is denied either way, and the write's
``n_allowed = min(seg_len, avail)`` saturates identically.

Decision math references: semantics/oracle.py (the executable spec);
ops/flat.py (the sorted step these decisions are bit-identical to —
tests/test_relay.py drives both on identical streams); reference
behaviors SlidingWindowRateLimiter.java:86-131 and
TokenBucketRateLimiter.java:38-68.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ratelimiter_tpu.core.config import TOKEN_FP_ONE
from ratelimiter_tpu.ops.sliding_window import _rolled, _sw_decode, _sw_encode
from ratelimiter_tpu.ops.token_bucket import _refilled, _tb_decode, _tb_encode


def relay_usable(rank_bits: int, max_permits_registered: int) -> bool:
    """Whether the word layout can carry the engine's traffic: the rank
    clamp ceiling (2^rank_bits - 1, a deny sentinel) must exceed every
    registered limiter's max_permits.  Shared by the single-device and
    sharded engines so the invariant lives in one place."""
    return (rank_bits >= 1
            and (1 << rank_bits) - 2 >= max_permits_registered)


def counts_dtype(max_permits_registered: int):
    """Smallest numpy dtype that can carry per-unique allowed counts
    (None if none fits — the per-request relay path has no such bound)."""
    import numpy as np

    if max_permits_registered <= 255:
        return np.uint8
    if max_permits_registered <= 65535:
        return np.uint16
    return None


def wire_costs(multi_lid: bool, resident_lids: bool = False):
    """(bytes per unique in digest mode, bytes per request in words mode)
    — the constants both stream loops use to elect a mode and to grow
    chunks toward the wire budget.  Digest: 4B uword + 1-2B count back,
    plus a 4B per-unique lid lane for multi-tenant callers that don't
    keep lids device-resident (the single-device loop does — its deltas
    are charged separately; the sharded loop ships the lane).  Words
    mode: 4B word + bits back (+4B lid lane when multi)."""
    digest = 6.0 if (not multi_lid or resident_lids) else 10.0
    return digest, (8.125 if multi_lid else 4.125)


def rebuild_words(uwords, uidx, rank, rank_bits: int):
    """Per-request (slot | clamped rank | last) words from the digest
    output — the words-mode wire format, built host-side in numpy.  For
    an over-clamp segment the flagged lane is the one at rank clamp-1
    rather than the true last; the device write saturates to the same
    value either way (n_allowed = min(avail, seg_len) with avail below
    the clamp)."""
    import numpy as np

    rank_mask = np.uint32((1 << rank_bits) - 1)
    slotf = uwords >> np.uint32(rank_bits + 1)
    cnt_cl = (uwords >> np.uint32(1)) & rank_mask
    return ((slotf[uidx] << np.uint32(rank_bits + 1))
            | (np.minimum(rank.astype(np.uint32), rank_mask)
               << np.uint32(1))
            | (rank.astype(np.uint32) + 1 == cnt_cl[uidx]))


def decode_words(words, rank_bits: int, num_slots: int):
    """uint32[B] -> (slot i32[B], rank i64[B], last bool[B], valid bool[B]).

    Padding lanes (0xFFFFFFFF) decode to slot >= num_slots => invalid.
    """
    w = words.astype(jnp.uint32)
    slot = (w >> (rank_bits + 1)).astype(jnp.int32)
    rank = ((w >> 1) & jnp.uint32((1 << rank_bits) - 1)).astype(jnp.int64)
    last = (w & 1) == 1
    valid = slot < num_slots
    return slot, rank, last, valid


def tb_relay_bits(packed, table, words, lids, now, *, rank_bits: int):
    """One relay batch of unit-permit token-bucket decisions.

    words uint32[B]; lids 0-d i32 (single tenant) or i32[B] lane; now i64
    scalar.  Returns (new_packed, uint8[B/8] arrival-order allow bits).
    Decisions are identical to tb_flat_bits(permits=None) on the same
    batch (tests/test_relay.py).
    """
    num_slots = packed.shape[0]
    slot, rank, last, valid = decode_words(words, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    scalar_lid = jnp.ndim(lids) == 0
    lidc = lids if scalar_lid else jnp.clip(
        lids, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    maxp = table.max_permits[lidc]
    ttl2 = table.ttl2_ms[lidc]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)

    # Segment-uniform closed form (ops/flat.py:tb_flat_bits, permits=None):
    # u = v1 - FP_ONE; request passes iff rank * FP_ONE <= u, i.e.
    # rank < avail with avail = u // FP_ONE + 1 (0 when u < 0).
    pre_ok = valid & (1 <= maxp)
    u = jnp.where(pre_ok, v1 - TOKEN_FP_ONE, jnp.int64(-1))
    avail = jnp.where(u >= 0, u // TOKEN_FP_ONE + 1, jnp.int64(0))
    allowed = valid & (rank < avail)

    # Single write per touched slot, at its last occurrence: seg_len is
    # rank + 1 there (the clamp saturates seg_len and avail coherently).
    seg_len = rank + 1
    n_alw = jnp.minimum(avail, seg_len)
    any_inc = n_alw > 0
    tokens_new = jnp.where(any_inc, v1 - n_alw * TOKEN_FP_ONE, rows[0])
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])

    mask = valid & last
    widx = jnp.where(mask, slot, jnp.int32(num_slots))  # out-of-range drops
    packed_new = packed.at[widx].set(
        _tb_encode(tokens_new, last_new), mode="drop")
    return packed_new, jnp.packbits(allowed)


def tb_relay_counts(packed, table, uwords, lids, now, *, rank_bits: int,
                    out_dtype=jnp.uint8, slots_sorted: bool = False):
    """Segment-digest token-bucket step: one lane per UNIQUE slot.

    uwords uint32[U] carries (slot | clamped segment count); the step
    returns how many of each segment's requests are allowed (`n_allowed`,
    clipped into out_dtype — the caller guarantees every limiter's
    max_permits fits), and the host reconstructs per-request booleans as
    ``rank < n_allowed[uidx]``.  State writes are identical to
    tb_relay_bits on the expanded batch: every valid lane is its own
    last occurrence.  Decision/state math lives in _tb_counts_core —
    shared with the split dispatch so the modes cannot drift.
    """
    num_slots = packed.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    packed_new, n_alw = _tb_counts_core(packed, table, slot, count, valid,
                                        lids, now,
                                        slots_sorted=slots_sorted)
    lim = jnp.int64(jnp.iinfo(out_dtype).max)
    return packed_new, jnp.clip(n_alw, 0, lim).astype(out_dtype)


def sw_relay_counts(packed, table, uwords, lids, now, *, rank_bits: int,
                    out_dtype=jnp.uint8, slots_sorted: bool = False):
    """Segment-digest sliding-window step (see tb_relay_counts).

    The per-request decision ``rank < n_allowed`` is exact: with unit
    permits the Q2 post-increment re-check is implied — n_pass =
    maxp - base - curr_e (when positive) and base >= 0, so any rank
    below n_pass also satisfies curr_e + rank + 1 <= maxp.  The core
    returns tot = min(count, n_pass), which reconstructs identically
    (rank < count always, so rank < tot <=> rank < n_pass).
    """
    num_slots = packed.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    packed_new, tot = _sw_counts_core(packed, table, slot, count, valid,
                                      lids, now, slots_sorted=slots_sorted)
    lim = jnp.int64(jnp.iinfo(out_dtype).max)
    return packed_new, jnp.clip(tot, 0, lim).astype(out_dtype)


def _decode_s3(s3, num_slots):
    """uint8[S, 3] little-endian 24-bit slot plane -> (slot i32[S],
    valid bool[S]).  The 0xFFFFFF padding sentinel decodes to a slot
    >= num_slots (callers gate split mode on num_slots < 2^24)."""
    w = s3.astype(jnp.uint32)
    slot = (w[:, 0] | (w[:, 1] << 8) | (w[:, 2] << 16)).astype(jnp.int32)
    return slot, slot < num_slots


def _relay_counts_split(algo_core, packed, table, s3, mwords, lids, now, *,
                        rank_bits, out_dtype):
    """Split-digest decision step shared by both algorithms (r5).

    Unit-permit digest traffic is mostly SINGLETON uniques (uniform:
    ~80-90% of uniques; Zipf: the tail).  A singleton needs no count
    field on the way in (count == 1) and only an allow BIT on the way
    out — so singles ship as a 3-byte slot plane (s3) and come back as
    packed bits, while multi-count uniques keep the 4-byte uword and
    the count download.  Wire vs classic digest: upload 4 -> 3 B and
    download 1-2 B -> 1/8 B per singleton; decisions and state writes
    are identical (tests/test_relay.py drives all three modes on the
    same chunks).  Both lane sets decide in ONE fused body over their
    concatenation (disjoint slots — singles and multis are different
    uniques), and the result ships as ONE uint8 array
    [packed singles bits | counts bytes] so the drain stays a single
    fetch round trip.
    """
    num_slots = packed.shape[0]
    slot_s, valid_s = _decode_s3(s3, num_slots)
    slot_m, count_m, _, valid_m = decode_words(mwords, rank_bits, num_slots)
    slot = jnp.concatenate([slot_s, slot_m])
    count = jnp.concatenate([jnp.ones_like(slot_s, dtype=jnp.int64),
                             count_m])
    valid = jnp.concatenate([valid_s, valid_m])
    n_s = s3.shape[0]
    packed_new, n_alw = algo_core(packed, table, slot, count, valid, lids,
                                  now)
    bits_s = jnp.packbits(n_alw[:n_s] > 0)
    csize = out_dtype(0).dtype.itemsize  # static (python) at trace time
    counts_m = jnp.clip(n_alw[n_s:], 0,
                        jnp.int64(jnp.iinfo(out_dtype).max)).astype(out_dtype)
    if csize > 1:
        counts_m = jax.lax.bitcast_convert_type(
            counts_m, jnp.uint8).reshape(-1)
    return packed_new, jnp.concatenate([bits_s, counts_m])


def _scatter_rows(packed, slot, valid, new_rows, slots_sorted):
    """Unique-row state write: the dense presorted block sweep when the
    host sorted the uniques by slot (padding decodes to slot >=
    num_slots, at the tail), else XLA's per-index scatter."""
    if slots_sorted:
        from ratelimiter_tpu.ops.scatter import scatter_rows_presorted

        return scatter_rows_presorted(packed, slot, valid, new_rows)
    widx = jnp.where(valid, slot, jnp.int32(packed.shape[0]))
    return packed.at[widx].set(new_rows, mode="drop")


def _tb_counts_core(packed, table, slot, count, valid, lids, now,
                    slots_sorted: bool = False):
    """(new_packed, n_allowed per lane) — THE token-bucket digest body.
    tb_relay_counts (classic uwords) and the split dispatch both decide
    through this, so the two modes cannot drift."""
    sc = jnp.where(valid, slot, 0)
    scalar_lid = jnp.ndim(lids) == 0
    lidc = lids if scalar_lid else jnp.clip(
        lids, 0, table.cap_fp.shape[0] - 1)
    cap = table.cap_fp[lidc]
    rate = table.rate_fp[lidc]
    maxp = table.max_permits[lidc]
    ttl2 = table.ttl2_ms[lidc]
    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)
    pre_ok = valid & (1 <= maxp)
    u = jnp.where(pre_ok, v1 - TOKEN_FP_ONE, jnp.int64(-1))
    avail = jnp.where(u >= 0, u // TOKEN_FP_ONE + 1, jnp.int64(0))
    n_alw = jnp.minimum(avail, count)
    any_inc = n_alw > 0
    tokens_new = jnp.where(any_inc, v1 - n_alw * TOKEN_FP_ONE, rows[0])
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])
    packed_new = _scatter_rows(packed, slot, valid,
                               _tb_encode(tokens_new, last_new),
                               slots_sorted)
    return packed_new, n_alw


def _sw_counts_core(packed, table, slot, count, valid, lids, now,
                    slots_sorted: bool = False):
    """Sliding-window counterpart of :func:`_tb_counts_core` (see
    sw_relay_counts for the derivation, incl. the implied Q2 check).

    Returns tot = min(count, n_pass) per lane: equivalent to n_pass for
    both the bit (tot > 0 <=> n_pass >= 1 for count >= 1) and the count
    reconstruction (rank < min(count, n_pass) <=> rank < n_pass, since
    rank < count by construction)."""
    sc = jnp.where(valid, slot, 0)
    scalar_lid = jnp.ndim(lids) == 0
    lidc = lids if scalar_lid else jnp.clip(
        lids, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]
    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    rem = now % win
    base = (prev_e * (win - rem)) // win
    u = jnp.where(valid, maxp - base - curr_e - 1, jnp.int64(-1))
    n_pass = jnp.maximum(u + 1, 0)
    tot = jnp.minimum(count, n_pass)
    any_inc = tot > 0
    curr_new = curr_e + tot
    samew = rows[0] == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e)
    packed_new = _scatter_rows(packed, slot, valid, new_rows, slots_sorted)
    return packed_new, tot


def tb_relay_counts_split(packed, table, s3, mwords, lids, now, *,
                          rank_bits: int, out_dtype=jnp.uint8):
    return _relay_counts_split(_tb_counts_core, packed, table, s3, mwords,
                               lids, now, rank_bits=rank_bits,
                               out_dtype=out_dtype)


def sw_relay_counts_split(packed, table, s3, mwords, lids, now, *,
                          rank_bits: int, out_dtype=jnp.uint8):
    return _relay_counts_split(_sw_counts_core, packed, table, s3, mwords,
                               lids, now, rank_bits=rank_bits,
                               out_dtype=out_dtype)


def tb_relay_counts_resident(packed, lid_map, table, uwords, delta_slots,
                             delta_lids, now, *, rank_bits: int,
                             out_dtype=jnp.uint8, slots_sorted: bool = False):
    """Digest step with the tenant ids RESIDENT on device.

    One slot is one (limiter, key) pair, so a slot's lid is immutable
    while assigned — the host uploads (slot, lid) pairs only for slots
    whose lid the device doesn't know yet (fresh assignments and
    post-eviction reuse), and the step folds that delta into ``lid_map``
    before deciding.  Steady-state multi-tenant wire cost drops from
    10 B/unique to ~5 (no per-unique lid lane).
    """
    lid_map = lid_map.at[jnp.where(delta_slots >= 0, delta_slots,
                                   lid_map.shape[0])].set(
        delta_lids, mode="drop")
    num_slots = packed.shape[0]
    slot, _, _, valid = decode_words(uwords, rank_bits, num_slots)
    lids = lid_map[jnp.where(valid, slot, 0)]
    packed_new, counts = tb_relay_counts(
        packed, table, uwords, lids, now, rank_bits=rank_bits,
        out_dtype=out_dtype, slots_sorted=slots_sorted)
    return packed_new, lid_map, counts


def sw_relay_counts_resident(packed, lid_map, table, uwords, delta_slots,
                             delta_lids, now, *, rank_bits: int,
                             out_dtype=jnp.uint8, slots_sorted: bool = False):
    """Sliding-window counterpart of :func:`tb_relay_counts_resident`."""
    lid_map = lid_map.at[jnp.where(delta_slots >= 0, delta_slots,
                                   lid_map.shape[0])].set(
        delta_lids, mode="drop")
    num_slots = packed.shape[0]
    slot, _, _, valid = decode_words(uwords, rank_bits, num_slots)
    lids = lid_map[jnp.where(valid, slot, 0)]
    packed_new, counts = sw_relay_counts(
        packed, table, uwords, lids, now, rank_bits=rank_bits,
        out_dtype=out_dtype, slots_sorted=slots_sorted)
    return packed_new, lid_map, counts


def _weighted_step_w(perms_rank, roff, r, count, u_b):
    """Permits of the r-th request of every segment (0 where r >= count).

    The host sorts a chunk's segments by occurrence count DESCENDING and
    ships permits rank-major compacted: all rank-0 permits (in segment
    order), then all rank-1 permits, ...  With that ordering the
    segments active at rank r are a PREFIX of the lane, so each step's
    permits are one contiguous ``dynamic_slice`` at ``roff[r]`` — no
    gathers, no rank-matrix padding, exactly 1 B/request on the wire.
    """
    w = jax.lax.dynamic_slice(perms_rank, (roff[r],),
                              (u_b,)).astype(jnp.int64)
    return jnp.where(r < count, w, jnp.int64(0))


def tb_relay_weighted(packed, table, uwords, perms_rank, roff, lid, now, *,
                      rank_bits: int, r_steps: int):
    """Weighted-permit relay token-bucket step — no sort, no solver.

    uwords uint32[U] carries (slot | segment count) per unique exactly as
    the digest path (padding 0xFFFFFFFF), in COUNT-DESCENDING segment
    order; ``perms_rank`` uint8[N+U] is the chunk's permits rank-major
    compacted (see :func:`_weighted_step_w`); ``roff`` i32[R] the
    per-rank offsets.  A ``lax.scan`` over the ``r_steps`` rank steps
    runs the exact skip recurrence of ops/flat.py:tb_flat_bits (denied
    requests consume nothing) with a U-wide elementwise body — nothing
    here has the super-linear XLA:TPU compile cost of
    sort/associative_scan, so chunks grow to the wire budget like the
    unit-permit relay.

    ``lid`` is a 0-d i32 (single-tenant streams; multi-lid weighted
    streams take the flat path).  Returns (new_packed, packed decision
    bits in the same compact rank-major layout as perms_rank — bit
    [roff[r] + j] decides the r-th request of the j-th count-sorted
    segment, ~1 bit/request); the host reconstructs arrival order via
    its (uidx, rank) scratch and the sort permutation.  Decisions are
    bit-identical to tb_flat_bits on the same chunking
    (tests/test_relay.py).
    """
    num_slots = packed.shape[0]
    u_b = uwords.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    cap = table.cap_fp[lid]
    rate = table.rate_fp[lid]
    maxp = table.max_permits[lid]
    ttl2 = table.ttl2_ms[lid]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)

    def body(carry, r):
        consumed, buf = carry
        w = _weighted_step_w(perms_rank, roff, r, count, u_b)
        w_fp = w * TOKEN_FP_ONE
        ok = (valid & (w >= 1) & (w <= maxp)
              & (consumed + w_fp <= v1))
        # Decisions go back in the SAME compact rank-major layout the
        # permits came in: ascending-r block writes, each fixing the
        # previous write's padding tail (see _weighted_step_w).
        buf = jax.lax.dynamic_update_slice(
            buf, ok.astype(jnp.uint8), (roff[r],))
        return (consumed + jnp.where(ok, w_fp, 0), buf), None

    (consumed, buf), _ = jax.lax.scan(
        body,
        (jnp.zeros_like(v1),
         jnp.zeros(perms_rank.shape[0], dtype=jnp.uint8)),
        jnp.arange(r_steps, dtype=jnp.int64))
    any_inc = consumed > 0
    tokens_new = jnp.where(any_inc, v1 - consumed, rows[0])
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])
    widx = jnp.where(valid & any_inc, slot, jnp.int32(num_slots))
    packed_new = packed.at[widx].set(
        _tb_encode(tokens_new, last_new), mode="drop")
    return packed_new, jnp.packbits(buf)


def sw_relay_weighted(packed, table, uwords, perms_rank, roff, lid, now, *,
                      rank_bits: int, r_steps: int):
    """Weighted-permit relay sliding-window step (see tb_relay_weighted).

    The recurrence state is the count of prior INCREMENTS m (quirk Q1:
    weighted requests check count+permits but increment by 1); the
    emitted decision additionally re-checks the post-increment count
    (quirk Q2), exactly as ops/flat.py:sw_flat_bits.
    """
    num_slots = packed.shape[0]
    u_b = uwords.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    maxp = table.max_permits[lid]
    win = table.window_ms[lid]
    rem = now % win

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    base = (prev_e * (win - rem)) // win

    def body(carry, r):
        m, buf = carry
        w = _weighted_step_w(perms_rank, roff, r, count, u_b)
        t = maxp - base - curr_e - w
        inc = valid & (w >= 1) & (m <= t)
        allowed = inc & (curr_e + m + 1 <= maxp)
        buf = jax.lax.dynamic_update_slice(
            buf, allowed.astype(jnp.uint8), (roff[r],))
        return (m + inc, buf), None

    (m_fin, buf), _ = jax.lax.scan(
        body,
        (jnp.zeros_like(curr_e),
         jnp.zeros(perms_rank.shape[0], dtype=jnp.uint8)),
        jnp.arange(r_steps, dtype=jnp.int64))
    any_inc = m_fin > 0
    curr_new = curr_e + m_fin
    samew = rows[0] == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    widx = jnp.where(valid, slot, jnp.int32(num_slots))
    packed_new = packed.at[widx].set(
        _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e),
        mode="drop")
    return packed_new, jnp.packbits(buf)


def tb_relay_weighted_counts(packed, table, uwords, wlane, lid, now, *,
                             rank_bits: int, out_dtype=jnp.uint8):
    """Coalesced weighted token-bucket step: one lane per unique, no scan.

    When every repeat of a key inside a chunk carries the SAME permit
    weight w (the overwhelmingly common shape — clients rarely vary a
    key's weight within one flush), the weighted scan recurrence of
    :func:`tb_relay_weighted` has a closed form per segment: denied
    requests consume nothing, so the allowed requests are a PREFIX of
    the segment and ``n_allowed = min(count, v1 // (w * FP_ONE))``
    (0 unless 1 <= w <= max_permits), consuming exactly
    ``n_allowed * w * FP_ONE``.  The host reconstructs per-request
    booleans as ``rank < n_allowed[uidx]`` — bit-identical to the scan
    and to sequential per-request replay (tests/test_coalesce.py drives
    all three).  uwords carries (slot | clamped count) exactly as the
    digest path; the clamp stays exact because n_allowed <= max_permits
    < clamp.  wlane uint8[U] is the per-unique weight (padding lanes
    don't care — they decode invalid).  Device work and wire traffic
    scale with UNIQUES (4B word + 1B weight up, 1-2B count down), not
    requests: the Zipf-coalescing win.
    """
    num_slots = packed.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    cap = table.cap_fp[lid]
    rate = table.rate_fp[lid]
    maxp = table.max_permits[lid]
    ttl2 = table.ttl2_ms[lid]

    rows = _tb_decode(packed[sc])
    v1 = _refilled(rows, cap, rate, ttl2, now)
    w = wlane.astype(jnp.int64)
    ok = valid & (w >= 1) & (w <= maxp)
    w_fp = jnp.where(ok, w, 1) * TOKEN_FP_ONE
    n_alw = jnp.where(ok, jnp.clip(v1 // w_fp, 0, count), jnp.int64(0))
    consumed = n_alw * w_fp
    any_inc = n_alw > 0
    tokens_new = jnp.where(any_inc, v1 - consumed, rows[0])
    last_new = jnp.where(any_inc, jnp.maximum(now, 1), rows[1])
    widx = jnp.where(valid & any_inc, slot, jnp.int32(num_slots))
    packed_new = packed.at[widx].set(
        _tb_encode(tokens_new, last_new), mode="drop")
    lim = jnp.int64(jnp.iinfo(out_dtype).max)
    return packed_new, jnp.clip(n_alw, 0, lim).astype(out_dtype)


def sw_relay_weighted_counts(packed, table, uwords, wlane, lid, now, *,
                             rank_bits: int, out_dtype=jnp.uint8):
    """Coalesced weighted sliding-window step (see
    tb_relay_weighted_counts).

    Closed form of the :func:`sw_relay_weighted` scan under a uniform
    segment weight: the increment test ``m <= maxp - base - curr_e - w``
    admits a prefix of ``n_inc = clip(maxp - base - curr_e - w + 1, 0,
    count)`` requests (0 unless w >= 1; quirk Q1 — weighted requests
    check count+permits but increment by 1), and the emitted decision
    re-checks the post-increment count (quirk Q2): request r is allowed
    iff ``r < min(n_inc, maxp - curr_e)``.  STATE advances by n_inc —
    the Q2-denied prefix tail still increments, exactly as the scan —
    while the returned count is the Q2-checked n_allowed the host
    reconstructs with.
    """
    num_slots = packed.shape[0]
    slot, count, _, valid = decode_words(uwords, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    maxp = table.max_permits[lid]
    win = table.window_ms[lid]
    rem = now % win

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    base = (prev_e * (win - rem)) // win
    w = wlane.astype(jnp.int64)
    ok = valid & (w >= 1)
    t = maxp - base - curr_e - w
    n_inc = jnp.where(ok, jnp.clip(t + 1, 0, count), jnp.int64(0))
    n_alw = jnp.minimum(n_inc, jnp.maximum(maxp - curr_e, 0))
    any_inc = n_inc > 0
    curr_new = curr_e + n_inc
    samew = rows[0] == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    widx = jnp.where(valid, slot, jnp.int32(num_slots))
    packed_new = packed.at[widx].set(
        _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e),
        mode="drop")
    lim = jnp.int64(jnp.iinfo(out_dtype).max)
    return packed_new, jnp.clip(n_alw, 0, lim).astype(out_dtype)


def sw_relay_bits(packed, table, words, lids, now, *, rank_bits: int):
    """Relay sliding-window counterpart of :func:`tb_relay_bits` (same
    contract; decision math mirrors ops/flat.py:sw_flat_bits with
    permits=None, including the Q1/Q2 increment-by-1 and
    post-increment-check quirks)."""
    num_slots = packed.shape[0]
    slot, rank, last, valid = decode_words(words, rank_bits, num_slots)
    sc = jnp.where(valid, slot, 0)
    scalar_lid = jnp.ndim(lids) == 0
    lidc = lids if scalar_lid else jnp.clip(
        lids, 0, table.max_permits.shape[0] - 1)
    maxp = table.max_permits[lidc]
    win = table.window_ms[lidc]

    rows = _sw_decode(packed[sc])
    curr_ws, curr_e, prev_e, prev_dl_e = _rolled(rows, win, now)
    rem = now % win
    base = (prev_e * (win - rem)) // win

    # ops/flat.py:sw_flat_bits, permits=None: u = maxp - base - curr_e - 1;
    # inc_j = rank_j <= u; prior increments at rank j are min(rank, n_pass);
    # allowed additionally re-checks the post-increment count (quirk Q2).
    u = jnp.where(valid, maxp - base - curr_e - 1, jnp.int64(-1))
    n_pass = jnp.maximum(u + 1, 0)
    inc = rank < n_pass
    s_prior = jnp.minimum(rank, n_pass)
    c_j = curr_e + s_prior
    allowed = inc & (c_j + 1 <= maxp) & valid

    seg_len = rank + 1
    tot = jnp.minimum(seg_len, n_pass)
    any_inc = tot > 0
    curr_new = curr_e + tot
    samew = rows[0] == curr_ws
    cdl_new = jnp.where(any_inc, now + win, jnp.where(samew, rows[2], 0))
    curr_ws_b = jnp.broadcast_to(curr_ws, sc.shape).astype(jnp.int64)
    new_rows = _sw_encode(curr_ws_b, curr_new, cdl_new, prev_e, prev_dl_e)

    mask = valid & last
    widx = jnp.where(mask, slot, jnp.int32(num_slots))
    packed_new = packed.at[widx].set(new_rows, mode="drop")
    return packed_new, jnp.packbits(allowed)
