"""The edge aggregator: bulk leases in, subleases out.

One :class:`EdgeAggregator` fronts many lease clients.  Per hot
``(lid, key)`` it holds ONE bulk lease from the core (a
:class:`~ratelimiter_tpu.leases.sublease.BulkPool`) and slices it to
clients via per-client :class:`EdgeSession` objects — a sublease grant
or renewal is a dict lookup and two integer moves, zero wire frames.
The aggregator's only upstream traffic is:

- one bulk LEASE frame when a pool is first (re-)created, and
- one ``OP_BULK_RENEW`` columnar frame per lid per flush interval,
  renewing the whole portfolio (used counts reported, budgets
  re-charged) in a single round trip.

Nesting invariant (ARCHITECTURE §14b, asserted by tests/test_edge.py):
every pool conserves ``remaining + sliced_out + used_pending ==
budget + deficit``, so the aggregator can never admit more than its
bulk budgets between flushes, and the fleet over-admission when an
aggregator dies mid-burn is bounded by the sum of its bulk budgets —
the same shape of bound the core documents per client lease, one tier
up.

Revocation is scoped: when a flush answer marks a pool revoked (the
core's ``lease_scope_epoch`` advanced for that key's shard), only that
pool dies — its clients re-grant at the new epoch on their next renew,
and burns they report against the dead pool are folded into
``used_pending`` and flushed upstream once more, where the core counts
them into ``lease.over_admission`` exactly as a direct client's
post-fence burns.  Pools on surviving shards are untouched.

``EdgeSession`` is intentionally bilingual: it implements BOTH the
manager duck-type (``grant``/``renew``/``release`` returning
``LeaseGrant``/``None`` — what ``service/sidecar.py`` dispatches lease
frames to) and the transport duck-type (``lease_grant``/
``lease_renew``/``lease_release``/``try_acquire``/
``telemetry_report`` — what ``LeaseClient`` burns against), so the
aggregator drops in on either side of the wire.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ratelimiter_tpu.leases.manager import LeaseGrant
from ratelimiter_tpu.leases.sublease import BulkPool, PoolKey, Sublease
from ratelimiter_tpu.utils.logging import get_logger

log = get_logger("edge.aggregator")


def _wall_ms() -> int:
    return time.time_ns() // 1_000_000


class EdgeAggregator:
    """Subleases bulk budgets to clients; renews them in bulk."""

    def __init__(self, upstream, *,
                 bulk_budget: int = 4096,
                 slice_budget: int = 64,
                 flush_ms: float = 50.0,
                 deny_ttl_ms: float = 25.0,
                 clock_ms=None,
                 registry=None,
                 name: str = "edge"):
        self.upstream = upstream
        self.bulk_budget = max(int(bulk_budget), 1)
        self.slice_budget = max(int(slice_budget), 1)
        self.flush_ms = float(flush_ms)
        self.deny_ttl_ms = max(float(deny_ttl_ms), 1.0)
        self.name = name
        self._clock_ms = clock_ms or _wall_ms
        self._lock = threading.RLock()
        self._pools: Dict[PoolKey, BulkPool] = {}
        # Revoked/expired pools still owed a flush (used_pending) or
        # holding client slices that have not folded back yet.
        self._dead: List[BulkPool] = []
        self._deny_until: Dict[PoolKey, int] = {}
        self._next_sid = 0
        self._last_flush = int(self._clock_ms())
        # Plain counters (drills and the bench read them directly).
        self.upstream_frames = 0       # wire frames sent upstream
        self.bulk_renewals_total = 0   # portfolio flush frames
        self.scoped_revocations_total = 0
        self.over_admission_total = 0  # burns folded on dead bulk leases
        self.slices_granted_total = 0
        self.local_renewals_total = 0  # sublease renewals, zero frames
        if registry is not None:
            self._m_aggs = registry.gauge(
                "ratelimiter.edge.aggregators",
                "Edge aggregators live in this process")
            self._m_subs = registry.gauge(
                "ratelimiter.edge.subleases",
                "Client subleases currently outstanding across pools")
            self._m_renewals = registry.counter(
                "ratelimiter.edge.bulk_renewals",
                "Bulk portfolio renewal frames sent upstream (one "
                "columnar OP_BULK_RENEW per lid per flush)")
            self._m_revoked = registry.counter(
                "ratelimiter.edge.scoped_revocations",
                "Bulk leases revoked by a scoped fence-epoch advance "
                "(only pools routing to the promoted shard)")
            self._m_over = registry.counter(
                "ratelimiter.edge.over_admission",
                "Permits burned against revoked bulk leases — the "
                "aggregator-tier over-admission, reported upstream and "
                "bounded by the revoked pools' bulk budgets")
            self._m_aggs.set(1.0)
        else:
            self._m_aggs = self._m_subs = None
            self._m_renewals = self._m_revoked = self._m_over = None

    # -- sessions --------------------------------------------------------------
    def session(self, session_id: Optional[int] = None) -> "EdgeSession":
        """A per-client identity: each connection/client gets its own
        sublease bookkeeping (one slice per (lid, key) per session)."""
        with self._lock:
            if session_id is None:
                self._next_sid += 1
                session_id = self._next_sid
            return EdgeSession(self, int(session_id))

    # -- pools -----------------------------------------------------------------
    def _gauge_subs(self) -> None:
        if self._m_subs is not None:
            n = sum(len(p.subs) for p in self._pools.values())
            n += sum(len(p.subs) for p in self._dead)
            self._m_subs.set(float(n))

    def _retire_pool(self, pool: BulkPool, *, revoked: bool) -> None:
        """Move a pool out of service: revoked pools count toward the
        scoped-revocation tally; either way the carcass stays on the
        dead list until its clients have folded back and its pending
        burns have flushed."""
        self._pools.pop((pool.lid, pool.key), None)
        pool.revoked = True
        if revoked:
            self.scoped_revocations_total += 1
            if self._m_revoked is not None:
                self._m_revoked.add(1)
        if pool.used_pending or pool.subs:
            self._dead.append(pool)

    def _ensure_pool(self, lid: int, key: str,
                     now: int) -> Optional[BulkPool]:
        """The live pool for (lid, key), taking a fresh bulk lease
        upstream (ONE frame, amortized over every sublease it will
        back) when none is held.  None while in deny cooldown or when
        the core refuses the bulk grant."""
        k = (int(lid), key)
        pool = self._pools.get(k)
        if pool is not None:
            if not pool.revoked and not pool.expired(now):
                return pool
            # TTL lapsed before a flush renewed it: the core may have
            # swept the lease, so nothing this pool vouches for is
            # trustworthy — retire it (not a scoped revocation) and
            # re-grant below.
            self._retire_pool(pool, revoked=False)
        if now < self._deny_until.get(k, 0):
            return None
        self.upstream_frames += 1
        resp = self.upstream.lease_grant(lid, key, self.bulk_budget,
                                         bulk=True)
        if resp is None or int(resp[0]) <= 0:
            ttl = int(resp[1]) if resp is not None else self.deny_ttl_ms
            self._deny_until[k] = now + max(int(ttl), 1)
            return None
        granted, ttl, epoch = int(resp[0]), int(resp[1]), int(resp[2])
        pool = BulkPool(lid=int(lid), key=key, budget=granted,
                        remaining=granted, epoch=epoch,
                        deadline_ms=now + max(ttl, 1),
                        granted_total=granted)
        self._pools[k] = pool
        self._deny_until.pop(k, None)
        return pool

    # -- the portfolio flush ---------------------------------------------------
    def maybe_flush(self, now: Optional[int] = None) -> None:
        now = int(self._clock_ms()) if now is None else int(now)
        if now - self._last_flush >= self.flush_ms:
            self.flush(now)

    def flush(self, now: Optional[int] = None) -> int:
        """Renew the whole bulk portfolio: ONE columnar frame per lid
        covering every live pool (used reported, budget re-charged,
        TTL re-armed) plus one last row for each dead pool still owed
        a burn report.  Returns the number of upstream frames sent."""
        with self._lock:
            now = int(self._clock_ms()) if now is None else int(now)
            self._last_flush = now
            by_lid: Dict[int, List[BulkPool]] = {}
            for pool in self._pools.values():
                by_lid.setdefault(pool.lid, []).append(pool)
            for pool in self._dead:
                if pool.used_pending > 0:
                    by_lid.setdefault(pool.lid, []).append(pool)
            frames = 0
            bulk_fn = getattr(self.upstream, "lease_bulk_renew", None)
            for lid, pools in sorted(by_lid.items()):
                keys = [p.key for p in pools]
                used = [int(p.used_pending) for p in pools]
                req = [0 if p.revoked else self.bulk_budget
                       for p in pools]
                # Each row names its lease INSTANCE: a dead pool's burn
                # report must land in over_admission even when a
                # successor bulk lease already lives on the same key.
                eps = [int(p.epoch) for p in pools]
                if bulk_fn is not None:
                    rows = bulk_fn(lid, keys, used, req, eps)
                    self.upstream_frames += 1
                    frames += 1
                else:
                    rows = []
                    for key, u, r in zip(keys, used, req):
                        resp = self.upstream.lease_renew(lid, key, u, r)
                        self.upstream_frames += 1
                        frames += 1
                        rows.append((0, 0, 0, True) if resp is None
                                    else (int(resp[0]), int(resp[1]),
                                          int(resp[2]), False))
                self.bulk_renewals_total += 1
                if self._m_renewals is not None:
                    self._m_renewals.add(1)
                for pool, sent, row in zip(pools, used, rows):
                    granted, ttl, epoch, revoked = row
                    if pool.revoked:
                        # Dead pool's final burn report landed (the
                        # core counted it into lease.over_admission).
                        pool.used_pending = max(
                            pool.used_pending - sent, 0)
                        continue
                    if revoked or int(granted) <= 0:
                        # Scoped fence advance (or the core closed the
                        # lease): the reported burns were already
                        # counted upstream; clients re-grant at the
                        # new epoch on their next renew.
                        pool.used_pending = max(
                            pool.used_pending - sent, 0)
                        self._retire_pool(pool, revoked=bool(revoked))
                        continue
                    pool.apply_renewal(int(granted), int(ttl),
                                       int(epoch), now, sent)
            self._dead = [p for p in self._dead
                          if p.used_pending > 0 or p.subs]
            self._gauge_subs()
            return frames

    # -- lifecycle -------------------------------------------------------------
    def drop(self) -> dict:
        """Simulate an aggregator crash (the chaos drill's kill):
        abandon every pool and sublease WITHOUT flushing.  Returns the
        outstanding exposure so the drill can assert the bound: burns
        after death stay within the sum of the dropped bulk budgets."""
        with self._lock:
            out = {
                "pools": len(self._pools),
                "bulk_budget": sum(p.budget
                                   for p in self._pools.values()),
                "sliced_out": sum(p.sliced_out
                                  for p in self._pools.values()),
                "used_pending": sum(p.used_pending
                                    for p in self._pools.values()),
                "subleases": sum(len(p.subs)
                                 for p in self._pools.values()),
            }
            self._pools.clear()
            self._dead = []
            self._deny_until.clear()
            self._gauge_subs()
            return out

    def release_all(self) -> None:
        """Graceful shutdown: flush the final burn report, then release
        every live bulk lease.  Unreturned client slices are counted as
        used (conservative — their burn status is unknowable), so the
        core's view stays an upper bound."""
        with self._lock:
            self.flush()
            for pool in list(self._pools.values()):
                used = min(pool.budget,
                           pool.used_pending + pool.sliced_out)
                self.upstream_frames += 1
                try:
                    self.upstream.lease_release(pool.lid, pool.key,
                                                int(used))
                except Exception:  # noqa: BLE001 — teardown best-effort
                    pass
            self._pools.clear()
            self._dead = []
            self._gauge_subs()
            if self._m_aggs is not None:
                self._m_aggs.set(0.0)

    close = release_all

    # -- introspection ---------------------------------------------------------
    def status(self) -> dict:
        with self._lock:
            return {
                "name": self.name,
                "pools": len(self._pools),
                "dead_pools": len(self._dead),
                "subleases": sum(len(p.subs)
                                 for p in self._pools.values()),
                "bulk_budget": sum(p.budget
                                   for p in self._pools.values()),
                "sliced_out": sum(p.sliced_out
                                  for p in self._pools.values()),
                "used_pending": sum(p.used_pending
                                    for p in self._pools.values()),
                "upstream_frames": self.upstream_frames,
                "bulk_renewals": self.bulk_renewals_total,
                "scoped_revocations": self.scoped_revocations_total,
                "over_admission": self.over_admission_total,
                "slices_granted": self.slices_granted_total,
                "local_renewals": self.local_renewals_total,
            }


class EdgeSession:
    """One client's identity at the aggregator (see module docstring
    for the dual duck-type contract)."""

    def __init__(self, agg: EdgeAggregator, sid: int):
        self._agg = agg
        self.sid = int(sid)
        # key -> the pool this session's slice was cut from (may be a
        # retired pool the client has not re-granted past yet).
        self._subs: Dict[PoolKey, BulkPool] = {}

    # -- manager duck-type (sidecar dispatch) ----------------------------------
    def grant(self, lid: int, key: str, requested: int = 0,
              trace_id: int = 0, bulk: bool = False) -> LeaseGrant:
        agg = self._agg
        with agg._lock:
            now = int(agg._clock_ms())
            agg.maybe_flush(now)
            k = (int(lid), key)
            old = self._subs.get(k)
            pool = agg._ensure_pool(lid, key, now)
            if old is not None and old is not pool:
                # The session's previous slice came from a pool that
                # has since been retired: the client lost track of it,
                # so fold it conservatively (counts as burned).
                sub = old.drop_sub(self.sid)
                if sub is not None:
                    old.fold_lost(sub)
                del self._subs[k]
            if pool is None:
                return LeaseGrant(0, int(agg.deny_ttl_ms), 0)
            req = int(requested) or agg.slice_budget
            req = max(1, min(req, agg.slice_budget))
            sub = pool.slice(self.sid, req)
            if sub.amount <= 0:
                # Pool dry: one portfolio flush may refill it (the
                # core credits+re-charges in the same call).
                agg.flush(now)
                if not pool.revoked:
                    pool.top_up(sub, req)
            if sub.amount <= 0:
                pool.drop_sub(self.sid)
                return LeaseGrant(0, int(agg.deny_ttl_ms), pool.epoch)
            self._subs[k] = pool
            agg.slices_granted_total += 1
            agg._gauge_subs()
            ttl = max(1, pool.deadline_ms - now)
            return LeaseGrant(sub.amount, ttl, pool.epoch)

    def renew(self, lid: int, key: str, used: int, requested: int = 0,
              trace_id: int = 0) -> Optional[LeaseGrant]:
        agg = self._agg
        with agg._lock:
            now = int(agg._clock_ms())
            agg.maybe_flush(now)
            k = (int(lid), key)
            used = max(int(used), 0)
            pool = self._subs.get(k)
            if pool is None:
                # Burns against a sublease this aggregator never saw
                # (restart, session churn): conserve them — fold into
                # the live pool's pending report if one exists.
                live = agg._pools.get(k)
                if used and live is not None:
                    live.fold_over_report(used)
                return None
            sub = pool.subs.get(self.sid)
            if sub is None:
                del self._subs[k]
                return None
            if pool.revoked or pool.expired(now):
                # The bulk lease died under this slice: fold the burns
                # (they flush upstream once more, where the core counts
                # them into lease.over_admission) and send the client
                # back to re-grant at the new epoch.
                pool.fold_used(sub, used)
                pool.drop_sub(self.sid)
                del self._subs[k]
                agg.over_admission_total += used
                if agg._m_over is not None:
                    agg._m_over.add(used)
                if not pool.revoked:
                    agg._retire_pool(pool, revoked=False)
                agg._gauge_subs()
                return None
            pool.fold_used(sub, used)
            pool.return_unused(sub)
            req = int(requested) or agg.slice_budget
            req = max(1, min(req, agg.slice_budget))
            amt = pool.top_up(sub, req)
            if amt <= 0:
                agg.flush(now)
                if pool.revoked:
                    pool.drop_sub(self.sid)
                    del self._subs[k]
                    agg._gauge_subs()
                    return None
                amt = pool.top_up(sub, req)
            agg.local_renewals_total += 1
            if amt <= 0:
                return LeaseGrant(0, int(agg.deny_ttl_ms), pool.epoch)
            ttl = max(1, pool.deadline_ms - now)
            return LeaseGrant(amt, ttl, pool.epoch)

    def release(self, lid: int, key: str, used: int,
                trace_id: int = 0) -> None:
        agg = self._agg
        with agg._lock:
            k = (int(lid), key)
            used = max(int(used), 0)
            pool = self._subs.pop(k, None)
            if pool is None:
                return
            sub = pool.drop_sub(self.sid)
            if sub is None:
                return
            pool.fold_used(sub, used)
            if pool.revoked:
                agg.over_admission_total += used
                if agg._m_over is not None:
                    agg._m_over.add(used)
            else:
                pool.return_unused(sub)
            agg._gauge_subs()

    # -- transport duck-type (LeaseClient-facing) ------------------------------
    def lease_grant(self, lid: int, key: str, requested: int,
                    trace_id: int = 0, bulk: bool = False):
        return self.grant(lid, key, requested, trace_id=trace_id)

    def lease_renew(self, lid: int, key: str, used: int,
                    requested: int = 0, trace_id: int = 0):
        return self.renew(lid, key, used, requested, trace_id=trace_id)

    def lease_release(self, lid: int, key: str, used: int,
                      trace_id: int = 0) -> None:
        self.release(lid, key, used, trace_id=trace_id)

    def try_acquire(self, lid: int, key: str, permits: int = 1,
                    trace_id: int = 0) -> bool:
        """Per-decision fallback: forwarded upstream (one frame) — the
        core's device keeps arbitrating keys the aggregator holds no
        budget for."""
        agg = self._agg
        agg.upstream_frames += 1
        return bool(agg.upstream.try_acquire(lid, key, permits))

    def telemetry_report(self, blob: bytes) -> bool:
        fn = getattr(self._agg.upstream, "telemetry_report", None)
        if fn is None:
            return False
        out = fn(blob)
        return bool(out) if not isinstance(out, int) else out >= 0
