"""Standalone edge aggregator process, runnable as
``python -m ratelimiter_tpu.edge.edgeproc`` (ARCHITECTURE §14b).

The process is the hierarchical tier's unit of deployment: it connects
ONE upstream ``SidecarClient`` (wire v6) to the core sidecar, wraps it
in an :class:`~ratelimiter_tpu.edge.aggregator.EdgeAggregator`, and
opens a FRONT sidecar of its own that lease clients point at instead of
the core.  Lease ops terminate at the aggregator (each front connection
gets its own :class:`EdgeSession` — ``SidecarServer`` resolves the
per-connection session through the backend's ``.session()``), so a
sublease grant or renewal never crosses the upstream link; only the
periodic ``OP_BULK_RENEW`` portfolio flush does.  Plain decision ops
(TRY_ACQUIRE / AVAILABLE / RESET / PING) are proxied upstream
frame-for-frame through :class:`UpstreamProxyStorage` — the core's
device stays the only arbiter for traffic the aggregator holds no
budget for.

Like ``replication/hostproc.py``, the process prints ONE JSON line on
stdout when ready (front port, upstream address, lids) and exits
cleanly on stdin EOF **or SIGTERM** — the launcher (a drill, an init
system wrapper) owns its lifetime through the pipe, and a TERM gets
the same graceful teardown (final portfolio flush, bulk releases, exit
0), so a chaos conductor can tell a crash-kill (signal death, budget
abandoned upstream) from a graceful stop (exit 0, accounting settled).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time


class LockedSidecarClient:
    """Serialize one ``SidecarClient`` across the front server's handler
    threads.  The client's request/response stream is strictly ordered,
    so concurrent callers would interleave frames and desync it; the
    lock makes every public call an atomic round trip."""

    def __init__(self, client):
        self._cli = client
        self._lock = threading.RLock()

    def __getattr__(self, name):
        target = getattr(self._cli, name)
        if not callable(target):
            return target
        lock = self._lock

        def call(*args, **kwargs):
            with lock:
                return target(*args, **kwargs)

        return call


class UpstreamProxyStorage:
    """Duck-typed storage for the front ``SidecarServer``: every
    decision op becomes one upstream frame on the shared client.  No
    async surface is offered (``acquire_async`` et al. absent), so the
    server rides its synchronous fallback path — identical answers,
    one-in one-out."""

    def __init__(self, client):
        self._cli = client

    def is_available(self) -> bool:
        try:
            return bool(self._cli.ping())
        except Exception:  # noqa: BLE001 — a dead upstream reads as down
            return False

    def acquire(self, algo: str, lid: int, key: str,
                permits: int = 1) -> dict:
        allowed = self._cli.try_acquire(int(lid), key, int(permits))
        return {"allowed": bool(allowed), "remaining": 0}

    def available_many(self, algo: str, lid: int, keys) -> list:
        return [int(self._cli.available(int(lid), k)) for k in keys]

    def reset_key(self, algo: str, lid: int, key: str) -> None:
        self._cli.reset(int(lid), key)


def build_edge(upstream_host: str, upstream_port: int, lids,
               *, host: str = "127.0.0.1", port: int = 0,
               bulk_budget: int = 4096, slice_budget: int = 64,
               flush_ms: float = 50.0, registry=None,
               upstream_timeout: float = 10.0):
    """Wire the aggregator tier: upstream client → aggregator → front
    sidecar.  Returns ``(server, aggregator, upstream_client)`` —
    shared by ``main`` and the in-process tests (tests/test_edge.py).
    """
    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.edge.aggregator import EdgeAggregator
    from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarServer

    upstream = LockedSidecarClient(
        SidecarClient(upstream_host, int(upstream_port),
                      timeout=upstream_timeout))
    if upstream.server_version < 6:
        raise RuntimeError(
            f"edgeproc needs a v6 core sidecar (bulk leases); upstream "
            f"negotiated v{upstream.server_version}")
    agg = EdgeAggregator(upstream, bulk_budget=bulk_budget,
                         slice_budget=slice_budget, flush_ms=flush_ms,
                         registry=registry)
    server = SidecarServer(UpstreamProxyStorage(upstream), host=host,
                           port=int(port), drain_timeout_ms=200.0)
    server.attach_leases(agg)
    # The front door answers for the CORE's limiter ids: the config here
    # is a placeholder for the registry lookup only — every decision is
    # proxied upstream, where the real policy lives.
    placeholder = RateLimitConfig(max_permits=1, window_ms=1000)
    for lid in lids:
        server.expose(int(lid), "tb", placeholder)
    server.start()
    return server, agg, upstream


# Graceful-shutdown latch (mirrors replication/hostproc.py): stdin EOF
# or SIGTERM, one teardown path, exit 0 either way.
_SHUTDOWN = threading.Event()


def _install_sigterm() -> None:
    try:
        signal.signal(signal.SIGTERM, lambda *_: _SHUTDOWN.set())
    except ValueError:  # not the main thread (in-process harnesses)
        pass


def _wait_for_eof() -> None:
    """Block until the launcher closes our stdin (its handle on our
    lifetime); also returns if stdin was never a pipe.  Raw-fd read:
    a buffered ``sys.stdin`` read holds the reader's lock, and a
    SIGTERM exit racing a daemon thread parked in it is a fatal
    ``_enter_buffered_busy`` abort at interpreter shutdown."""
    try:
        fd = sys.stdin.fileno()
        while os.read(fd, 4096):
            pass
    except (OSError, ValueError):
        time.sleep(3600.0)


def _wait_for_shutdown() -> None:
    """Block until stdin EOF or SIGTERM, whichever first (the EOF
    watch rides a daemon thread so TERM can interrupt a blocked pipe
    read)."""

    def eof_watch() -> None:
        _wait_for_eof()
        _SHUTDOWN.set()

    threading.Thread(target=eof_watch, name="eof-watch",
                     daemon=True).start()
    _SHUTDOWN.wait()


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--upstream-host", default="127.0.0.1")
    parser.add_argument("--upstream-port", type=int, required=True)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=0,
                        help="front sidecar port (0 = ephemeral)")
    parser.add_argument("--lids", default="1",
                        help="comma-separated core limiter ids to front")
    parser.add_argument("--bulk-budget", type=int, default=4096)
    parser.add_argument("--slice-budget", type=int, default=64)
    parser.add_argument("--flush-ms", type=float, default=50.0)
    args = parser.parse_args(argv)
    _install_sigterm()

    lids = [int(x) for x in args.lids.split(",") if x.strip()]
    server, agg, upstream = build_edge(
        args.upstream_host, args.upstream_port, lids,
        host=args.host, port=args.port,
        bulk_budget=args.bulk_budget, slice_budget=args.slice_budget,
        flush_ms=args.flush_ms)
    print(json.dumps({
        "ready": True, "role": "edge", "port": server.port,
        "upstream": f"{args.upstream_host}:{args.upstream_port}",
        "lids": lids, "version": upstream.server_version,
    }), flush=True)
    _wait_for_shutdown()
    # Graceful: final portfolio flush + bulk releases BEFORE the front
    # door closes, so the core's accounting is settled.
    agg.release_all()
    server.stop()
    try:
        upstream.close()
    except Exception:  # noqa: BLE001 — teardown best-effort
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
