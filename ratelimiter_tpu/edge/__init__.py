"""Edge aggregator tier (ARCHITECTURE §14b): hierarchical token leases.

An :class:`EdgeAggregator` sits between a fleet of lease clients and
the core sidecar.  It takes one BULK lease per hot ``(lid, key)`` from
the core (leases/manager.py, ``bulk=True``) and subleases slices to its
clients at memory speed, renewing its whole portfolio in one columnar
``OP_BULK_RENEW`` frame (wire v6) per flush interval — so ingress
collapses multiplicatively on top of the per-client lease collapse, and
failover cost drops from O(clients) to O(affected aggregators): the
core's scoped fence epoch revokes only the bulk leases whose keys route
to a promoted shard, and survivors keep their slices.
"""

from ratelimiter_tpu.edge.aggregator import EdgeAggregator, EdgeSession

__all__ = ["EdgeAggregator", "EdgeSession"]
