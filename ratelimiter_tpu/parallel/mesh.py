"""Device mesh construction for key-space sharding.

The reference scales horizontally by running N app instances against one
Redis (README "Horizontal scaling"), with Redis Cluster sharding the
keyspace when one server is not enough (ARCHITECTURE.md scaling section).
The TPU-native equivalent is a 1-D ``jax.sharding.Mesh`` over the available
chips: the slot array is sharded over the ``shard`` axis, every key hashes
to exactly one shard, and the hot path needs **no cross-device traffic** —
decisions are embarrassingly parallel across the key space, exactly like
Redis Cluster hash slots.  Only aggregate metrics ride a ``psum`` over ICI.

Multi-host deployments stack the same design over DCN: each host process
owns the shards of its local chips, and the service tier routes keys to
hosts by the same hash — the hot path never crosses DCN (SURVEY.md §5.8).
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh


SHARD_AXIS = "shard"


def make_mesh(devices: Optional[Sequence] = None, n_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over ``devices`` (default: all local devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (SHARD_AXIS,))
