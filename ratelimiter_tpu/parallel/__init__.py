from ratelimiter_tpu.parallel.mesh import SHARD_AXIS, make_mesh
from ratelimiter_tpu.parallel.sharded import ShardedDeviceEngine, ShardedSlotIndex, shard_of_key

__all__ = [
    "SHARD_AXIS",
    "make_mesh",
    "ShardedDeviceEngine",
    "ShardedSlotIndex",
    "shard_of_key",
]
