"""Multi-host key routing (the DCN tier).

Scaling past one host follows the same rule as scaling past one chip
(parallel/mesh.py): *pin keys, don't coordinate*.  Each host process owns
the key-space shards of its local chips; a stateless router in front (or
embedded in every client) maps a key to its owning host with the same
deterministic hash used for chip sharding.  The hot path therefore never
crosses DCN — only client->owner traffic does, exactly like Redis Cluster
client-side hash-slot routing (the reference's prescribed scale-out,
ARCHITECTURE notes on Redis Cluster).

``HostRouter`` is that mapping plus sidecar connection management: give it
the host:port list of the fleet's sidecars (config-distributed, like the
reference's redis.host property) and call it like a limiter.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ratelimiter_tpu.service.sidecar import SidecarClient


def host_of_key(key: str, n_hosts: int) -> int:
    """Deterministic key -> host hash.

    Uses a different stream than shard_of_key (chip-level) so the two
    tiers stripe independently.
    """
    return zlib.crc32(b"host:" + key.encode()) % n_hosts


class HostRouter:
    """Routes decisions to the owning host's sidecar."""

    def __init__(self, endpoints: Sequence[Tuple[str, int]]):
        if not endpoints:
            raise ValueError("at least one endpoint required")
        self._endpoints = list(endpoints)
        self._clients: Dict[int, SidecarClient] = {}
        self._lock = threading.Lock()

    def _client(self, host_idx: int) -> SidecarClient:
        with self._lock:
            client = self._clients.get(host_idx)
            if client is None:
                host, port = self._endpoints[host_idx]
                client = SidecarClient(host, port)
                self._clients[host_idx] = client
            return client

    def try_acquire(self, lid: int, key: str, permits: int = 1) -> bool:
        return self._client(host_of_key(key, len(self._endpoints))).try_acquire(
            lid, key, permits)

    def acquire_batch(self, lid: int, keys: Sequence[str],
                      permits: Optional[Sequence[int]] = None) -> List[bool]:
        """Split a batch by owning host, pipeline each sub-batch, reassemble."""
        permits = list(permits) if permits is not None else [1] * len(keys)
        n = len(self._endpoints)
        per_host: Dict[int, List[int]] = {}
        for i, key in enumerate(keys):
            per_host.setdefault(host_of_key(key, n), []).append(i)
        out: List[bool] = [False] * len(keys)
        for host_idx, positions in per_host.items():
            res = self._client(host_idx).acquire_batch(
                lid, [keys[i] for i in positions],
                [permits[i] for i in positions])
            for pos, (_status, allowed, _rem) in zip(positions, res):
                out[pos] = allowed
        return out

    def available(self, lid: int, key: str) -> int:
        return self._client(host_of_key(key, len(self._endpoints))).available(lid, key)

    def reset(self, lid: int, key: str) -> None:
        self._client(host_of_key(key, len(self._endpoints))).reset(lid, key)

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()
