"""Multi-host key routing (the DCN tier).

Scaling past one host follows the same rule as scaling past one chip
(parallel/mesh.py): *pin keys, don't coordinate*.  Each host process owns
the key-space shards of its local chips; a stateless router in front (or
embedded in every client) maps a key to its owning host with the same
deterministic hash used for chip sharding.  The hot path therefore never
crosses DCN — only client->owner traffic does, exactly like Redis Cluster
client-side hash-slot routing (the reference's prescribed scale-out,
ARCHITECTURE notes on Redis Cluster).

``HostRouter`` is that mapping plus sidecar connection management: give it
the host:port list of the fleet's sidecars (config-distributed, like the
reference's redis.host property) and call it like a limiter.
"""

from __future__ import annotations

import threading
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

from ratelimiter_tpu.service.sidecar import SidecarClient, SidecarSendError


def host_of_key(key: str, n_hosts: int) -> int:
    """Deterministic key -> host hash.

    Uses a different stream than shard_of_key (chip-level) so the two
    tiers stripe independently.
    """
    return zlib.crc32(b"host:" + key.encode()) % n_hosts


class HostRouter:
    """Routes decisions to the owning host's sidecar.

    Failure semantics: a DOWN endpoint surfaces its ``ConnectionError`` /
    ``OSError`` to the caller immediately (nothing broken is cached — the
    next call attempts a fresh connection, so recovery is automatic).  A
    STALE connection (owner restarted since the last call) is dropped and
    retried once against a fresh connection before the error surfaces,
    which makes host restarts invisible to callers as long as the endpoint
    is back up.  No cross-host failover exists by design: keys are pinned
    to their owner's state, and deciding a key on a different host would
    silently hand it a fresh quota (the same reason Redis Cluster clients
    don't fail over hash slots to arbitrary nodes).
    """

    def __init__(self, endpoints: Sequence[Tuple[str, int]]):
        if not endpoints:
            raise ValueError("at least one endpoint required")
        self._endpoints = list(endpoints)
        self._clients: Dict[int, SidecarClient] = {}
        self._lock = threading.Lock()

    def _client(self, host_idx: int) -> SidecarClient:
        with self._lock:
            client = self._clients.get(host_idx)
        if client is not None:
            return client
        # Connect OUTSIDE the lock: a blackholed endpoint's connect timeout
        # must not head-of-line-block traffic to healthy hosts.
        host, port = self._endpoints[host_idx]
        fresh = SidecarClient(host, port)
        with self._lock:
            current = self._clients.get(host_idx)
            if current is None:
                self._clients[host_idx] = fresh
                return fresh
        fresh.close()  # lost a benign connect race; use the winner
        return current

    def _drop(self, host_idx: int, client: SidecarClient) -> None:
        with self._lock:
            if self._clients.get(host_idx) is client:
                del self._clients[host_idx]
        try:
            client.close()
        except OSError:
            pass

    def _call(self, host_idx: int, op, replay_safe: bool = True):
        """Run ``op(client)``; on a dead connection drop it and (when safe)
        retry once against a fresh one.

        ``replay_safe=False`` (the batch path) limits the retry to
        SEND-phase failures — the server cannot have processed a request
        whose frames never arrived, whereas replaying after a READ-phase
        failure could double-charge every key of a batch the server
        already decided.  Single-key ops replay unconditionally (reference
        parity with the per-op Redis retry; blast radius one permit).
        """
        client = self._client(host_idx)
        try:
            return op(client)
        except (ConnectionError, OSError) as exc:
            self._drop(host_idx, client)
            if not replay_safe and not isinstance(exc, SidecarSendError):
                raise
            client = self._client(host_idx)  # raises if the host is down
            try:
                return op(client)
            except (ConnectionError, OSError):
                self._drop(host_idx, client)
                raise

    def try_acquire(self, lid: int, key: str, permits: int = 1) -> bool:
        return self._call(host_of_key(key, len(self._endpoints)),
                          lambda c: c.try_acquire(lid, key, permits))

    def acquire_batch(self, lid: int, keys: Sequence[str],
                      permits: Optional[Sequence[int]] = None) -> List[bool]:
        """Split a batch by owning host, pipeline each sub-batch, reassemble."""
        permits = list(permits) if permits is not None else [1] * len(keys)
        n = len(self._endpoints)
        per_host: Dict[int, List[int]] = {}
        for i, key in enumerate(keys):
            per_host.setdefault(host_of_key(key, n), []).append(i)
        out: List[bool] = [False] * len(keys)
        for host_idx, positions in per_host.items():
            res = self._call(host_idx, lambda c, p=positions: c.acquire_batch(
                lid, [keys[i] for i in p], [permits[i] for i in p]),
                replay_safe=False)
            for pos, (_status, allowed, _rem) in zip(positions, res):
                out[pos] = allowed
        return out

    def available(self, lid: int, key: str) -> int:
        return self._call(host_of_key(key, len(self._endpoints)),
                          lambda c: c.available(lid, key))

    def reset(self, lid: int, key: str) -> None:
        self._call(host_of_key(key, len(self._endpoints)),
                   lambda c: c.reset(lid, key))

    def close(self) -> None:
        with self._lock:
            for client in self._clients.values():
                client.close()
            self._clients.clear()
