"""Multi-chip decision engine: slot state sharded over a device mesh.

``shard_map`` over a 1-D mesh runs the *same* single-device step
(ops/sliding_window.py, ops/token_bucket.py) independently on every shard's
partition of the slot array.  Keys are pinned to shards by hash, so a
request batch is routed host-side into per-shard sub-batches of identical
shape ``(n_shards, B)`` — SPMD with zero cross-shard traffic on the hot
path (the Redis-Cluster-hash-slots analog; SURVEY.md §2 "Parallelism
strategies").  The only collective is a ``psum`` over the mesh that
aggregates per-step allow/deny totals for metrics.

The global state lives as ``(n_shards, S_local)`` arrays with
``NamedSharding(P('shard', None))`` — on a real TPU slice each row is
resident in one chip's HBM and updates happen entirely chip-locally over
ICI-free code; the same program runs unchanged on the CPU test mesh.
"""

from __future__ import annotations

import functools
import threading
import zlib
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ratelimiter_tpu.engine.slots import SlotIndex
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.ops.sliding_window import (
    SWOut,
    sw_pack_state,
    sw_peek_p,
    sw_reset_p,
    sw_step_p,
    sw_unpack_state,
)
from ratelimiter_tpu.ops.token_bucket import (
    TBOut,
    tb_pack_state,
    tb_peek_p,
    tb_reset_p,
    tb_step_p,
    tb_unpack_state,
)
from ratelimiter_tpu.parallel.mesh import SHARD_AXIS, make_mesh

_MIN_BATCH = 256


def _bucket(n: int) -> int:
    size = _MIN_BATCH
    while size < n:
        size *= 2
    return size


def shard_of_key(key, n_shards: int) -> int:
    """Deterministic, process-independent key -> shard hash (crc32), so a
    multi-host router and this engine always agree."""
    return zlib.crc32(repr(key).encode()) % n_shards


class ShardedSlotIndex:
    """Key -> global slot with per-shard LRU sub-indexes.

    Global slot id = shard * slots_per_shard + local slot; eviction is
    shard-local (a key's state never migrates between shards).
    """

    def __init__(self, slots_per_shard: int, n_shards: int):
        self.slots_per_shard = int(slots_per_shard)
        self.n_shards = int(n_shards)
        self.num_slots = self.slots_per_shard * self.n_shards
        self._sub = [SlotIndex(self.slots_per_shard) for _ in range(self.n_shards)]

    def _split(self, global_slot: int):
        return divmod(global_slot, self.slots_per_shard)

    def get(self, key):
        shard = shard_of_key(key, self.n_shards)
        local = self._sub[shard].get(key)
        return None if local is None else shard * self.slots_per_shard + local

    def assign(self, key, pinned=None):
        shard = shard_of_key(key, self.n_shards)
        local_pinned = None
        if pinned:
            local_pinned = {
                s % self.slots_per_shard
                for s in pinned
                if s // self.slots_per_shard == shard
            }
        local, evicted = self._sub[shard].assign(key, pinned=local_pinned)
        base = shard * self.slots_per_shard
        return base + local, None if evicted is None else base + evicted

    def remove(self, key):
        shard = shard_of_key(key, self.n_shards)
        local = self._sub[shard].remove(key)
        return None if local is None else shard * self.slots_per_shard + local

    def __len__(self):
        return sum(len(s) for s in self._sub)


# ---------------------------------------------------------------------------
# Sharded step construction
# ---------------------------------------------------------------------------

def build_sharded_sw_step(mesh):
    """shard_map'd sliding-window step over (n_shards, S_local, 6) packed
    state and (n_shards, B) batches; returns (state, out, global totals)."""

    def local_step(state, table, slots, lids, permits, now):
        new_state, out = sw_step_p(state[0], table, slots[0], lids[0],
                                   permits[0], now)
        n_allowed = jnp.sum(out.allowed.astype(jnp.int64))
        n_total = jnp.sum((slots[0] >= 0).astype(jnp.int64))
        totals = jax.lax.psum(jnp.stack([n_allowed, n_total]), SHARD_AXIS)
        return new_state[None], SWOut(*(f[None] for f in out)), totals

    return jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
    )


def build_sharded_tb_step(mesh):
    def local_step(state, table, slots, lids, permits, now):
        new_state, out = tb_step_p(state[0], table, slots[0], lids[0],
                                   permits[0], now)
        n_allowed = jnp.sum(out.allowed.astype(jnp.int64))
        n_total = jnp.sum((slots[0] >= 0).astype(jnp.int64))
        totals = jax.lax.psum(jnp.stack([n_allowed, n_total]), SHARD_AXIS)
        return new_state[None], TBOut(*(f[None] for f in out)), totals

    return jax.shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
    )


def build_sharded_peek(mesh, peek_fn):
    def local_peek(state, table, slots, lids, now):
        out = peek_fn(state[0], table, slots[0], lids[0], now)
        return out[None]

    return jax.shard_map(
        local_peek,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(SHARD_AXIS),
    )


def build_sharded_reset(mesh, reset_fn):
    def local_reset(state, slots):
        return reset_fn(state[0], slots[0])[None]

    return jax.shard_map(
        local_reset,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS),
    )


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

class ShardedDeviceEngine:
    """Drop-in DeviceEngine with state sharded over a mesh.

    Public surface is identical (global slot ids in, numpy decisions out);
    host-side routing scatters each request to its shard's row and unscatters
    the results.  Exposes ``last_step_totals`` = (allowed, total) aggregated
    across all shards by the on-device psum.
    """

    def __init__(self, slots_per_shard: int, table: LimiterTable, mesh=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        self.slots_per_shard = int(slots_per_shard)
        self.num_slots = self.n_shards * self.slots_per_shard
        self.table = table
        self._lock = threading.RLock()
        self.last_step_totals = (0, 0)

        self._state_sharding = NamedSharding(self.mesh, P(SHARD_AXIS, None, None))

        def zeros(lanes):
            return jax.device_put(
                jnp.zeros((self.n_shards, self.slots_per_shard, lanes),
                          dtype=jnp.int32),
                self._state_sharding)

        # Packed-resident per-shard state (same codec as DeviceEngine).
        self.sw_packed = zeros(6)
        self.tb_packed = zeros(4)

        self._sw_step = jax.jit(build_sharded_sw_step(self.mesh), donate_argnums=0)
        self._tb_step = jax.jit(build_sharded_tb_step(self.mesh), donate_argnums=0)
        self._sw_peek = jax.jit(build_sharded_peek(self.mesh, sw_peek_p))
        self._tb_peek = jax.jit(build_sharded_peek(self.mesh, tb_peek_p))
        self._sw_reset = jax.jit(build_sharded_reset(self.mesh, sw_reset_p), donate_argnums=0)
        self._tb_reset = jax.jit(build_sharded_reset(self.mesh, tb_reset_p), donate_argnums=0)

    # -- i64 field view (checkpoint/compat) ------------------------------------
    @property
    def sw_state(self):
        return sw_unpack_state(self.sw_packed)

    @sw_state.setter
    def sw_state(self, state) -> None:
        self.sw_packed = jax.device_put(
            sw_pack_state(type(state)(*(jnp.asarray(f) for f in state))),
            self._state_sharding)

    @property
    def tb_state(self):
        return tb_unpack_state(self.tb_packed)

    @tb_state.setter
    def tb_state(self, state) -> None:
        self.tb_packed = jax.device_put(
            tb_pack_state(type(state)(*(jnp.asarray(f) for f in state))),
            self._state_sharding)

    def make_slot_index(self) -> ShardedSlotIndex:
        return ShardedSlotIndex(self.slots_per_shard, self.n_shards)

    # -- routing --------------------------------------------------------------
    def _route(self, slots, fill_extra=None):
        """Scatter global-slot requests into (n_shards, B) rows.

        Returns (mat_local_slots, row_of_req, col_of_req, B).
        """
        slots = np.asarray(slots, dtype=np.int64)
        shard = slots // self.slots_per_shard
        local = slots % self.slots_per_shard
        counts = np.bincount(shard, minlength=self.n_shards)
        B = _bucket(max(int(counts.max(initial=0)), 1))
        order = np.argsort(shard, kind="stable")
        offsets = np.zeros(self.n_shards + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        cols = np.empty(len(slots), dtype=np.int64)
        cols[order] = np.arange(len(slots)) - offsets[shard[order]]
        mat = np.full((self.n_shards, B), -1, dtype=np.int32)
        mat[shard, cols] = local
        return mat, shard, cols, B

    def _route_batch(self, slots, limiter_ids, permits):
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        perms = np.ones((self.n_shards, B), dtype=np.int64)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        perms[shard, cols] = np.asarray(permits, dtype=np.int64)
        return mat, lids, perms, shard, cols

    # -- public API (mirrors DeviceEngine) ------------------------------------
    def sw_acquire(self, slots, limiter_ids, permits, now_ms: int):
        mat, lids, perms, shard, cols = self._route_batch(slots, limiter_ids, permits)
        with self._lock:
            new_state, out, totals = self._sw_step(
                self.sw_packed, self.table.device_arrays,
                jnp.asarray(mat), jnp.asarray(lids), jnp.asarray(perms),
                jnp.int64(now_ms))
            self.sw_packed = new_state
            totals = np.asarray(totals)
            self.last_step_totals = (int(totals[0]), int(totals[1]))
            return {
                "allowed": np.asarray(out.allowed)[shard, cols],
                "mutated": np.asarray(out.mutated)[shard, cols],
                "observed": np.asarray(out.observed)[shard, cols],
                "cache_value": np.asarray(out.cache_value)[shard, cols],
            }

    def tb_acquire(self, slots, limiter_ids, permits, now_ms: int):
        mat, lids, perms, shard, cols = self._route_batch(slots, limiter_ids, permits)
        with self._lock:
            new_state, out, totals = self._tb_step(
                self.tb_packed, self.table.device_arrays,
                jnp.asarray(mat), jnp.asarray(lids), jnp.asarray(perms),
                jnp.int64(now_ms))
            self.tb_packed = new_state
            totals = np.asarray(totals)
            self.last_step_totals = (int(totals[0]), int(totals[1]))
            return {
                "allowed": np.asarray(out.allowed)[shard, cols],
                "observed": np.asarray(out.observed)[shard, cols],
                "remaining": np.asarray(out.remaining)[shard, cols],
            }

    def sw_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        mat = np.maximum(mat, 0)  # peek clamps; padding read is discarded
        with self._lock:
            out = self._sw_peek(self.sw_packed, self.table.device_arrays,
                                jnp.asarray(mat), jnp.asarray(lids), jnp.int64(now_ms))
        return np.asarray(out)[shard, cols]

    def tb_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        mat = np.maximum(mat, 0)
        with self._lock:
            out = self._tb_peek(self.tb_packed, self.table.device_arrays,
                                jnp.asarray(mat), jnp.asarray(lids), jnp.int64(now_ms))
        return np.asarray(out)[shard, cols]

    def sw_clear(self, slots: Sequence[int]) -> None:
        mat, _, _, _ = self._route(slots)
        with self._lock:
            self.sw_packed = self._sw_reset(self.sw_packed, jnp.asarray(mat))

    def tb_clear(self, slots: Sequence[int]) -> None:
        mat, _, _, _ = self._route(slots)
        with self._lock:
            self.tb_packed = self._tb_reset(self.tb_packed, jnp.asarray(mat))

    def block_until_ready(self) -> None:
        with self._lock:
            jax.block_until_ready((self.sw_packed, self.tb_packed))
