"""Multi-chip decision engine: slot state sharded over a device mesh.

``shard_map`` over a 1-D mesh runs the *same* single-device step
(ops/sliding_window.py, ops/token_bucket.py) independently on every shard's
partition of the slot array.  Keys are pinned to shards by hash, so a
request batch is routed host-side into per-shard sub-batches of identical
shape ``(n_shards, B)`` — SPMD with zero cross-shard traffic on the hot
path (the Redis-Cluster-hash-slots analog; SURVEY.md §2 "Parallelism
strategies").  The only collective is a ``psum`` over the mesh that
aggregates per-step allow/deny totals for metrics.

The global state lives as ``(n_shards, S_local)`` arrays with
``NamedSharding(P('shard', None))`` — on a real TPU slice each row is
resident in one chip's HBM and updates happen entirely chip-locally over
ICI-free code; the same program runs unchanged on the CPU test mesh.
"""

from __future__ import annotations

import contextlib
import functools
import threading
import zlib
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

try:  # jax >= 0.6 exports shard_map at top level
    _shard_map_impl = jax.shard_map
except AttributeError:  # older jax: the same API lives in experimental
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` with the replication checker off: the flat
    step's duplicate solver lowers a ``while_loop``, for which older
    checkers have no replication rule (every spec here is explicit, so
    the checker adds nothing)."""
    try:
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs, check_rep=False)
    except TypeError:  # newer jax dropped/renamed check_rep
        return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                               out_specs=out_specs)

from ratelimiter_tpu.engine.slots import SlotIndex
from ratelimiter_tpu.engine.state import LimiterTable
from ratelimiter_tpu.ops.sliding_window import (
    SWOut,
    sw_pack_state,
    sw_peek_p,
    sw_reset_p,
    sw_step_p,
    sw_unpack_state,
)
from ratelimiter_tpu.ops.token_bucket import (
    TBOut,
    tb_pack_state,
    tb_peek_p,
    tb_reset_p,
    tb_step_p,
    tb_unpack_state,
)
from ratelimiter_tpu.parallel.mesh import SHARD_AXIS, make_mesh

_MIN_BATCH = 256


def _bucket(n: int, floor: int = _MIN_BATCH) -> int:
    size = floor
    while size < n:
        size *= 2
    return size


def shard_of_int_keys(key_ids, n_shards: int):
    """Vectorized deterministic shard hash for int64 user keys (splitmix64
    finalizer).  The scalar path routes int keys through this same function,
    so stream and scalar calls always agree on a key's shard."""
    x = np.asarray(key_ids).astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        x = x ^ (x >> np.uint64(31))
    return (x % np.uint64(n_shards)).astype(np.int64)


def _splitmix64_device(x):
    """The splitmix64 finalizer as device math (u64 lanes) — must stay
    bit-identical to :func:`shard_of_int_keys` and to the C router
    (native/slot_index.cpp:rl_shard_route*): the route-and-count pass
    below bins by it, and host and device routing MUST agree on every
    key's shard (tests/test_sharded.py pins the parity)."""
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def build_route_count(mesh, n_shards: int, int_keys: bool):
    """shard_map route-and-count pass: bin a replicated key chunk by the
    deterministic shard hash ON THE MESH (r8, ROADMAP item 1).

    Each shard receives the whole chunk (one replicated upload — on a
    real slice the broadcast rides ICI, where bandwidth is free relative
    to the host), hashes it (splitmix64 for int keys; string keys arrive
    pre-hashed as their fingerprint h1 stream, exactly what
    ``shard_of_key``'s string branch computes), and emits

    - ``counts`` i32[n_shards] — how many of the chunk's keys it owns,
    - ``pos``   i32[n_shards, n] — the arrival-order positions of its
      own keys, compacted left, ``-1`` padding (so the all-one-shard
      edge case is representable: one full row, seven empty ones).

    The host turns ``pos`` rows back into the exact (shard, order,
    counts) contract of the C router (``rl_shard_route2``); parity is
    pinned bit-for-bit by tests.  Which router serves is a measured
    election (storage layer) — on a CPU container the host C pass wins
    (the "device" shares its core); on a real slice the device does the
    O(n) binning where the mesh is real.
    """

    def local_route(keys):
        idx = jax.lax.axis_index(SHARD_AXIS).astype(jnp.int32)
        h = (_splitmix64_device(keys) if int_keys
             else keys.astype(jnp.uint64))
        mine = (h % jnp.uint64(n_shards)).astype(jnp.int32) == idx
        cnt = jnp.sum(mine.astype(jnp.int32))
        pos = jnp.nonzero(mine, size=keys.shape[0],
                          fill_value=-1)[0].astype(jnp.int32)
        return cnt[None], pos[None]

    return shard_map(
        local_route,
        mesh=mesh,
        in_specs=(P(),),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )


def shard_of_key(key, n_shards: int) -> int:
    """Deterministic, process-independent key -> shard hash, so a multi-host
    router and this engine always agree.  Int user keys use the vectorizable
    splitmix hash (same as the int stream path).  String/bytes user keys
    route by the h1 stream of their index FINGERPRINT (r6): the same hash
    the slot index keys on, so the batched string stream can hash a chunk
    once natively and both route and assign from the result — scalar calls
    compute the identical h1 here in Python.  Everything else (exotic key
    types, which have no batch path) keeps crc32-of-repr.

    The string branch changed from crc32-of-repr in r6; sharded checkpoint
    dumps carry a shard-hash version so a dump written under the old
    routing is refused (or placement-checked) instead of silently
    orphaning entries (engine/checkpoint.py:SHARD_HASH_VERSION)."""
    user = key[1] if isinstance(key, tuple) and len(key) == 2 else key
    if isinstance(user, (int, np.integer)):
        return int(shard_of_int_keys(np.asarray([user]), n_shards)[0])
    lid = key[0] if isinstance(key, tuple) and len(key) == 2 else 0
    if isinstance(user, (str, bytes)) and isinstance(lid, (int, np.integer)):
        from ratelimiter_tpu.engine.native_index import fnv_fingerprint_h1

        data = user.encode() if isinstance(user, str) else user
        return fnv_fingerprint_h1(data, int(lid)) % n_shards
    return zlib.crc32(repr(key).encode()) % n_shards


class ShardedSlotIndex:
    """Key -> global slot with per-shard LRU sub-indexes.

    Global slot id = shard * slots_per_shard + local slot; eviction is
    shard-local (a key's state never migrates between shards).
    """

    def __init__(self, slots_per_shard: int, n_shards: int,
                 native: bool = True):
        self.slots_per_shard = int(slots_per_shard)
        self.n_shards = int(n_shards)
        self.num_slots = self.slots_per_shard * self.n_shards
        sub_cls = SlotIndex
        if native:
            from ratelimiter_tpu.engine.native_index import (
                NativeSlotIndex,
                native_available,
            )

            if native_available():
                sub_cls = NativeSlotIndex
        self._sub = [sub_cls(self.slots_per_shard) for _ in range(self.n_shards)]
        # The sharded stream path needs per-shard vectorized assignment.
        self.supports_batch_ints = all(
            hasattr(s, "assign_batch_ints") for s in self._sub)
        # The sharded STRING stream additionally needs native fingerprint
        # hashing (hash once -> route by h1 -> per-shard fps assign; the
        # h1 routing is what shard_of_key's string branch computes
        # scalar-side, so both paths agree on a key's shard).
        from ratelimiter_tpu.engine.native_index import str_hash_available

        self.supports_batch_strs = (
            str_hash_available()
            and all(hasattr(s, "assign_batch_fps_uniques")
                    for s in self._sub))

    def _split(self, global_slot: int):
        return divmod(global_slot, self.slots_per_shard)

    def get(self, key):
        shard = shard_of_key(key, self.n_shards)
        local = self._sub[shard].get(key)
        return None if local is None else shard * self.slots_per_shard + local

    def assign(self, key, pinned=None, hold_pin=False):
        shard = shard_of_key(key, self.n_shards)
        local_pinned = None
        if pinned:
            local_pinned = {
                s % self.slots_per_shard
                for s in pinned
                if s // self.slots_per_shard == shard
            }
        local, evicted = self._sub[shard].assign(key, pinned=local_pinned,
                                                 hold_pin=hold_pin)
        base = shard * self.slots_per_shard
        return base + local, None if evicted is None else base + evicted

    def remove(self, key):
        shard = shard_of_key(key, self.n_shards)
        local = self._sub[shard].remove(key)
        return None if local is None else shard * self.slots_per_shard + local

    def __len__(self):
        return sum(len(s) for s in self._sub)

    def pin_batch(self, slots) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        shard = slots // self.slots_per_shard
        for q, sub in enumerate(self._sub):
            m = shard == q
            if m.any() and hasattr(sub, "pin_batch"):
                sub.pin_batch(slots[m] - np.int32(q * self.slots_per_shard))

    def unpin_batch(self, slots) -> None:
        slots = np.ascontiguousarray(slots, dtype=np.int32)
        shard = slots // self.slots_per_shard
        for q, sub in enumerate(self._sub):
            m = shard == q
            if m.any() and hasattr(sub, "unpin_batch"):
                sub.unpin_batch(slots[m] - np.int32(q * self.slots_per_shard))


# ---------------------------------------------------------------------------
# Sharded step construction
# ---------------------------------------------------------------------------

def build_sharded_sw_step(mesh):
    """shard_map'd sliding-window step over (n_shards, S_local, 6) packed
    state and (n_shards, B) batches; returns (state, out, global totals)."""

    def local_step(state, table, slots, lids, permits, now):
        new_state, out = sw_step_p(state[0], table, slots[0], lids[0],
                                   permits[0], now)
        n_allowed = jnp.sum(out.allowed.astype(jnp.int64))
        n_total = jnp.sum((slots[0] >= 0).astype(jnp.int64))
        totals = jax.lax.psum(jnp.stack([n_allowed, n_total]), SHARD_AXIS)
        return new_state[None], SWOut(*(f[None] for f in out)), totals

    return shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
    )


def build_sharded_tb_step(mesh):
    def local_step(state, table, slots, lids, permits, now):
        new_state, out = tb_step_p(state[0], table, slots[0], lids[0],
                                   permits[0], now)
        n_allowed = jnp.sum(out.allowed.astype(jnp.int64))
        n_total = jnp.sum((slots[0] >= 0).astype(jnp.int64))
        totals = jax.lax.psum(jnp.stack([n_allowed, n_total]), SHARD_AXIS)
        return new_state[None], TBOut(*(f[None] for f in out)), totals

    return shard_map(
        local_step,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P()),
    )


def build_sharded_scan(mesh, step_p, lids_scalar: bool, has_permits: bool):
    """shard_map'd K-sub-batch scan with bit-packed decisions.

    Shapes: state (n_shards, S_local, L) packed; slots (n_shards, K, B);
    lids 0-d or (n_shards, K, B); permits None or (n_shards, K, B);
    now (K,).  Returns (state, bits (n_shards, K, ceil(B/8))).
    """
    from ratelimiter_tpu.ops.packed import _scan

    lid_spec = P() if lids_scalar else P(SHARD_AXIS)
    if has_permits:
        def local_scan(state, table, slots, lids, permits, now):
            st, bits = _scan(step_p, state[0], table, slots[0],
                             lids if lids_scalar else lids[0],
                             permits[0], now)
            return st[None], bits[None]

        in_specs = (P(SHARD_AXIS), P(), P(SHARD_AXIS), lid_spec,
                    P(SHARD_AXIS), P())
    else:
        def local_scan(state, table, slots, lids, now):
            st, bits = _scan(step_p, state[0], table, slots[0],
                             lids if lids_scalar else lids[0],
                             None, now)
            return st[None], bits[None]

        in_specs = (P(SHARD_AXIS), P(), P(SHARD_AXIS), lid_spec, P())
    return shard_map(
        local_scan,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )


def build_sharded_flat(mesh, flat_fn, lids_scalar: bool, has_permits: bool):
    """shard_map'd FLAT mega-batch with bit-packed decisions (ops/flat.py —
    payload sorts, closed-form solve, block-scatter, per shard).

    Shapes: state (n_shards, S_local, L); slots (n_shards, B) local ids
    (-1 padding); lids 0-d or (n_shards, B); permits None or (n_shards, B);
    now i64 scalar.  Returns (state, bits (n_shards, ceil(B/8))).
    """
    lid_spec = P() if lids_scalar else P(SHARD_AXIS)
    if has_permits:
        def local_flat(state, table, slots, lids, permits, now):
            st, bits = flat_fn(state[0], table, slots[0],
                               lids if lids_scalar else lids[0],
                               permits[0], now)
            return st[None], bits[None]

        in_specs = (P(SHARD_AXIS), P(), P(SHARD_AXIS), lid_spec,
                    P(SHARD_AXIS), P())
    else:
        def local_flat(state, table, slots, lids, now):
            st, bits = flat_fn(state[0], table, slots[0],
                               lids if lids_scalar else lids[0],
                               None, now)
            return st[None], bits[None]

        in_specs = (P(SHARD_AXIS), P(), P(SHARD_AXIS), lid_spec, P())
    return shard_map(
        local_flat,
        mesh=mesh,
        in_specs=in_specs,
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )


def build_sharded_relay(mesh, relay_fn, lids_scalar: bool):
    """shard_map'd relay step (ops/relay.py — no sort/scan; the host
    index supplies the duplicate structure).  Works for both flavors:
    bits (words (n_shards, B) -> uint8 (n_shards, B/8)) and counts
    (uwords (n_shards, U) -> out_dtype (n_shards, U)).

    State stays (n_shards, S_local, L); each shard decides its slice with
    LOCAL slot ids; zero cross-shard device traffic.
    """
    lid_spec = P() if lids_scalar else P(SHARD_AXIS)

    def local_relay(state, table, words, lids, now):
        st, out = relay_fn(state[0], table, words[0],
                           lids if lids_scalar else lids[0], now)
        return st[None], out[None]

    return shard_map(
        local_relay,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), lid_spec, P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )


def build_sharded_peek(mesh, peek_fn):
    def local_peek(state, table, slots, lids, now):
        out = peek_fn(state[0], table, slots[0], lids[0], now)
        return out[None]

    return shard_map(
        local_peek,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=P(SHARD_AXIS),
    )


def build_sharded_reset(mesh, reset_fn):
    def local_reset(state, slots):
        return reset_fn(state[0], slots[0])[None]

    return shard_map(
        local_reset,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS),
    )


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------

class ShardedDeviceEngine:
    """Drop-in DeviceEngine with state sharded over a mesh.

    Public surface is identical (global slot ids in, numpy decisions out);
    host-side routing scatters each request to its shard's row and unscatters
    the results.  Exposes ``last_step_totals`` = (allowed, total) aggregated
    across all shards by the on-device psum.

    **Per-shard state parts (r8).**  The canonical state is a LIST of
    single-device arrays, one ``(1, S_local, L)`` part committed to each
    mesh device; the mesh-wide ``(n_shards, S_local, L)`` array every
    shard_map path consumes is assembled on demand with
    ``jax.make_array_from_single_device_arrays`` (zero-copy metadata)
    and cached until a part changes.  That representation is what makes
    the per-shard stream pipelines possible: ``relay_shard_dispatch``
    runs ONE shard's relay step as an independent single-device XLA
    execution on that shard's own device — no mesh collective, no
    multi-device launch rendezvous, no waiting for sibling shards'
    layouts — so shard A can be assembling chunk N+1 while shard B's
    chunk N is still in flight.  Locking: each shard has its own lock;
    whole-mesh operations (the shard_map dispatch/peek/clear paths,
    read/write_rows, state (re)assembly) take every shard lock in
    ascending order, so a per-shard dispatch never races a global step
    and lock order is deadlock-free.
    """

    # Per-shard replication (replication/sharded.py): every dispatch path
    # marks its touched slots (global ids) into an attached journal, so a
    # ShardedReplicationLog can cut per-shard epoch deltas.  The flat
    # ReplicationLog refuses this engine — shard streams must ship
    # independently so one shard can be promoted without the world.
    supports_replication = True

    def __init__(self, slots_per_shard: int, table: LimiterTable, mesh=None):
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        self.slots_per_shard = int(slots_per_shard)
        self.num_slots = self.n_shards * self.slots_per_shard
        self.table = table
        # Dirty-slot journal (engine/state.py): None (default) keeps the
        # hot path at one attribute check per dispatch.
        self.journal = None
        self._lock = threading.RLock()
        self.last_step_totals = (0, 0)
        # Monotone stamp so concurrent drains (the batcher's drain pool
        # completes batches in arbitrary order) can't regress
        # last_step_totals to an older batch.
        self._totals_seq = 0
        self._totals_seen = 0

        self._state_sharding = NamedSharding(self.mesh, P(SHARD_AXIS, None, None))
        self._devices = list(self.mesh.devices.flat)
        # Per-shard locks (r8): per-shard dispatch/clear take ONLY their
        # shard's lock; every whole-mesh path takes all of them ascending
        # via _exclusive().  RLocks so the packed-property assembly can
        # run inside an already-exclusive section.
        self._shard_locks = [threading.RLock() for _ in range(self.n_shards)]
        # Per-device colocated copies of the limiter table (keyed by the
        # TableArrays instance, which is rebuilt on any config change) so
        # per-shard dispatches never re-ship the table per call.
        self._table_parts: tuple = (None, {})
        self._route_fns: dict = {}

        def zero_parts(lanes):
            return [
                jax.device_put(
                    jnp.zeros((1, self.slots_per_shard, lanes),
                              dtype=jnp.int32), d)
                for d in self._devices
            ]

        # Packed-resident per-shard state (same codec as DeviceEngine),
        # held as canonical single-device parts + a lazily assembled
        # mesh-wide view.
        self._parts = {"sw": zero_parts(6), "tb": zero_parts(4)}
        self._packed_cache = {"sw": None, "tb": None}

        # Settle the Pallas probes before any shard_map step compiles
        # (same reason as DeviceEngine: a probe firing lazily inside
        # another program's lowering nests a remote compile some
        # toolchains cannot serve, sticking as a permanent fallback).
        from ratelimiter_tpu.ops import pallas as pallas_kernels

        pallas_kernels.settle_all()
        self._sw_step = jax.jit(build_sharded_sw_step(self.mesh), donate_argnums=0)
        self._tb_step = jax.jit(build_sharded_tb_step(self.mesh), donate_argnums=0)
        self._sw_peek = jax.jit(build_sharded_peek(self.mesh, sw_peek_p))
        self._tb_peek = jax.jit(build_sharded_peek(self.mesh, tb_peek_p))
        self._sw_reset = jax.jit(build_sharded_reset(self.mesh, sw_reset_p), donate_argnums=0)
        self._tb_reset = jax.jit(build_sharded_reset(self.mesh, tb_reset_p), donate_argnums=0)
        self._scan_fns = {}

    # -- per-shard state parts (r8) --------------------------------------------
    @contextlib.contextmanager
    def _exclusive(self):
        """Hold every shard lock (ascending = deadlock-free against the
        per-shard paths, which take exactly one)."""
        for lk in self._shard_locks:
            lk.acquire()
        try:
            yield
        finally:
            for lk in reversed(self._shard_locks):
                lk.release()

    def _assembled(self, algo: str):
        """The mesh-wide (n_shards, S_local, L) view of the per-shard
        parts — zero-copy assembly, cached until a part changes."""
        with self._exclusive():
            arr = self._packed_cache[algo]
            if arr is None:
                parts = self._parts[algo]
                shape = (self.n_shards,) + tuple(parts[0].shape[1:])
                arr = jax.make_array_from_single_device_arrays(
                    shape, self._state_sharding, list(parts))
                self._packed_cache[algo] = arr
            return arr

    def _set_packed(self, algo: str, arr) -> None:
        """Decompose a mesh-sharded result back into canonical parts
        (zero-copy: each addressable shard IS the part)."""
        with self._exclusive():
            shards = sorted(arr.addressable_shards,
                            key=lambda s: s.index[0].start)
            self._parts[algo] = [s.data for s in shards]
            self._packed_cache[algo] = arr

    @property
    def sw_packed(self):
        return self._assembled("sw")

    @sw_packed.setter
    def sw_packed(self, arr) -> None:
        self._set_packed("sw", arr)

    @property
    def tb_packed(self):
        return self._assembled("tb")

    @tb_packed.setter
    def tb_packed(self, arr) -> None:
        self._set_packed("tb", arr)

    def _table_for(self, shard: int):
        """Colocated table arrays for one shard's device (cache keyed by
        the TableArrays instance — any registration rebuilds it).  Called
        BEFORE taking the shard lock (it takes the engine lock; lock
        order is engine > shard)."""
        src = self.table.device_arrays
        with self._lock:
            cache_src, per_dev = self._table_parts
            if cache_src is not src:
                per_dev = {}
                self._table_parts = (src, per_dev)
            tab = per_dev.get(shard)
            if tab is None:
                tab = jax.device_put(src, self._devices[shard])
                per_dev[shard] = tab
            return tab

    def _shard_relay_fn(self, algo: str, flavor: str, lids_scalar: bool,
                        out_dtype):
        from ratelimiter_tpu.ops import relay as relay_ops

        key = ("shard_relay", algo, flavor, lids_scalar,
               None if out_dtype is None else np.dtype(out_dtype).name)
        fn = self._scan_fns.get(key)
        if fn is None:
            if flavor == "bits":
                base = (relay_ops.sw_relay_bits if algo == "sw"
                        else relay_ops.tb_relay_bits)
                local = functools.partial(base, rank_bits=self.rank_bits)
            else:
                base = (relay_ops.sw_relay_counts if algo == "sw"
                        else relay_ops.tb_relay_counts)
                jdt = (jnp.uint8 if np.dtype(out_dtype) == np.uint8
                       else jnp.uint16)
                local = functools.partial(base, rank_bits=self.rank_bits,
                                          out_dtype=jdt)

            def stepped(state, table, words, lids, now):
                st, out = local(state[0], table, words, lids, now)
                return st[None], out

            fn = jax.jit(stepped, donate_argnums=0)
            self._scan_fns[key] = fn
        return fn

    def relay_shard_dispatch(self, algo: str, shard: int, flavor: str,
                             words, lids, now_ms: int, out_dtype=None):
        """ONE shard's relay step as an independent single-device XLA
        execution on that shard's own device (r8) — the per-shard stream
        pipelines' dispatch.  ``words`` carries LOCAL slot ids in the
        same word layout as the mesh-wide relay (``rank_bits``); padding
        is 0xFFFFFFFF.  Only this shard's lock is held: sibling shards
        dispatch, drain and assemble concurrently.  Returns the lazy
        per-shard handle (uint8 bits or per-unique counts)."""
        self._mark_words_shard(algo, shard, words)
        dev = self._devices[shard]
        words_dev = jax.device_put(
            np.ascontiguousarray(words, dtype=np.uint32), dev)
        lids_scalar = np.ndim(lids) == 0
        if lids_scalar:
            lids_dev = jnp.asarray(np.int32(lids))
        else:
            lids_dev = jax.device_put(
                np.ascontiguousarray(lids, dtype=np.int32), dev)
        fn = self._shard_relay_fn(algo, flavor, lids_scalar, out_dtype)
        tab = self._table_for(shard)
        now = jnp.int64(now_ms)
        with self._shard_locks[shard]:
            # Donation invalidates the assembled view's buffer for this
            # shard — drop the cache before the step.
            self._packed_cache[algo] = None
            state, out = fn(self._parts[algo][shard], tab, words_dev,
                            lids_dev, now)
            self._parts[algo][shard] = state
        return out

    def clear_shard(self, algo: str, shard: int, local_slots) -> None:
        """Zero LOCAL slots on one shard's device — the per-shard stream
        pipelines' eviction-clear path.  Stream order is the caller's
        job (each shard pipeline is a FIFO, so a shard's clears land
        before the dispatch that reuses the slots, with no cross-shard
        barrier)."""
        local_slots = np.asarray(list(local_slots), dtype=np.int32)
        if not len(local_slots):
            return
        j = self.journal
        if j is not None:
            j.mark(algo, local_slots.astype(np.int64)
                   + shard * self.slots_per_shard)
        padded = np.full(_bucket(len(local_slots), floor=64), -1,
                         dtype=np.int32)
        padded[:len(local_slots)] = local_slots
        key = ("shard_reset", algo)
        fn = self._scan_fns.get(key)
        if fn is None:
            reset_fn = sw_reset_p if algo == "sw" else tb_reset_p

            def reset1(state, slots):
                return reset_fn(state[0], slots)[None]

            fn = jax.jit(reset1, donate_argnums=0)
            self._scan_fns[key] = fn
        slots_dev = jax.device_put(padded, self._devices[shard])
        with self._shard_locks[shard]:
            self._packed_cache[algo] = None
            self._parts[algo][shard] = fn(self._parts[algo][shard],
                                          slots_dev)

    def _mark_words_shard(self, algo: str, shard: int, words) -> None:
        """Journal one shard's relay words (host-side decode: LOCAL slot
        in the high bits -> global id; padding decodes past
        slots_per_shard and is dropped by the journal's bounds filter)."""
        j = self.journal
        if j is None:
            return
        loc = (np.asarray(words).astype(np.uint64)
               >> np.uint64(self.rank_bits + 1)).astype(np.int64)
        base = shard * self.slots_per_shard
        j.mark(algo, np.where(loc < self.slots_per_shard, loc + base, -1))

    def route_on_device(self, key_ids=None, hashes=None):
        """(shard, order, counts) for one chunk via the on-mesh
        route-and-count pass (:func:`build_route_count`) — the same
        contract as the host C router, so the storage's measured route
        election can swap them freely.  ``key_ids`` i64 int keys, or
        ``hashes`` u64 fingerprint h1 for string traffic."""
        int_keys = hashes is None
        arr = np.ascontiguousarray(
            key_ids if int_keys else hashes,
            dtype=np.int64 if int_keys else np.uint64)
        n = len(arr)
        size = _bucket(n, floor=1 << 14)
        if size != n:
            # Padding keys bin somewhere; their positions (>= n) are
            # dropped below.
            arr = np.concatenate(
                [arr, np.zeros(size - n, dtype=arr.dtype)])
        fn = self._route_fns.get(int_keys)
        if fn is None:
            fn = jax.jit(build_route_count(self.mesh, self.n_shards,
                                           int_keys))
            self._route_fns[int_keys] = fn
        cnt, pos = fn(jnp.asarray(arr))
        pos = np.asarray(pos)
        del cnt  # padded-row counts; recomputed over valid positions
        valid = (pos >= 0) & (pos < n)
        counts = valid.sum(axis=1).astype(np.int64)
        order = np.empty(n, dtype=np.int64)
        shard = np.empty(n, dtype=np.int32)
        off = 0
        for s in range(self.n_shards):
            sel = pos[s][valid[s]]
            order[off:off + len(sel)] = sel
            shard[sel] = s
            off += len(sel)
        return shard, order, counts

    # -- dirty-slot journal hooks (per-shard replication) ----------------------
    # Same host/device split as DeviceEngine's hooks: a device journal
    # marks from the dispatch's own uploaded matrix (one async device op,
    # zero extra bytes); the host journal gets the host copy.
    def _mark_mat(self, algo: str, mat, dev=None) -> None:
        j = self.journal
        if j is not None:
            j.mark_matrix(algo, dev if dev is not None
                          and getattr(j, "device", False) else mat,
                          self.slots_per_shard)

    def _mark_words_mat(self, algo: str, wmat, dev=None) -> None:
        j = self.journal
        if j is not None:
            j.mark_words_matrix(algo, dev if dev is not None
                                and getattr(j, "device", False) else wmat,
                                self.rank_bits, self.slots_per_shard)

    def _mark_global(self, algo: str, slots) -> None:
        j = self.journal
        if j is not None:
            j.mark(algo, slots)

    # -- i64 field view (checkpoint/compat) ------------------------------------
    @property
    def sw_state(self):
        return sw_unpack_state(self.sw_packed)

    @sw_state.setter
    def sw_state(self, state) -> None:
        if self.journal is not None:
            self.journal.mark_all("sw")
        self.sw_packed = jax.device_put(
            sw_pack_state(type(state)(*(jnp.asarray(f) for f in state))),
            self._state_sharding)

    @property
    def tb_state(self):
        return tb_unpack_state(self.tb_packed)

    @tb_state.setter
    def tb_state(self, state) -> None:
        if self.journal is not None:
            self.journal.mark_all("tb")
        self.tb_packed = jax.device_put(
            tb_pack_state(type(state)(*(jnp.asarray(f) for f in state))),
            self._state_sharding)

    def make_slot_index(self) -> ShardedSlotIndex:
        return ShardedSlotIndex(self.slots_per_shard, self.n_shards)

    # -- scan dispatch (sharded streaming; mirrors DeviceEngine's) ------------
    def sw_scan_dispatch(self, slots_skb, lids, permits_skb, now_k):
        return self._scan_dispatch("sw", slots_skb, lids, permits_skb, now_k)

    def tb_scan_dispatch(self, slots_skb, lids, permits_skb, now_k):
        return self._scan_dispatch("tb", slots_skb, lids, permits_skb, now_k)

    def _scan_fn(self, algo: str, lids_scalar: bool, has_permits: bool):
        key = (algo, lids_scalar, has_permits)
        fn = self._scan_fns.get(key)
        if fn is None:
            step_p = sw_step_p if algo == "sw" else tb_step_p
            fn = jax.jit(
                build_sharded_scan(self.mesh, step_p, lids_scalar, has_permits),
                donate_argnums=0)
            self._scan_fns[key] = fn
        return fn

    # -- flat mega-batch dispatch (the streaming hot path; ops/flat.py) -------
    def sw_flat_sharded_dispatch(self, slots_sb, lids, permits_sb, now_ms):
        return self._flat_dispatch("sw", slots_sb, lids, permits_sb, now_ms)

    def tb_flat_sharded_dispatch(self, slots_sb, lids, permits_sb, now_ms):
        return self._flat_dispatch("tb", slots_sb, lids, permits_sb, now_ms)

    def _flat_fn(self, algo: str, lids_scalar: bool, has_permits: bool):
        from ratelimiter_tpu.ops.flat import sw_flat_bits, tb_flat_bits

        key = ("flat", algo, lids_scalar, has_permits)
        fn = self._scan_fns.get(key)
        if fn is None:
            flat = sw_flat_bits if algo == "sw" else tb_flat_bits
            fn = jax.jit(
                build_sharded_flat(self.mesh, flat, lids_scalar, has_permits),
                donate_argnums=0)
            self._scan_fns[key] = fn
        return fn

    def _flat_dispatch(self, algo, slots_sb, lids, permits_sb, now_ms):
        """slots_sb: i32[n_shards, B_local] LOCAL slot ids (-1 padding);
        lids scalar or i32[n_shards, B_local]; permits likewise or None;
        now_ms scalar.  Returns a lazy uint8[n_shards, ceil(B/8)] handle."""
        slots_host = slots_sb
        slots_sb = jnp.asarray(np.ascontiguousarray(slots_sb, dtype=np.int32))
        self._mark_mat(algo, slots_host, dev=slots_sb)
        lids_scalar = np.ndim(lids) == 0
        if lids_scalar:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        has_permits = permits_sb is not None
        now = jnp.int64(now_ms)
        fn = self._flat_fn(algo, lids_scalar, has_permits)
        with self._lock, self._exclusive():
            state = self.sw_packed if algo == "sw" else self.tb_packed
            if has_permits:
                permits_sb = jnp.asarray(
                    np.ascontiguousarray(permits_sb, dtype=np.int32))
                state, bits = fn(state, self.table.device_arrays,
                                 slots_sb, lids, permits_sb, now)
            else:
                state, bits = fn(state, self.table.device_arrays,
                                 slots_sb, lids, now)
            if algo == "sw":
                self.sw_packed = state
            else:
                self.tb_packed = state
        return bits

    # -- relay dispatch (ops/relay.py, per shard) ------------------------------
    # Word layout is per-SHARD: slot_bits covers slots_per_shard, so the
    # rank field is wider than the single-device engine would get at the
    # same total capacity.

    @property
    def slot_bits(self) -> int:
        return max(int(self.slots_per_shard).bit_length(), 1)

    @property
    def rank_bits(self) -> int:
        return 31 - self.slot_bits

    def relay_usable(self) -> bool:
        from ratelimiter_tpu.ops import relay as relay_ops

        return relay_ops.relay_usable(self.rank_bits,
                                      self.table.max_permits_registered)

    def counts_dtype(self):
        from ratelimiter_tpu.ops import relay as relay_ops

        return relay_ops.counts_dtype(self.table.max_permits_registered)

    def sw_relay_sharded_dispatch(self, words_sb, lids, now_ms):
        return self._relay_dispatch("sw", "bits", words_sb, lids, now_ms,
                                    None)

    def tb_relay_sharded_dispatch(self, words_sb, lids, now_ms):
        return self._relay_dispatch("tb", "bits", words_sb, lids, now_ms,
                                    None)

    def sw_relay_counts_sharded_dispatch(self, uwords_sb, lids, now_ms,
                                         out_dtype):
        return self._relay_dispatch("sw", "counts", uwords_sb, lids, now_ms,
                                    out_dtype)

    def tb_relay_counts_sharded_dispatch(self, uwords_sb, lids, now_ms,
                                         out_dtype):
        return self._relay_dispatch("tb", "counts", uwords_sb, lids, now_ms,
                                    out_dtype)

    def _relay_fn(self, algo, flavor, lids_scalar, out_dtype):
        import functools

        from ratelimiter_tpu.ops import relay as relay_ops

        key = ("relay", algo, flavor, lids_scalar,
               None if out_dtype is None else out_dtype().dtype.name)
        fn = self._scan_fns.get(key)
        if fn is None:
            if flavor == "bits":
                base = (relay_ops.sw_relay_bits if algo == "sw"
                        else relay_ops.tb_relay_bits)
                local = functools.partial(base, rank_bits=self.rank_bits)
            else:
                base = (relay_ops.sw_relay_counts if algo == "sw"
                        else relay_ops.tb_relay_counts)
                jdt = jnp.uint8 if out_dtype == np.uint8 else jnp.uint16
                local = functools.partial(base, rank_bits=self.rank_bits,
                                          out_dtype=jdt)
            fn = jax.jit(build_sharded_relay(self.mesh, local, lids_scalar),
                         donate_argnums=0)
            self._scan_fns[key] = fn
        return fn

    def _relay_dispatch(self, algo, flavor, words_sb, lids, now_ms,
                        out_dtype):
        """words_sb: uint32[n_shards, B_local] relay words with LOCAL slot
        ids (0xFFFFFFFF padding); lids scalar or i32[n_shards, B_local].
        Returns a lazy (n_shards, B/8) bits or (n_shards, B) counts
        handle."""
        words_host = words_sb
        words_sb = jnp.asarray(
            np.ascontiguousarray(words_sb, dtype=np.uint32))
        self._mark_words_mat(algo, words_host, dev=words_sb)
        lids_scalar = np.ndim(lids) == 0
        if lids_scalar:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        now = jnp.int64(now_ms)
        fn = self._relay_fn(algo, flavor, lids_scalar, out_dtype)
        with self._lock, self._exclusive():
            state = self.sw_packed if algo == "sw" else self.tb_packed
            state, out = fn(state, self.table.device_arrays,
                            words_sb, lids, now)
            if algo == "sw":
                self.sw_packed = state
            else:
                self.tb_packed = state
        return out

    def _scan_dispatch(self, algo, slots_skb, lids, permits_skb, now_k):
        """slots_skb: i32[n_shards, K, B_local] LOCAL slot ids (-1 padding);
        lids: scalar or i32[n_shards, K, B_local]; permits likewise or None;
        now_k: i64[K].  Returns a lazy uint8[n_shards, K, ceil(B/8)] handle."""
        slots_host = slots_skb
        slots_skb = jnp.asarray(np.ascontiguousarray(slots_skb, dtype=np.int32))
        self._mark_mat(algo, slots_host, dev=slots_skb)
        lids_scalar = np.ndim(lids) == 0
        if lids_scalar:
            lids = jnp.asarray(np.int32(lids))
        else:
            lids = jnp.asarray(np.ascontiguousarray(lids, dtype=np.int32))
        has_permits = permits_skb is not None
        now_k = jnp.asarray(np.ascontiguousarray(now_k, dtype=np.int64))
        fn = self._scan_fn(algo, lids_scalar, has_permits)
        with self._lock, self._exclusive():
            state = self.sw_packed if algo == "sw" else self.tb_packed
            if has_permits:
                permits_skb = jnp.asarray(
                    np.ascontiguousarray(permits_skb, dtype=np.int32))
                state, bits = fn(state, self.table.device_arrays,
                                 slots_skb, lids, permits_skb, now_k)
            else:
                state, bits = fn(state, self.table.device_arrays,
                                 slots_skb, lids, now_k)
            if algo == "sw":
                self.sw_packed = state
            else:
                self.tb_packed = state
        return bits

    # -- routing --------------------------------------------------------------
    def _route(self, slots, fill_extra=None):
        """Scatter global-slot requests into (n_shards, B) rows.

        Returns (mat_local_slots, row_of_req, col_of_req, B).
        """
        slots = np.asarray(slots, dtype=np.int64)
        # Padding slots (< 0, e.g. warmup batches) route to shard 0 as local
        # padding: every kernel masks negative slots out.
        shard = np.clip(slots, 0, None) // self.slots_per_shard
        local = np.where(slots < 0, -1, slots % self.slots_per_shard)
        counts = np.bincount(shard, minlength=self.n_shards)
        B = _bucket(max(int(counts.max(initial=0)), 1))
        order = np.argsort(shard, kind="stable")
        offsets = np.zeros(self.n_shards + 1, dtype=np.int64)
        np.cumsum(counts, out=offsets[1:])
        cols = np.empty(len(slots), dtype=np.int64)
        cols[order] = np.arange(len(slots)) - offsets[shard[order]]
        mat = np.full((self.n_shards, B), -1, dtype=np.int32)
        mat[shard, cols] = local
        return mat, shard, cols, B

    def _route_batch(self, slots, limiter_ids, permits):
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        perms = np.ones((self.n_shards, B), dtype=np.int64)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        perms[shard, cols] = np.asarray(permits, dtype=np.int64)
        return mat, lids, perms, shard, cols

    # -- public API (mirrors DeviceEngine, incl. the dispatch/drain split
    # that lets the micro-batcher pipeline fetches against dispatches) ------
    def sw_acquire_dispatch(self, slots, limiter_ids, permits, now_ms: int):
        mat, lids, perms, shard, cols = self._route_batch(slots, limiter_ids, permits)
        self._mark_mat("sw", mat)
        with self._lock, self._exclusive():
            new_state, out, totals = self._sw_step(
                self.sw_packed, self.table.device_arrays,
                jnp.asarray(mat), jnp.asarray(lids), jnp.asarray(perms),
                jnp.int64(now_ms))
            self.sw_packed = new_state
            self._totals_seq += 1
            seq = self._totals_seq
        return (out, totals, shard, cols, seq)

    def sw_acquire_drain(self, handle, n: int):
        out, totals, shard, cols, seq = handle
        totals = np.asarray(totals)
        self._set_totals(seq, (int(totals[0]), int(totals[1])))
        return {
            "allowed": np.asarray(out.allowed)[shard, cols],
            "mutated": np.asarray(out.mutated)[shard, cols],
            "observed": np.asarray(out.observed)[shard, cols],
            "cache_value": np.asarray(out.cache_value)[shard, cols],
        }

    def _set_totals(self, seq: int, totals) -> None:
        with self._lock, self._exclusive():
            if seq > self._totals_seen:
                self._totals_seen = seq
                self.last_step_totals = totals

    def sw_acquire(self, slots, limiter_ids, permits, now_ms: int):
        handle = self.sw_acquire_dispatch(slots, limiter_ids, permits, now_ms)
        return self.sw_acquire_drain(handle, len(slots))

    def tb_acquire_dispatch(self, slots, limiter_ids, permits, now_ms: int):
        mat, lids, perms, shard, cols = self._route_batch(slots, limiter_ids, permits)
        self._mark_mat("tb", mat)
        with self._lock, self._exclusive():
            new_state, out, totals = self._tb_step(
                self.tb_packed, self.table.device_arrays,
                jnp.asarray(mat), jnp.asarray(lids), jnp.asarray(perms),
                jnp.int64(now_ms))
            self.tb_packed = new_state
            self._totals_seq += 1
            seq = self._totals_seq
        return (out, totals, shard, cols, seq)

    def tb_acquire_drain(self, handle, n: int):
        out, totals, shard, cols, seq = handle
        totals = np.asarray(totals)
        self._set_totals(seq, (int(totals[0]), int(totals[1])))
        return {
            "allowed": np.asarray(out.allowed)[shard, cols],
            "observed": np.asarray(out.observed)[shard, cols],
            "remaining": np.asarray(out.remaining)[shard, cols],
        }

    def tb_acquire(self, slots, limiter_ids, permits, now_ms: int):
        handle = self.tb_acquire_dispatch(slots, limiter_ids, permits, now_ms)
        return self.tb_acquire_drain(handle, len(slots))

    def sw_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        mat = np.maximum(mat, 0)  # peek clamps; padding read is discarded
        with self._lock, self._exclusive():
            out = self._sw_peek(self.sw_packed, self.table.device_arrays,
                                jnp.asarray(mat), jnp.asarray(lids), jnp.int64(now_ms))
        return np.asarray(out)[shard, cols]

    def tb_available(self, slots, limiter_ids, now_ms: int) -> np.ndarray:
        mat, shard, cols, B = self._route(slots)
        lids = np.zeros((self.n_shards, B), dtype=np.int32)
        lids[shard, cols] = np.asarray(limiter_ids, dtype=np.int32)
        mat = np.maximum(mat, 0)
        with self._lock, self._exclusive():
            out = self._tb_peek(self.tb_packed, self.table.device_arrays,
                                jnp.asarray(mat), jnp.asarray(lids), jnp.int64(now_ms))
        return np.asarray(out)[shard, cols]

    def sw_clear(self, slots: Sequence[int]) -> None:
        mat, _, _, _ = self._route(slots)
        self._mark_mat("sw", mat)
        with self._lock, self._exclusive():
            self.sw_packed = self._sw_reset(self.sw_packed, jnp.asarray(mat))

    def tb_clear(self, slots: Sequence[int]) -> None:
        mat, _, _, _ = self._route(slots)
        self._mark_mat("tb", mat)
        with self._lock, self._exclusive():
            self.tb_packed = self._tb_reset(self.tb_packed, jnp.asarray(mat))

    # -- raw packed-row access (export/import rebalance; replication cuts) ----
    def read_rows(self, algo: str, slots) -> np.ndarray:
        """Packed rows for GLOBAL slot ids — device-side gather, so a
        per-shard replication cut fetches only its dirty rows instead of
        round-tripping the whole (n_shards, S_local, L) array.  Inputs
        are padded to a power of two so cut-to-cut count jitter reuses
        a handful of gather compilations."""
        slots = np.asarray(slots, dtype=np.int64)
        n = len(slots)
        if n == 0:
            packed = self.sw_packed if algo == "sw" else self.tb_packed
            return np.empty((0, packed.shape[-1]), dtype=np.int32)
        size = _bucket(n, floor=256)
        padded = np.zeros(size, dtype=np.int64)
        padded[:n] = slots
        shard = jnp.asarray(padded // self.slots_per_shard, dtype=jnp.int32)
        local = jnp.asarray(padded % self.slots_per_shard, dtype=jnp.int32)
        with self._lock, self._exclusive():
            packed = self.sw_packed if algo == "sw" else self.tb_packed
            rows = packed[shard, local]
        return np.asarray(rows)[:n]

    def write_rows(self, algo: str, slots, rows: np.ndarray) -> None:
        self._mark_global(algo, slots)
        slots = np.asarray(slots, dtype=np.int64)
        shard = jnp.asarray(slots // self.slots_per_shard, dtype=jnp.int32)
        local = jnp.asarray(slots % self.slots_per_shard, dtype=jnp.int32)
        vals = jnp.asarray(np.ascontiguousarray(rows, dtype=np.int32))
        with self._lock, self._exclusive():
            packed = self.sw_packed if algo == "sw" else self.tb_packed
            # Device-side scatter (no full-array host roundtrip), then
            # re-constrain to the shard placement.
            new = jax.device_put(packed.at[shard, local].set(vals),
                                 self._state_sharding)
            if algo == "sw":
                self.sw_packed = new
            else:
                self.tb_packed = new

    # -- lease RESERVE / CREDIT (ops/lease.py; leases/) ------------------------
    # The sharded mesh reserves via a read-rows -> host arithmetic ->
    # write-rows round trip under the exclusive lock set (atomic against
    # every other dispatch path — both read_rows and write_rows re-enter
    # the same RLocks).  Lease ops are rare by design (one reserve
    # amortizes over a whole client-side budget), so the host round trip
    # is off every hot path; the single-device engine runs the fused
    # device kernel instead (engine/engine.py:lease_reserve).  Callers
    # pass UNIQUE slots per call (the lease manager reserves one key at
    # a time); the host mirrors process lanes independently.

    def lease_reserve(self, algo: str, slots, limiter_ids, requested,
                      now_ms: int):
        from ratelimiter_tpu.ops import lease as lease_ops

        slots = np.asarray(slots, dtype=np.int64)
        with self._lock, self._exclusive():
            rows = self.read_rows(algo, slots)
            granted, ws, new_rows, changed = lease_ops.host_reserve_rows(
                algo, rows, np.asarray(limiter_ids, dtype=np.int64),
                np.asarray(requested, dtype=np.int64),
                self.table.host_policy, int(now_ms))
            if changed.any():
                self.write_rows(algo, slots[changed], new_rows[changed])
        return granted, ws

    def lease_credit(self, algo: str, slots, limiter_ids, credit, grant_ws,
                     now_ms: int) -> np.ndarray:
        from ratelimiter_tpu.ops import lease as lease_ops

        slots = np.asarray(slots, dtype=np.int64)
        with self._lock, self._exclusive():
            rows = self.read_rows(algo, slots)
            credited, new_rows, changed = lease_ops.host_credit_rows(
                algo, rows, np.asarray(limiter_ids, dtype=np.int64),
                np.asarray(credit, dtype=np.int64),
                np.asarray(grant_ws, dtype=np.int64),
                self.table.host_policy, int(now_ms))
            if changed.any():
                self.write_rows(algo, slots[changed], new_rows[changed])
        return credited

    def block_until_ready(self) -> None:
        with self._lock, self._exclusive():
            jax.block_until_ready((self.sw_packed, self.tb_packed))
