"""The storage plugin boundary.

Capability parity with the reference's 10-method backend contract
``storage/RateLimitStorage.java:10-70`` ("Allows swapping backends without
changing rate limiter logic").  Implementations in this framework:

- ``InMemoryStorage`` — process-local dict-based backend; the *real* (not
  mocked) test double and single-process deployment option.
- ``TpuBatchedStorage`` — the TPU-resident device-array backend that
  micro-batches operations (storage/tpu.py).

Design deviations from the reference, both deliberate:

- ``eval_script`` takes a *named device script* plus integer args instead of
  a Lua source string.  The reference ships Lua to Redis for atomicity
  (TokenBucketRateLimiter.java:38-68); our backends execute named atomic ops
  (the registered scripts are this framework's "stored procedures" — on the
  TPU backend they are device kernels).  Script names: ``token_bucket``,
  ``token_bucket_peek``.
- z-set methods (``z_add``/``z_remove_range_by_score``/``z_count``) are kept
  for interface parity (quirk Q5: dead surface in the reference for an
  unimplemented sliding-window-log algorithm) and are fully implemented by
  ``InMemoryStorage`` so a sliding-window-log algorithm can be built on them.
"""

from __future__ import annotations

import abc
from typing import List, Sequence


class RateLimitStorage(abc.ABC):
    """Abstract distributed-storage backend (storage/RateLimitStorage.java)."""

    # -- counters -------------------------------------------------------------
    @abc.abstractmethod
    def increment_and_expire(self, key: str, ttl_ms: int) -> int:
        """Atomically increment a counter and (re)set its TTL; returns the new
        value (RateLimitStorage.java:20-28, pipelined INCR+PEXPIRE)."""

    @abc.abstractmethod
    def get(self, key: str) -> int:
        """Current value of a counter; 0 if absent/expired."""

    @abc.abstractmethod
    def set(self, key: str, value: int, ttl_ms: int) -> None:
        """Set a value with expiration."""

    @abc.abstractmethod
    def compare_and_set(self, key: str, expect: int, update: int) -> bool:
        """Atomic CAS; True if the value was updated
        (RateLimitStorage.java:37-41)."""

    @abc.abstractmethod
    def delete(self, key: str) -> None:
        """Delete a key."""

    # -- sorted sets (sliding-window-log support) -----------------------------
    @abc.abstractmethod
    def z_add(self, key: str, score: float, member: str) -> None:
        """Add to a sorted set; score is typically a timestamp."""

    @abc.abstractmethod
    def z_remove_range_by_score(self, key: str, min_score: float, max_score: float) -> int:
        """Remove members with min <= score <= max; returns count removed."""

    @abc.abstractmethod
    def z_count(self, key: str, min_score: float, max_score: float) -> int:
        """Count members with min <= score <= max."""

    # -- scripts --------------------------------------------------------------
    @abc.abstractmethod
    def eval_script(self, script: str, keys: List[str], args: List[int]) -> Sequence[int]:
        """Execute a named atomic script (RateLimitStorage.java:60-64).

        Known scripts:

        ``token_bucket`` — keys=[bucket_key],
            args=[cap_fp, rate_fp, requested_fp, now_ms, ttl_ms];
            returns (allowed, tokens_fp_after) with the exact semantics of
            ``semantics.oracle.TokenBucketOracle``.
        ``token_bucket_peek`` — keys=[bucket_key],
            args=[cap_fp, rate_fp, now_ms]; returns (tokens_fp,) after a
            read-only refill.
        """

    # -- health ---------------------------------------------------------------
    @abc.abstractmethod
    def is_available(self) -> bool:
        """Health check (RateLimitStorage.java:66-69)."""

    def close(self) -> None:  # parity with RedisRateLimitStorage.close()
        pass
