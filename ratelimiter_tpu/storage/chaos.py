"""Fault-injecting storage wrapper (chaos testing).

The reference has no fault injection at all (SURVEY.md §5.3 — its failure
handling is asserted, not exercised). This wrapper makes failure paths
first-class testable: it delegates to any ``RateLimitStorage`` and injects
``StorageException`` (and optional latency) on a configurable schedule, so
retry logic, fail-open policy, and metric accounting can be driven
deterministically in tests and chaos drills.

Determinism: failures come from a seeded RNG; ``fail_next(n)`` forces the
next n operations to fail regardless of probability — the tool for exact
retry-count assertions (the reference's retry wrapper does 3 attempts with
linear backoff; ``service/app.py`` implements the documented fail-open on
exhaustion).
"""

from __future__ import annotations

import collections
import random
import threading
import time

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import StorageException

_DECISION_OPS = ("acquire", "acquire_many", "acquire_many_ids",
                 "acquire_stream_ids", "acquire_stream_strs",
                 "available_many", "reset_key")
_LEGACY_OPS = ("increment_and_expire", "get", "set", "compare_and_set",
               "delete", "z_add", "z_remove_range_by_score", "z_count",
               "eval_script")


class FaultInjectingStorage(RateLimitStorage):
    """Wraps a real backend; injects failures/latency on configured ops."""

    def __init__(
        self,
        inner: RateLimitStorage,
        failure_rate: float = 0.0,
        latency_ms: float = 0.0,
        seed: int = 0,
        ops: tuple = _DECISION_OPS + _LEGACY_OPS,
    ):
        self._inner = inner
        self.failure_rate = float(failure_rate)
        self.latency_ms = float(latency_ms)
        self._rng = random.Random(seed)
        self._ops = set(ops)
        self._lock = threading.Lock()
        self._forced = 0
        self.injected_failures = 0
        # Recent op names only — bounded so long-running drills can't leak.
        self.calls = collections.deque(maxlen=1024)

    # -- control surface ------------------------------------------------------
    def fail_next(self, n: int = 1) -> None:
        """Force the next ``n`` wrapped operations to fail."""
        with self._lock:
            self._forced += int(n)

    def heal(self) -> None:
        """Cancel any remaining forced failures (drills: end an outage)."""
        with self._lock:
            self._forced = 0

    def _maybe_fail(self, op: str) -> None:
        if op not in self._ops:
            return
        with self._lock:
            self.calls.append(op)
            if self._forced > 0:
                self._forced -= 1
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
            if self.failure_rate and self._rng.random() < self.failure_rate:
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
        if self.latency_ms:
            time.sleep(self.latency_ms / 1000.0)

    def __getattr__(self, name):
        # Everything not explicitly wrapped (register_limiter, flush,
        # checkpoints, attributes like engine/trace) passes straight through.
        return getattr(self._inner, name)

    # -- wrapped surface ------------------------------------------------------
    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)


def _wrap(op: str):
    def method(self, *args, **kwargs):
        self._maybe_fail(op)
        return getattr(self._inner, op)(*args, **kwargs)

    method.__name__ = op
    return method


for _op in _DECISION_OPS + _LEGACY_OPS + ("is_available", "close"):
    setattr(FaultInjectingStorage, _op, _wrap(_op))
# is_available/close are wrapped for delegation but never injected by
# default (they are the health/shutdown path; pass them in ``ops`` to
# chaos-test the health check itself).
#
# The abstract-method set was frozen before the loop above filled the
# contract in; clear it so the wrapper instantiates.
FaultInjectingStorage.__abstractmethods__ = frozenset()


# ---------------------------------------------------------------------------
# Failover drill (replication/ — kill the primary mid-soak, promote)
# ---------------------------------------------------------------------------

def failover_drill(
    num_slots: int = 2048,
    n_keys: int = 64,
    waves: int = 6,
    kill_after_wave: int = 3,
    post_waves: int = 3,
    batch: int = 48,
    seed: int = 0,
    registry=None,
    background_interval_ms: float | None = None,
) -> dict:
    """Deterministic replicated-failover drill, differential vs the oracle.

    Builds a primary and a same-geometry standby ``TpuBatchedStorage``
    under a controlled clock, replicates primary -> standby through the
    full frame pipeline (journal -> log -> encoded wire frames ->
    receiver), and drives mixed sliding-window + token-bucket waves with
    every decision checked against ``semantics/oracle.py``.  After
    ``kill_after_wave`` waves the drill ships a final epoch, runs one
    more LOSS wave that is never replicated, kills the primary
    (``close()``), promotes the standby, and verifies that every
    post-failover decision is bit-identical to an oracle rolled back to
    the promoted epoch — the exact availability contract: state at or
    before the last replicated epoch survives, the loss wave does not.

    ``background_interval_ms`` additionally runs the async replicator
    thread during the soak (the production shape); the drill still cuts
    a deterministic final epoch before the kill so the differential
    stays exact.  Returns a report dict; raises AssertionError on any
    decision mismatch.
    """
    import copy
    import random

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        StandbyReceiver,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    primary = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    cfg_sw = RateLimitConfig(max_permits=20, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=2000,
                             refill_rate=10.0)
    lid_sw = primary.register_limiter("sw", cfg_sw)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    # The standby registers limiters from replicated frames, not here —
    # that path is part of what the drill proves.
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)
    repl = Replicator(log, InProcessSink(receiver), registry=registry,
                      interval_ms=background_interval_ms or 200.0)
    if background_interval_ms:
        repl.start()

    oracle_sw = SlidingWindowOracle(cfg_sw)
    oracle_tb = TokenBucketOracle(cfg_tb)
    report = {"decisions": 0, "mismatches": 0, "lag_ms_samples": [],
              "frames": 0, "loss_wave_decisions": 0}

    def run_wave(storage) -> None:
        clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
        now = clock["t"]
        keys = [f"u{rng.randrange(n_keys)}" for _ in range(batch)]
        perms = [rng.choice([1, 1, 1, 2, 5, 21]) for _ in range(batch)]
        out = storage.acquire_many("sw", [lid_sw] * batch, keys, perms)
        for j in range(batch):
            d = oracle_sw.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["observed"][j]) != d.observed):
                report["mismatches"] += 1
        out = storage.acquire_many("tb", [lid_tb] * batch, keys, perms)
        for j in range(batch):
            d = oracle_tb.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["remaining"][j]) != d.remaining_hint):
                report["mismatches"] += 1

    try:
        for _ in range(max(kill_after_wave, 1)):
            run_wave(primary)
            if not background_interval_ms:
                report["frames"] += repl.ship_now()
                report["lag_ms_samples"].append(log.last_cut_lag_ms)
        if background_interval_ms:
            repl.stop()
        # Final deterministic epoch: everything up to here survives.
        report["frames"] += repl.ship_now()
        report["lag_ms_samples"].append(log.last_cut_lag_ms)
        snap_sw = copy.deepcopy(oracle_sw)
        snap_tb = copy.deepcopy(oracle_tb)
        promoted_epoch = log.epoch

        # Loss wave: mutations after the last replicated epoch die with
        # the primary.  The oracle rolls back to the snapshot below.
        pre = report["decisions"]
        run_wave(primary)
        report["loss_wave_decisions"] = report["decisions"] - pre
    finally:
        repl.stop()
        primary.close()  # the "crash"

    # Roll the oracle back to the promoted epoch: the loss wave's
    # mutations died with the primary, by contract.
    oracle_sw = snap_sw
    oracle_tb = snap_tb
    promoted = receiver.promote()
    assert promoted is standby

    for _ in range(post_waves):
        run_wave(promoted)
    promoted.close()
    report["promoted_epoch"] = promoted_epoch
    report["frames_applied"] = receiver.frames_applied
    if report["mismatches"]:
        raise AssertionError(
            f"failover drill diverged from the oracle: {report}")
    return report


# ---------------------------------------------------------------------------
# Sustained-outage drill (breaker open -> degraded -> resync -> bit-identical)
# ---------------------------------------------------------------------------

def outage_drill(
    num_slots: int = 512,
    n_keys: int = 24,
    healthy_waves: int = 3,
    outage_waves: int = 4,
    post_waves: int = 3,
    batch: int = 24,
    seed: int = 0,
    failure_threshold: int = 4,
    max_retries: int = 2,
    open_ms: float = 5000.0,
    registry=None,
) -> dict:
    """Deterministic sustained-outage drill over the production composition
    ``retry(breaker(chaos(storage)))``, differential vs the oracle.

    Phases, all under a controlled clock:

    1. **Healthy** — mixed sw/tb waves through single ``acquire``; every
       decision checked bit-exact against ``semantics/oracle.py`` (and the
       breaker's healthy path snapshots each key's last counter into the
       degraded limiter's seed cache).
    2. **Outage** — every backend op is forced to fail.  The drill proves
       the breaker opens within ``ceil(threshold / attempts)`` requests
       (each retry attempt counts), then that decisions are served by the
       degraded host limiter — marked ``degraded``, ZERO backend calls
       (the short-circuit claim, checked against the injector's op log),
       and per-key-per-window admission never exceeds ``max_permits``
       (bounded over-admission: fail-*approximate*, not fail-open).
    3. **Recovery** — the fault is healed and the clock advanced past
       ``open_ms``; a half-open probe on a dedicated key closes the
       breaker, which resyncs: every key the degraded limiter mutated is
       reset on the device.  The drill mirrors those resets in the oracle.
    4. **Post-resync** — waves again, bit-identical vs the oracle.

    Returns a report dict; raises AssertionError on any violated claim.
    """
    import math
    import random

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.breaker import (
        CLOSED,
        OPEN,
        CircuitBreakerStorage,
    )
    from ratelimiter_tpu.storage.degraded import DegradedHostLimiter
    from ratelimiter_tpu.storage.errors import RetryPolicy, StorageException
    from ratelimiter_tpu.storage.retry import RetryingStorage
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    inner = TpuBatchedStorage(num_slots=num_slots, clock_ms=lambda: clock["t"])
    chaos = FaultInjectingStorage(inner)
    fallback = DegradedHostLimiter(clock_ms=lambda: clock["t"],
                                   registry=registry)
    breaker = CircuitBreakerStorage(
        chaos, failure_threshold=failure_threshold, open_ms=open_ms,
        half_open_probes=1, clock_ms=lambda: clock["t"], fallback=fallback,
        registry=registry)
    storage = RetryingStorage(breaker, RetryPolicy(
        max_retries=max_retries, retry_delay_ms=0.01))

    cfg_sw = RateLimitConfig(max_permits=12, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=20, window_ms=2000, refill_rate=8.0)
    lid_sw = storage.register_limiter("sw", cfg_sw)
    lid_tb = storage.register_limiter("tb", cfg_tb)
    oracle_sw = SlidingWindowOracle(cfg_sw)
    oracle_tb = TokenBucketOracle(cfg_tb)

    report = {"decisions": 0, "mismatches": 0, "requests_to_open": 0,
              "degraded_decisions": 0, "over_admissions": 0,
              "touched_keys": 0, "shorted_backend_calls": 0}

    def one(algo, lid, oracle, key, permits, check=True):
        now = clock["t"]
        out = storage.acquire(algo, lid, key, permits)
        if not check:
            return out
        d = oracle.try_acquire(key, permits, now)
        report["decisions"] += 1
        hint = out.get("cache_value", out.get("remaining"))
        if (bool(out["allowed"]) != d.allowed
                or int(out["observed"]) != d.observed
                or int(hint) != d.remaining_hint):
            report["mismatches"] += 1
        return out

    def wave(check=True):
        clock["t"] += rng.choice([3, 17, 250, 999, 2000])
        for _ in range(batch):
            key = f"u{rng.randrange(n_keys)}"
            permits = rng.choice([1, 1, 1, 2, 5])
            one("sw", lid_sw, oracle_sw, key, permits, check=check)
            one("tb", lid_tb, oracle_tb, key, permits, check=check)

    try:
        # Phase 1: healthy, bit-identical.
        for _ in range(healthy_waves):
            wave()
        assert report["mismatches"] == 0, (
            f"healthy phase diverged from the oracle: {report}")

        # Phase 2: sustained outage.
        chaos.fail_next(10_000_000)
        budget = math.ceil(failure_threshold / max(max_retries, 1)) + 1
        opened_after = None
        for i in range(budget):
            try:
                storage.acquire("sw", lid_sw, f"u{i % n_keys}", 1)
            except StorageException:
                pass
            if breaker.state == OPEN:
                opened_after = i + 1
                break
        assert opened_after is not None, (
            f"breaker failed to open within {budget} requests of a "
            f"sustained outage (threshold={failure_threshold}, "
            f"attempts/request={max_retries})")
        report["requests_to_open"] = opened_after

        # Degraded service: no exceptions, no backend traffic, admission
        # bounded per key per window by the policy ceiling.
        backend_calls_at_open = len(chaos.calls)
        admitted: dict = {}
        for _ in range(outage_waves):
            clock["t"] += rng.choice([3, 17, 250, 999])
            for _ in range(batch):
                key = f"u{rng.randrange(n_keys)}"
                permits = rng.choice([1, 1, 2, 5])
                out = storage.acquire("sw", lid_sw, key, permits)
                assert out.get("degraded"), (
                    "breaker open but the decision did not come from the "
                    f"degraded host limiter: {out}")
                report["degraded_decisions"] += 1
                if out["allowed"]:
                    # The sw bucket counts REQUESTS (one increment per
                    # acquire regardless of permits — reference quirk
                    # Q1/Q2), so the per-bucket admission ceiling is
                    # max_permits requests.
                    win = clock["t"] // cfg_sw.window_ms
                    admitted[key, win] = admitted.get((key, win), 0) + 1
        report["shorted_backend_calls"] = (
            len(chaos.calls) - backend_calls_at_open)
        assert report["shorted_backend_calls"] == 0, (
            "degraded decisions still reached the backend: "
            f"{report['shorted_backend_calls']} op(s) after open")
        report["over_admissions"] = sum(
            1 for count in admitted.values() if count > cfg_sw.max_permits)
        assert report["over_admissions"] == 0, (
            f"degraded mode over-admitted past the policy ceiling: {admitted}")

        # Phase 3: heal, half-open probe, close + resync.
        chaos.heal()
        clock["t"] += int(open_ms) + 1
        touched = fallback.touched()
        report["touched_keys"] = len(touched)
        assert report["touched_keys"] > 0, "outage phase mutated no keys?"
        probe = storage.acquire("sw", lid_sw, "__probe__", 1)
        assert not probe.get("degraded") and breaker.state == CLOSED, (
            f"half-open probe did not close the breaker: state="
            f"{breaker.state}")
        assert breaker.resyncs_total == 1
        # Mirror the resync in the oracle: reset exactly the touched keys.
        oracle_sw.try_acquire("__probe__", 1, clock["t"])
        for algo, _lid, key in touched:
            (oracle_sw if algo == "sw" else oracle_tb).reset(key, clock["t"])

        # Phase 4: post-resync, bit-identical again.
        for _ in range(post_waves):
            wave()
        assert report["mismatches"] == 0, (
            f"post-resync decisions diverged from the oracle: {report}")
    finally:
        storage.close()
    return report


# ---------------------------------------------------------------------------
# Overload drill (bounded queue depth, shed-not-hang, p99 under load)
# ---------------------------------------------------------------------------

def overload_drill(
    load_multipliers=(1.0, 2.0),
    max_pending: int = 256,
    deadline_ms: float = 1000.0,
    dispatch_ms: float = 5.0,
    max_batch: int = 32,
    bursts: int = 40,
    burst_interval_ms: float = 10.0,
    p99_slack_ms: float = 250.0,
) -> dict:
    """Drive a MicroBatcher over a fixed-rate synthetic device at 1x..Nx
    its capacity and prove the admission-control claims:

    - pending queue depth never exceeds ``max_pending`` (hard bound),
    - overload is SHED (typed ``OverloadedError`` with a positive
      Retry-After hint), never queued forever,
    - p99 latency of *admitted* requests stays within the queue-deadline
      budget plus a dispatch cycle (shedding protects the admitted).

    The synthetic device resolves a batch in ``dispatch_ms`` regardless of
    size, so capacity = ``max_batch / dispatch_ms`` requests/s and the
    offered load is ``multiplier * capacity`` submitted in bursts.  The
    defaults are deliberately coarse (deep queue, 1 s deadline) so that
    scheduler stalls on a loaded CI box do not read as overload; tighten
    them when measuring, not when gating.
    Returns per-multiplier stats; raises AssertionError on any violation.
    """
    import statistics

    from ratelimiter_tpu.engine.batcher import MicroBatcher
    from ratelimiter_tpu.engine.errors import OverloadedError

    capacity_rps = max_batch / (dispatch_ms / 1000.0)
    report = {"capacity_rps": capacity_rps, "runs": []}

    for mult in load_multipliers:
        def dispatch(slots, lids, permits):
            # Cost scales with the number of max_batch-sized device steps:
            # the flusher hands over whatever accumulated, and an elastic
            # single-sleep model would let a deep queue raise capacity.
            n = len(slots)
            time.sleep(-(-n // max_batch) * dispatch_ms / 1000.0)
            return {"allowed": [True] * n}

        batcher = MicroBatcher(
            dispatch={"sw": dispatch}, clear={"sw": lambda slots: None},
            max_batch=max_batch, max_delay_ms=0.0, max_inflight=1,
            max_pending=max_pending, deadline_ms=deadline_ms)
        done_ms: dict = {}  # future -> completion latency (done callback,
        shed = deadline = admitted = 0  # so collection order can't inflate)
        per_burst = max(int(capacity_rps * burst_interval_ms / 1000.0
                            * mult), 1)
        pending: list = []

        def stamp(fut, born):
            fut.add_done_callback(
                lambda f: done_ms.setdefault(
                    f, (time.monotonic() - born) * 1000.0))
            return fut

        try:
            start = time.monotonic()
            for k in range(bursts):
                # Absolute schedule: a late burst fires immediately rather
                # than sliding every later burst (which would quietly lower
                # the offered rate on a loaded box).
                delay = start + k * burst_interval_ms / 1000.0 \
                    - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                born = time.monotonic()
                for i in range(per_burst):
                    try:
                        pending.append(stamp(
                            batcher.submit("sw", i % 32, 0, 1), born))
                    except OverloadedError as exc:
                        assert exc.retry_after_ms > 0, (
                            "shed without a Retry-After hint")
                        shed += 1
            lat_ms = []
            for fut in pending:
                try:
                    fut.result(timeout=10.0)
                    lat_ms.append(done_ms[fut])
                    admitted += 1
                except OverloadedError:
                    deadline += 1
            depth_seen = batcher.max_depth_seen
        finally:
            batcher.close()

        offered = shed + len(pending)
        p99 = (statistics.quantiles(lat_ms, n=100)[98]
               if len(lat_ms) >= 100 else max(lat_ms, default=0.0))
        run = {"multiplier": mult, "offered": offered, "admitted": admitted,
               "shed": shed, "deadline_expired": deadline,
               "goodput_frac": admitted / max(offered, 1),
               "shed_frac": (shed + deadline) / max(offered, 1),
               "max_depth_seen": depth_seen, "p99_ms": p99}
        report["runs"].append(run)

        assert depth_seen <= max_pending, (
            f"queue depth {depth_seen} exceeded the configured bound "
            f"{max_pending} at {mult}x load")
        assert admitted + shed + deadline == offered  # nothing stranded
        budget = deadline_ms + 2 * dispatch_ms + p99_slack_ms
        assert p99 <= budget, (
            f"p99 of admitted requests {p99:.1f} ms blew the "
            f"{budget:.1f} ms budget at {mult}x load")
        if mult >= 2.0:
            assert run["shed_frac"] > 0, (
                f"{mult}x offered load shed nothing — the queue bound "
                "is not engaging")
    return report
