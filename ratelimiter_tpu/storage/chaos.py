"""Fault-injecting storage wrapper (chaos testing).

The reference has no fault injection at all (SURVEY.md §5.3 — its failure
handling is asserted, not exercised). This wrapper makes failure paths
first-class testable: it delegates to any ``RateLimitStorage`` and injects
``StorageException`` (and optional latency) on a configurable schedule, so
retry logic, fail-open policy, and metric accounting can be driven
deterministically in tests and chaos drills.

Determinism: failures come from a seeded RNG; ``fail_next(n)`` forces the
next n operations to fail regardless of probability — the tool for exact
retry-count assertions (the reference's retry wrapper does 3 attempts with
linear backoff; ``service/app.py`` implements the documented fail-open on
exhaustion).
"""

from __future__ import annotations

import collections
import random
import threading
import time

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import StorageException

_DECISION_OPS = ("acquire", "acquire_many", "acquire_many_ids",
                 "acquire_stream_ids", "acquire_stream_strs",
                 "available_many", "reset_key")
_LEGACY_OPS = ("increment_and_expire", "get", "set", "compare_and_set",
               "delete", "z_add", "z_remove_range_by_score", "z_count",
               "eval_script")


class FaultInjectingStorage(RateLimitStorage):
    """Wraps a real backend; injects failures/latency on configured ops."""

    def __init__(
        self,
        inner: RateLimitStorage,
        failure_rate: float = 0.0,
        latency_ms: float = 0.0,
        seed: int = 0,
        ops: tuple = _DECISION_OPS + _LEGACY_OPS,
    ):
        self._inner = inner
        self.failure_rate = float(failure_rate)
        self.latency_ms = float(latency_ms)
        self._rng = random.Random(seed)
        self._ops = set(ops)
        self._lock = threading.Lock()
        self._forced = 0
        self.injected_failures = 0
        # Recent op names only — bounded so long-running drills can't leak.
        self.calls = collections.deque(maxlen=1024)

    # -- control surface ------------------------------------------------------
    def fail_next(self, n: int = 1) -> None:
        """Force the next ``n`` wrapped operations to fail."""
        with self._lock:
            self._forced += int(n)

    def _maybe_fail(self, op: str) -> None:
        if op not in self._ops:
            return
        with self._lock:
            self.calls.append(op)
            if self._forced > 0:
                self._forced -= 1
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
            if self.failure_rate and self._rng.random() < self.failure_rate:
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
        if self.latency_ms:
            time.sleep(self.latency_ms / 1000.0)

    def __getattr__(self, name):
        # Everything not explicitly wrapped (register_limiter, flush,
        # checkpoints, attributes like engine/trace) passes straight through.
        return getattr(self._inner, name)

    # -- wrapped surface ------------------------------------------------------
    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)


def _wrap(op: str):
    def method(self, *args, **kwargs):
        self._maybe_fail(op)
        return getattr(self._inner, op)(*args, **kwargs)

    method.__name__ = op
    return method


for _op in _DECISION_OPS + _LEGACY_OPS + ("is_available", "close"):
    setattr(FaultInjectingStorage, _op, _wrap(_op))
# is_available/close are wrapped for delegation but never injected by
# default (they are the health/shutdown path; pass them in ``ops`` to
# chaos-test the health check itself).
#
# The abstract-method set was frozen before the loop above filled the
# contract in; clear it so the wrapper instantiates.
FaultInjectingStorage.__abstractmethods__ = frozenset()


# ---------------------------------------------------------------------------
# Failover drill (replication/ — kill the primary mid-soak, promote)
# ---------------------------------------------------------------------------

def failover_drill(
    num_slots: int = 2048,
    n_keys: int = 64,
    waves: int = 6,
    kill_after_wave: int = 3,
    post_waves: int = 3,
    batch: int = 48,
    seed: int = 0,
    registry=None,
    background_interval_ms: float | None = None,
) -> dict:
    """Deterministic replicated-failover drill, differential vs the oracle.

    Builds a primary and a same-geometry standby ``TpuBatchedStorage``
    under a controlled clock, replicates primary -> standby through the
    full frame pipeline (journal -> log -> encoded wire frames ->
    receiver), and drives mixed sliding-window + token-bucket waves with
    every decision checked against ``semantics/oracle.py``.  After
    ``kill_after_wave`` waves the drill ships a final epoch, runs one
    more LOSS wave that is never replicated, kills the primary
    (``close()``), promotes the standby, and verifies that every
    post-failover decision is bit-identical to an oracle rolled back to
    the promoted epoch — the exact availability contract: state at or
    before the last replicated epoch survives, the loss wave does not.

    ``background_interval_ms`` additionally runs the async replicator
    thread during the soak (the production shape); the drill still cuts
    a deterministic final epoch before the kill so the differential
    stays exact.  Returns a report dict; raises AssertionError on any
    decision mismatch.
    """
    import copy
    import random

    from ratelimiter_tpu.core.config import RateLimitConfig
    from ratelimiter_tpu.replication import (
        InProcessSink,
        ReplicationLog,
        Replicator,
        StandbyReceiver,
    )
    from ratelimiter_tpu.semantics.oracle import (
        SlidingWindowOracle,
        TokenBucketOracle,
    )
    from ratelimiter_tpu.storage.tpu import TpuBatchedStorage

    rng = random.Random(seed)
    clock = {"t": 1_753_000_000_000}
    primary = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    standby = TpuBatchedStorage(num_slots=num_slots,
                                clock_ms=lambda: clock["t"])
    cfg_sw = RateLimitConfig(max_permits=20, window_ms=2000,
                             enable_local_cache=False)
    cfg_tb = RateLimitConfig(max_permits=30, window_ms=2000,
                             refill_rate=10.0)
    lid_sw = primary.register_limiter("sw", cfg_sw)
    lid_tb = primary.register_limiter("tb", cfg_tb)
    # The standby registers limiters from replicated frames, not here —
    # that path is part of what the drill proves.
    log = ReplicationLog(primary)
    receiver = StandbyReceiver(standby, registry=registry)
    repl = Replicator(log, InProcessSink(receiver), registry=registry,
                      interval_ms=background_interval_ms or 200.0)
    if background_interval_ms:
        repl.start()

    oracle_sw = SlidingWindowOracle(cfg_sw)
    oracle_tb = TokenBucketOracle(cfg_tb)
    report = {"decisions": 0, "mismatches": 0, "lag_ms_samples": [],
              "frames": 0, "loss_wave_decisions": 0}

    def run_wave(storage) -> None:
        clock["t"] += rng.choice([1, 7, 250, 999, 2000, 2001])
        now = clock["t"]
        keys = [f"u{rng.randrange(n_keys)}" for _ in range(batch)]
        perms = [rng.choice([1, 1, 1, 2, 5, 21]) for _ in range(batch)]
        out = storage.acquire_many("sw", [lid_sw] * batch, keys, perms)
        for j in range(batch):
            d = oracle_sw.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["observed"][j]) != d.observed):
                report["mismatches"] += 1
        out = storage.acquire_many("tb", [lid_tb] * batch, keys, perms)
        for j in range(batch):
            d = oracle_tb.try_acquire(keys[j], perms[j], now)
            report["decisions"] += 1
            if (bool(out["allowed"][j]) != d.allowed
                    or int(out["remaining"][j]) != d.remaining_hint):
                report["mismatches"] += 1

    try:
        for _ in range(max(kill_after_wave, 1)):
            run_wave(primary)
            if not background_interval_ms:
                report["frames"] += repl.ship_now()
                report["lag_ms_samples"].append(log.last_cut_lag_ms)
        if background_interval_ms:
            repl.stop()
        # Final deterministic epoch: everything up to here survives.
        report["frames"] += repl.ship_now()
        report["lag_ms_samples"].append(log.last_cut_lag_ms)
        snap_sw = copy.deepcopy(oracle_sw)
        snap_tb = copy.deepcopy(oracle_tb)
        promoted_epoch = log.epoch

        # Loss wave: mutations after the last replicated epoch die with
        # the primary.  The oracle rolls back to the snapshot below.
        pre = report["decisions"]
        run_wave(primary)
        report["loss_wave_decisions"] = report["decisions"] - pre
    finally:
        repl.stop()
        primary.close()  # the "crash"

    # Roll the oracle back to the promoted epoch: the loss wave's
    # mutations died with the primary, by contract.
    oracle_sw = snap_sw
    oracle_tb = snap_tb
    promoted = receiver.promote()
    assert promoted is standby

    for _ in range(post_waves):
        run_wave(promoted)
    promoted.close()
    report["promoted_epoch"] = promoted_epoch
    report["frames_applied"] = receiver.frames_applied
    if report["mismatches"]:
        raise AssertionError(
            f"failover drill diverged from the oracle: {report}")
    return report
