"""Fault-injecting storage wrapper (chaos testing).

The reference has no fault injection at all (SURVEY.md §5.3 — its failure
handling is asserted, not exercised). This wrapper makes failure paths
first-class testable: it delegates to any ``RateLimitStorage`` and injects
``StorageException`` (and optional latency) on a configurable schedule, so
retry logic, fail-open policy, and metric accounting can be driven
deterministically in tests and chaos drills.

Determinism: failures come from a seeded RNG; ``fail_next(n)`` forces the
next n operations to fail regardless of probability — the tool for exact
retry-count assertions (the reference's retry wrapper does 3 attempts with
linear backoff; ``service/app.py`` implements the documented fail-open on
exhaustion).
"""

from __future__ import annotations

import collections
import random
import threading
import time

from ratelimiter_tpu.storage.base import RateLimitStorage
from ratelimiter_tpu.storage.errors import StorageException

_DECISION_OPS = ("acquire", "acquire_many", "acquire_many_ids",
                 "acquire_stream_ids", "acquire_stream_strs",
                 "available_many", "reset_key")
_LEGACY_OPS = ("increment_and_expire", "get", "set", "compare_and_set",
               "delete", "z_add", "z_remove_range_by_score", "z_count",
               "eval_script")


class FaultInjectingStorage(RateLimitStorage):
    """Wraps a real backend; injects failures/latency on configured ops."""

    def __init__(
        self,
        inner: RateLimitStorage,
        failure_rate: float = 0.0,
        latency_ms: float = 0.0,
        seed: int = 0,
        ops: tuple = _DECISION_OPS + _LEGACY_OPS,
    ):
        self._inner = inner
        self.failure_rate = float(failure_rate)
        self.latency_ms = float(latency_ms)
        self._rng = random.Random(seed)
        self._ops = set(ops)
        self._lock = threading.Lock()
        self._forced = 0
        self.injected_failures = 0
        # Recent op names only — bounded so long-running drills can't leak.
        self.calls = collections.deque(maxlen=1024)

    # -- control surface ------------------------------------------------------
    def fail_next(self, n: int = 1) -> None:
        """Force the next ``n`` wrapped operations to fail."""
        with self._lock:
            self._forced += int(n)

    def _maybe_fail(self, op: str) -> None:
        if op not in self._ops:
            return
        with self._lock:
            self.calls.append(op)
            if self._forced > 0:
                self._forced -= 1
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
            if self.failure_rate and self._rng.random() < self.failure_rate:
                self.injected_failures += 1
                raise StorageException(f"injected failure in {op}")
        if self.latency_ms:
            time.sleep(self.latency_ms / 1000.0)

    def __getattr__(self, name):
        # Everything not explicitly wrapped (register_limiter, flush,
        # checkpoints, attributes like engine/trace) passes straight through.
        return getattr(self._inner, name)

    # -- wrapped surface ------------------------------------------------------
    @property
    def supports_device_batching(self):  # type: ignore[override]
        return getattr(self._inner, "supports_device_batching", False)


def _wrap(op: str):
    def method(self, *args, **kwargs):
        self._maybe_fail(op)
        return getattr(self._inner, op)(*args, **kwargs)

    method.__name__ = op
    return method


for _op in _DECISION_OPS + _LEGACY_OPS + ("is_available", "close"):
    setattr(FaultInjectingStorage, _op, _wrap(_op))
# is_available/close are wrapped for delegation but never injected by
# default (they are the health/shutdown path; pass them in ``ops`` to
# chaos-test the health check itself).
#
# The abstract-method set was frozen before the loop above filled the
# contract in; clear it so the wrapper instantiates.
FaultInjectingStorage.__abstractmethods__ = frozenset()
